"""gRPC front door for the router (reference: internal/router/server.go:92
— the reference raises a gRPC server on `rpc_port` next to the HTTP
gateway; its IDL lives in internal/proto). Here the service is defined by
`api/vearch.proto` (an original IDL: JSON payloads for schema-dependent
parts, packed floats for query vectors) and served by grpcio using
explicit method handlers — the image ships protoc but not the gRPC
python plugin, so message classes are protoc-generated while the service
table is registered by hand (grpc.method_handlers_generic_handler).

Generated code is cached next to this module, keyed on the .proto's
sha256 (same no-stale-binary discipline as vearch_tpu/native)."""

from __future__ import annotations

import hashlib
import importlib.util
import json
import os
import subprocess
import threading
from typing import Any

from vearch_tpu.cluster.rpc import RpcError

_PROTO = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "api",
    "vearch.proto",
)
_GEN_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".proto_cache"
)
_lock = threading.Lock()
_pb2_mod = None


def load_pb2():
    """protoc-generate (if stale) and import vearch_pb2."""
    global _pb2_mod
    with _lock:
        if _pb2_mod is not None:
            return _pb2_mod
        with open(_PROTO, "rb") as f:
            src = f.read()
        h = hashlib.sha256(src).hexdigest()
        os.makedirs(_GEN_DIR, exist_ok=True)
        gen = os.path.join(_GEN_DIR, "vearch_pb2.py")
        hfile = gen + ".srchash"
        stale = True
        if os.path.exists(gen) and os.path.exists(hfile):
            with open(hfile) as f:
                stale = f.read().strip() != h
        if stale:
            subprocess.run(
                ["protoc", f"-I{os.path.dirname(_PROTO)}",
                 f"--python_out={_GEN_DIR}", _PROTO],
                check=True, capture_output=True, timeout=60,
            )
            with open(hfile, "w") as f:
                f.write(h)
        spec = importlib.util.spec_from_file_location("vearch_pb2", gen)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _pb2_mod = mod
        return mod


# RpcError HTTP codes -> canonical gRPC status codes
_STATUS = {
    400: "INVALID_ARGUMENT",
    401: "UNAUTHENTICATED",
    403: "PERMISSION_DENIED",
    404: "NOT_FOUND",
    409: "ABORTED",
    503: "UNAVAILABLE",
}


def _loads(s: str, what: str, want: type = dict) -> Any:
    """Parse a JSON payload field and enforce its top-level shape, so a
    malformed client payload maps to INVALID_ARGUMENT — not a TypeError
    escaping as UNKNOWN."""
    if not s:
        return None
    try:
        out = json.loads(s)
    except ValueError:
        raise RpcError(400, f"invalid JSON in {what}") from None
    if not isinstance(out, want):
        names = (want.__name__ if isinstance(want, type)
                 else "/".join(t.__name__ for t in want))
        raise RpcError(
            400, f"{what} must be a JSON {names}, "
                 f"got {type(out).__name__}")
    return out


class GrpcRouter:
    """gRPC server bound to a RouterServer's handler internals: each RPC
    converts proto -> the HTTP handlers' body dicts and back, so both
    front doors share validation, routing, merging, and tracing."""

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 32):
        import grpc
        from concurrent import futures

        self.router = router
        self.pb2 = load_pb2()
        self._grpc = grpc
        self.server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers,
                thread_name_prefix="grpc-router",
            )
        )
        pb2 = self.pb2

        def handler(fn, req_cls, resp_cls, http_path):
            def call(request, context):
                try:
                    # same BasicAuth + per-endpoint privilege enforcement
                    # as the HTTP front door (an auth-enabled cluster
                    # must not be writable through an unauthenticated
                    # side entrance). Credentials ride the standard
                    # `authorization` metadata key.
                    authenticator = self.router.server.authenticator
                    if authenticator is not None:
                        md = {k: v for k, v in
                              (context.invocation_metadata() or ())}
                        headers = {
                            "Authorization": md.get("authorization", "")
                        }
                        authenticator(headers, "POST", http_path)
                    return fn(request)
                except RpcError as e:
                    context.abort(
                        getattr(grpc.StatusCode,
                                _STATUS.get(e.code, "INTERNAL")),
                        e.msg,
                    )
            return grpc.unary_unary_rpc_method_handler(
                call,
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )

        service = grpc.method_handlers_generic_handler(
            "vearch_tpu.Router",
            {
                "Upsert": handler(self._upsert, pb2.UpsertRequest,
                                  pb2.UpsertResponse, "/document/upsert"),
                "Search": handler(self._search, pb2.SearchRequest,
                                  pb2.SearchResponse, "/document/search"),
                "Query": handler(self._query, pb2.QueryRequest,
                                 pb2.QueryResponse, "/document/query"),
                "Delete": handler(self._delete, pb2.DeleteRequest,
                                  pb2.DeleteResponse, "/document/delete"),
            },
        )
        self.server.add_generic_rpc_handlers((service,))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            # grpc reports bind failure by returning port 0, not raising
            raise OSError(f"gRPC bind failed on {host}:{port}")
        self.addr = f"{host}:{self.port}"

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop(grace=1.0)

    # -- RPC implementations -------------------------------------------------

    def _upsert(self, req):
        docs = []
        for d in req.documents:
            fields = _loads(d.fields_json, "fields_json") or {}
            if d.id:
                fields["_id"] = d.id
            docs.append(fields)
        out = self.router._h_upsert(
            {"db_name": req.db_name, "space_name": req.space_name,
             "documents": docs}, None)
        return self.pb2.UpsertResponse(
            total=out["total"], document_ids=out["document_ids"])

    def _search(self, req):
        import numpy as np

        body: dict[str, Any] = {
            "db_name": req.db_name,
            "space_name": req.space_name,
            # np.asarray reads the packed repeated-scalar container
            # directly — no intermediate PyFloat list on the hot path
            "vectors": [
                {"field": v.field,
                 "feature": np.asarray(v.feature, dtype=np.float32),
                 **({"min_score": v.min_score}
                    if v.HasField("min_score") else {}),
                 **({"max_score": v.max_score}
                    if v.HasField("max_score") else {}),
                 **({"boost": v.boost} if v.HasField("boost") else {})}
                for v in req.vectors
            ],
        }
        if req.HasField("limit"):
            body["limit"] = req.limit
        if req.filters_json:
            body["filters"] = _loads(req.filters_json, "filters_json")
        if req.fields:
            body["fields"] = list(req.fields)
        if req.index_params_json:
            body["index_params"] = _loads(
                req.index_params_json, "index_params_json")
        if req.ranker_json:
            body["ranker"] = _loads(req.ranker_json, "ranker_json")
        if req.load_balance:
            body["load_balance"] = req.load_balance
        if req.sort_json:
            body["sort"] = _loads(req.sort_json, "sort_json",
                                  want=(dict, list, str))
        if req.trace:
            body["trace"] = True
        out = self.router._h_search(body, None)
        resp = self.pb2.SearchResponse(trace_id=out.get("trace_id", ""))
        for per_query in out["documents"]:
            result = resp.results.add()
            for item in per_query:
                rest = {k: v for k, v in item.items()
                        if k not in ("_id", "_score")}
                result.items.add(
                    id=str(item.get("_id", "")),
                    score=float(item.get("_score", 0.0)),
                    fields_json=json.dumps(rest) if rest else "",
                )
        return resp

    def _query(self, req):
        body: dict[str, Any] = {
            "db_name": req.db_name, "space_name": req.space_name,
        }
        if req.document_ids:
            body["document_ids"] = list(req.document_ids)
        if req.filters_json:
            body["filters"] = _loads(req.filters_json, "filters_json")
        if req.HasField("limit"):
            body["limit"] = req.limit
        if req.HasField("offset"):
            body["offset"] = req.offset
        if req.fields:
            body["fields"] = list(req.fields)
        if req.vector_value:
            body["vector_value"] = True
        if req.sort_json:
            body["sort"] = _loads(req.sort_json, "sort_json",
                                  want=(dict, list, str))
        out = self.router._h_query(body, None)
        resp = self.pb2.QueryResponse()
        for doc in out["documents"]:
            rest = {k: v for k, v in doc.items() if k != "_id"}
            resp.documents.add(
                id=str(doc.get("_id", "")),
                fields_json=json.dumps(rest) if rest else "",
            )
        return resp

    def _delete(self, req):
        body: dict[str, Any] = {
            "db_name": req.db_name, "space_name": req.space_name,
        }
        if req.document_ids:
            body["document_ids"] = list(req.document_ids)
        if req.filters_json:
            body["filters"] = _loads(req.filters_json, "filters_json")
        if req.HasField("limit"):
            # limit=0 stays a zero delete budget (deletes nothing),
            # matching the documented HTTP semantics — absent = unbounded
            body["limit"] = req.limit
        out = self.router._h_delete(body, None)
        return self.pb2.DeleteResponse(total=out["total"])

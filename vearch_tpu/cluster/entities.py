"""Cluster metadata entities + metastore key schema.

Mirrors the reference's entity layer (reference: internal/entity/space.go:75
`Space`, partition.go:50 `Partition`, server.go `Server`, meta.go etcd key
schema). Spaces embed the engine TableSchema plus partition topology.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

from vearch_tpu.engine.types import TableSchema

# -- metastore key schema (reference: entity/meta.go) ------------------------

PREFIX_DB = "/db/"
PREFIX_SPACE = "/space/"  # /space/{db}/{space}
PREFIX_SERVER = "/server/"  # /server/{node_id}
PREFIX_PARTITION = "/partition/"  # /partition/{id}
SEQ_SPACE_ID = "/seq/space"
SEQ_PARTITION_ID = "/seq/partition"
SEQ_NODE_ID = "/seq/node"


@dataclass
class Partition:
    id: int
    space_id: int
    db_name: str
    space_name: str
    slot: int  # slot range start (reference: entity/partition.go Slot)
    replicas: list[int] = field(default_factory=list)  # node ids
    leader: int = -1  # node id of raft leader
    # raft leadership epoch: bumped by the master on every failover /
    # membership change; fences deposed leaders (raft.py)
    term: int = 1

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Partition":
        return cls(**d)


@dataclass
class Space:
    id: int
    name: str
    db_name: str
    schema: TableSchema
    partition_num: int = 1
    replica_num: int = 1
    partitions: list[Partition] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "name": self.name,
            "db_name": self.db_name,
            "schema": self.schema.to_dict(),
            "partition_num": self.partition_num,
            "replica_num": self.replica_num,
            "partitions": [p.to_dict() for p in self.partitions],
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Space":
        return cls(
            id=d["id"],
            name=d["name"],
            db_name=d["db_name"],
            schema=TableSchema.from_dict(d["schema"]),
            partition_num=d.get("partition_num", 1),
            replica_num=d.get("replica_num", 1),
            partitions=[Partition.from_dict(p) for p in d.get("partitions", [])],
        )

    def slot_starts(self) -> list[int]:
        return [p.slot for p in self.partitions]


@dataclass
class Server:
    node_id: int
    rpc_addr: str  # host:port of the PS data service
    partition_ids: list[int] = field(default_factory=list)
    last_heartbeat: float = field(default_factory=time.time)
    alive: bool = True

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Server":
        return cls(**d)

"""Cluster metadata entities + metastore key schema.

Mirrors the reference's entity layer (reference: internal/entity/space.go:75
`Space`, partition.go:50 `Partition`, server.go `Server`, meta.go etcd key
schema). Spaces embed the engine TableSchema plus partition topology.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

from vearch_tpu.engine.types import TableSchema

# -- metastore key schema (reference: entity/meta.go) ------------------------

PREFIX_DB = "/db/"
PREFIX_SPACE = "/space/"  # /space/{db}/{space}
PREFIX_SERVER = "/server/"  # /server/{node_id}
PREFIX_PARTITION = "/partition/"  # /partition/{id}
SEQ_SPACE_ID = "/seq/space"
SEQ_PARTITION_ID = "/seq/partition"
SEQ_NODE_ID = "/seq/node"


@dataclass
class Partition:
    id: int
    space_id: int
    db_name: str
    space_name: str
    slot: int  # slot range start (reference: entity/partition.go Slot)
    replicas: list[int] = field(default_factory=list)  # node ids
    leader: int = -1  # node id of raft leader
    # raft leadership epoch: bumped by the master on every failover /
    # membership change; fences deposed leaders (raft.py)
    term: int = 1
    # partition-rule group this partition belongs to (the range name;
    # reference: entity/partition.go Partition.Name under PartitionRule)
    group: str | None = None
    # non-voting replication targets (raft learners): they receive
    # appends/snapshots and report lag but never count toward quorum or
    # campaign — the replica-migration catch-up state (reference:
    # etcd-raft learner semantics)
    learners: list[int] = field(default_factory=list)
    # routing-map epoch this partition was minted under; responses echo
    # it so routers detect a split cutover without waiting for the
    # metastore watch
    map_version: int = 0
    # (last_term, last_index) of the leader log chosen at the most
    # recent promotion — the floor a later promotion's candidate must
    # reach, or entries committed under an earlier membership could be
    # discarded (master.py _reconfigure_partition)
    promoted_log: list[int] | None = None

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Partition":
        return cls(**d)


@dataclass
class Space:
    id: int
    name: str
    db_name: str
    schema: TableSchema
    partition_num: int = 1
    replica_num: int = 1
    partitions: list[Partition] = field(default_factory=list)
    # {"type": "RANGE", "field": ..., "ranges": [{"name", "value"}]} —
    # ranges ascending; each range backs partition_num slot-sharded
    # partitions (reference: entity/partition.go:125 PartitionRule)
    partition_rule: dict | None = None
    # replica placement anti-affinity: none|host|rack|zone (reference:
    # config.go:389 strategies 0-3)
    anti_affinity: str = "none"
    # set once partition_num has been expanded online: slots were
    # re-carved, so rows ingested before the expansion may live in a
    # partition that no longer owns their slot — id-routed reads must
    # fan out instead of slot-routing (reference: expandPartitions,
    # space_service.go:792 — same re-carve, same consequence)
    expanded: bool = False
    # partition ids that existed before the latest expansion — the only
    # ones that can hold off-slot rows, so id-routed writes scope their
    # existence probes to these instead of every partition
    pre_expand_pids: list[int] = field(default_factory=list)
    # id->docid cache toggle (reference: entity/space.go:88-94). Kept
    # for wire compat: this engine holds the key->docid map in-process
    # (table.py _key_to_docid — no FFI boundary to cache across), so the
    # cache is structurally always-on; the flag round-trips the API.
    enable_id_cache: bool = True
    # partition-map epoch: bumped by every split cutover; routers
    # compare against response-carried versions to hot-reload the map
    map_version: int = 0
    # declared service objective for this space, e.g.
    # {"latency_ms": 50, "availability": 0.999, "recall_floor": 0.9} —
    # the router scores every logical search against latency/
    # availability and exports error-budget burn rates
    # (docs/ACCOUNTING.md); recall_floor rides the master's register
    # response to every hosting PS, whose shadow recall sampler flags a
    # statistical breach (docs/QUALITY.md). None = unscored
    slo: dict | None = None

    def to_dict(self) -> dict[str, Any]:
        d = {
            "id": self.id,
            "name": self.name,
            "db_name": self.db_name,
            "schema": self.schema.to_dict(),
            "partition_num": self.partition_num,
            "replica_num": self.replica_num,
            "partitions": [p.to_dict() for p in self.partitions],
        }
        if self.partition_rule:
            d["partition_rule"] = self.partition_rule
        if self.anti_affinity != "none":
            d["anti_affinity"] = self.anti_affinity
        if not self.enable_id_cache:
            d["enable_id_cache"] = False
        if self.expanded:
            d["expanded"] = True
        if self.pre_expand_pids:
            d["pre_expand_pids"] = list(self.pre_expand_pids)
        if self.map_version:
            d["map_version"] = self.map_version
        if self.slo:
            d["slo"] = dict(self.slo)
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Space":
        return cls(
            id=d["id"],
            name=d["name"],
            db_name=d["db_name"],
            schema=TableSchema.from_dict(d["schema"]),
            partition_num=d.get("partition_num", 1),
            replica_num=d.get("replica_num", 1),
            partitions=[Partition.from_dict(p) for p in d.get("partitions", [])],
            partition_rule=d.get("partition_rule"),
            anti_affinity=d.get("anti_affinity", "none"),
            enable_id_cache=bool(d.get("enable_id_cache", True)),
            expanded=bool(d.get("expanded", False)),
            pre_expand_pids=[int(x) for x in d.get("pre_expand_pids", [])],
            map_version=int(d.get("map_version", 0)),
            slo=d.get("slo"),
        )

    def slot_starts(self) -> list[int]:
        return [p.slot for p in self.partitions]

    # -- partition-rule routing (reference: space.go:198
    #    PartitionIdsByRangeField — first range whose bound exceeds the
    #    field value wins) -------------------------------------------------

    def rule_groups(self) -> dict[str, list[Partition]]:
        groups: dict[str, list[Partition]] = {}
        for p in self.partitions:
            groups.setdefault(p.group or "", []).append(p)
        for parts in groups.values():
            parts.sort(key=lambda p: p.slot)
        return groups

    def rule_bounds(self) -> tuple[list[int], list[str]]:
        """(ascending ns bounds, range names) — normalize the rule once
        per request, not once per document."""
        ranges = self.partition_rule["ranges"]
        return ([rule_value_ns(r["value"]) for r in ranges],
                [r["name"] for r in ranges])

    def rule_group_for(self, value: Any,
                       bounds: tuple[list[int], list[str]] | None = None
                       ) -> str:
        import bisect

        vals, names = bounds if bounds is not None else self.rule_bounds()
        i = bisect.bisect_right(vals, rule_value_ns(value))
        if i >= len(names):
            raise ValueError(
                f"no partition range covers "
                f"{self.partition_rule['field']}={value!r} "
                f"(ranges are exclusive upper bounds)"
            )
        return names[i]


def rule_value_ns(value: Any) -> int:
    """Normalize a partition-rule value to nanoseconds (reference:
    partition.go ToTimestamp — ints are seconds, strings parse as
    dates). Document DATE fields arrive as epoch millis."""
    if isinstance(value, bool):
        raise ValueError("bool is not a date")
    if isinstance(value, (int, float)):
        v = int(value)
        # heuristically scale: ns > 1e16, ms > 1e11, else seconds
        if v > 10**16:
            return v
        if v > 10**11:
            return v * 1_000_000
        return v * 1_000_000_000
    from datetime import datetime, timezone

    dt = datetime.fromisoformat(str(value))
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return int(dt.timestamp() * 1e9)


@dataclass
class Server:
    node_id: int
    rpc_addr: str  # host:port of the PS data service
    partition_ids: list[int] = field(default_factory=list)
    last_heartbeat: float = field(default_factory=time.time)
    alive: bool = True
    # topology labels for replica anti-affinity (reference:
    # config.go:389 strategies 0-3: none/host/rack/zone)
    labels: dict[str, str] = field(default_factory=dict)
    # load summary riding the PS heartbeat (search queue depth,
    # inflight, latency quantiles): merged into /servers by the master
    # from its in-memory heartbeat state — never persisted, so the
    # metastore is not churned once per heartbeat. Routers score
    # replicas with it for least-loaded read routing.
    load: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Server":
        return cls(**d)

"""Elastic data-plane planning: placement, imbalance scoring, and
split/move plan computation.

Pure functions over cluster metadata (entities.Space/Server) and the
heartbeat-fed per-node partition stats — no RPC, no locks, no store —
so the planner is unit-testable standalone and the master's rebalance
endpoints stay thin (reference: the partition admin + placement layer
under internal/master/, which scores PS load from the monitor gauges).

Load model: a partition's *weight* is its reported engine bytes (what
actually pins a PS's memory); its *heat* is the cumulative search +
write counters riding heartbeats. Moves balance weight; splits target
heat concentrated in one partition of a space (a hot partition must be
subdivided before its halves can spread).
"""

from __future__ import annotations

from typing import Iterable, Mapping

from vearch_tpu.cluster.entities import Server, Space
from vearch_tpu.cluster.hashing import MAX_UINT32

__all__ = [
    "place_replicas",
    "imbalance_score",
    "node_loads",
    "compute_plan",
    "split_ranges",
]


def place_replicas(
    space: Space,
    servers: list[Server],
    node_stats: Mapping[int, Mapping[str, Mapping]] | None = None,
) -> list[int]:
    """Pick `space.replica_num` distinct PS nodes for one partition.

    Strict anti-affinity by node: two replicas of one partition NEVER
    co-locate — co-location means one PS failure eats both copies, so
    when fewer distinct alive servers exist than replica_num this
    raises ValueError instead of silently doubling up (the old
    placement crashed with AttributeError *or* co-located, depending on
    pool order).

    Preference order is least-loaded first: reported engine bytes from
    the heartbeat stats, then fewest hosted partitions, then node_id as
    the deterministic tie-break (same inputs -> same placement, so
    placement decisions are reproducible in tests and postmortems).
    The space's label anti-affinity (host/rack/zone) stays a soft
    preference on top, falling back to label collisions when the
    topology is too small — matching the reference's fallback.
    """
    uniq: dict[int, Server] = {}
    for s in servers:
        uniq.setdefault(s.node_id, s)
    if space.replica_num > len(uniq):
        raise ValueError(
            f"cannot place {space.replica_num} replicas on "
            f"{len(uniq)} distinct alive servers without co-locating"
        )
    loads = node_loads(list(uniq.values()), node_stats or {})
    pool = sorted(
        uniq.values(),
        key=lambda s: (loads.get(s.node_id, 0.0),
                       len(s.partition_ids), s.node_id),
    )
    label = space.anti_affinity
    chosen: list[int] = []
    used_labels: set[str] = set()
    for _ in range(space.replica_num):
        pick = None
        if label != "none":
            pick = next(
                (s for s in pool
                 if s.node_id not in chosen
                 and s.labels.get(label, f"~{s.node_id}")
                 not in used_labels),
                None,
            )
        if pick is None:
            pick = next(s for s in pool if s.node_id not in chosen)
        chosen.append(pick.node_id)
        used_labels.add(pick.labels.get(label, f"~{pick.node_id}"))
    return chosen


def node_loads(
    servers: Iterable[Server],
    node_stats: Mapping[int, Mapping[str, Mapping]],
) -> dict[int, float]:
    """Per-node weight: sum of reported engine bytes over the
    partitions each node actually heartbeated. A node with no stats yet
    (freshly joined) weighs 0.0 — exactly what makes it the preferred
    move/placement target."""
    out: dict[int, float] = {}
    for s in servers:
        stats = node_stats.get(s.node_id, {})
        out[s.node_id] = float(sum(
            float(st.get("size_bytes", 0) or 0)
            for st in stats.values()
        ))
    return out


def imbalance_score(loads: Iterable[float]) -> float:
    """(max - min) / mean over per-node loads; 0.0 for degenerate
    inputs (fewer than two nodes, or an all-empty cluster). 0 means
    perfectly even; 1.0 means the spread equals the average load."""
    vals = [float(v) for v in loads]
    if len(vals) < 2:
        return 0.0
    mean = sum(vals) / len(vals)
    if mean <= 0:
        return 0.0
    return (max(vals) - min(vals)) / mean


def split_ranges(space: Space, pid: int) -> tuple[int, int, int]:
    """(lo, mid, hi) slot bounds for splitting partition `pid` of a
    slot-sharded space: children cover [lo, mid) and [mid, hi).

    Raises ValueError when the split is structurally impossible: rule
    spaces (groups are keyed by range name, not slot), expanded spaces
    (pre-carve rows live off-slot, so slot-range children would lose
    them), or a slot range already too narrow to subdivide."""
    if space.partition_rule:
        raise ValueError("rule spaces grow via /partitions/rule ADD, "
                         "not slot splits")
    if space.expanded:
        raise ValueError(
            "expanded spaces hold off-slot rows (pre-carve data); a "
            "slot-range split would strand them")
    parts = sorted(space.partitions, key=lambda p: p.slot)
    idx = next((i for i, p in enumerate(parts) if p.id == pid), None)
    if idx is None:
        raise ValueError(f"partition {pid} not in space "
                         f"{space.db_name}/{space.name}")
    lo = parts[idx].slot
    hi = parts[idx + 1].slot if idx + 1 < len(parts) else MAX_UINT32 + 1
    mid = (lo + hi) // 2
    if not lo < mid < hi:
        raise ValueError(
            f"slot range [{lo}, {hi}) of partition {pid} is too "
            f"narrow to split")
    return lo, mid, hi


def compute_plan(
    spaces: list[Space],
    servers: list[Server],
    node_stats: Mapping[int, Mapping[str, Mapping]],
    max_moves: int = 4,
    imbalance_threshold: float = 0.25,
    split_hot_share: float = 0.6,
) -> dict:
    """Compute a rebalance plan: replica moves that level per-node
    weight, plus split suggestions for heat concentrated in single
    partitions. Returns a plain dict the operator endpoints serve
    verbatim:

        {"imbalance": float, "node_loads": {node_id: bytes},
         "moves":  [{partition_id, from_node, to_node, reason}],
         "splits": [{partition_id, db_name, space_name, reason}],
         "needs_retrain": [{partition_id, db_name, space_name,
                            reasons}]}

    Moves are greedy hottest-node -> coldest-node: pick the heaviest
    partition on the most loaded node whose move (a) lands on a node
    not already holding a replica and (b) strictly shrinks the
    hot/cold gap. Deterministic: ties break by partition id and
    node id, so the same inputs always yield the same plan (apply-mode
    reruns and tests depend on that).
    """
    servers = sorted({s.node_id: s for s in servers}.values(),
                     key=lambda s: s.node_id)
    loads = node_loads(servers, node_stats)
    plan: dict = {
        "imbalance": round(imbalance_score(loads.values()), 4),
        "node_loads": {str(n): v for n, v in sorted(loads.items())},
        "moves": [],
        "splits": [],
        "needs_retrain": [],
    }

    # index-health retrain hints: the PS quality monitor's drift
    # verdict (recon error off its train-time baseline, IVF cell
    # imbalance, deleted/unindexed fractions) rides the heartbeat's
    # per-partition "quality" block — surface it next to the placement
    # plan so one endpoint answers "what should the autopilot do".
    # Leader report wins; deterministic order by partition id.
    for sp in sorted(spaces, key=lambda s: (s.db_name, s.name)):
        for p in sorted(sp.partitions, key=lambda p: p.id):
            q = None
            for nid in [p.leader] + [r for r in p.replicas
                                     if r != p.leader]:
                st = node_stats.get(nid, {}).get(str(p.id)) or {}
                if st.get("quality") is not None:
                    q = st["quality"]
                    break
            if q and q.get("needs_retrain"):
                plan["needs_retrain"].append({
                    "partition_id": p.id, "db_name": sp.db_name,
                    "space_name": sp.name,
                    "reasons": list(q.get("reasons") or []),
                })

    # partition weight/replicas index (leader report wins; any replica
    # report is better than nothing)
    weight: dict[int, float] = {}
    replicas: dict[int, list[int]] = {}
    for sp in spaces:
        for p in sp.partitions:
            replicas[p.id] = list(p.replicas)
            best = 0.0
            for nid in p.replicas:
                st = node_stats.get(nid, {}).get(str(p.id))
                if st is not None:
                    v = float(st.get("size_bytes", 0) or 0)
                    best = max(best, v)
            weight[p.id] = best

    if len(servers) >= 2:
        moved: set[int] = set()
        sim = dict(loads)
        for _ in range(max_moves):
            if imbalance_score(sim.values()) <= imbalance_threshold:
                break
            hot = max(sim, key=lambda n: (sim[n], n))
            cold = min(sim, key=lambda n: (sim[n], -n))
            gap = sim[hot] - sim[cold]
            if gap <= 0:
                break
            # heaviest movable partition on the hot node whose weight
            # fits inside the gap (otherwise the move just swaps which
            # node is hot); prefer larger weight, tie-break by id
            candidates = sorted(
                (pid for pid, reps in replicas.items()
                 if hot in reps and cold not in reps
                 and pid not in moved and 0 < weight.get(pid, 0.0) < gap),
                key=lambda pid: (-weight[pid], pid),
            )
            if not candidates:
                break
            pid = candidates[0]
            plan["moves"].append({
                "partition_id": pid, "from_node": hot, "to_node": cold,
                "reason": f"level load: node {hot} carries "
                          f"{int(sim[hot])}B vs node {cold} "
                          f"{int(sim[cold])}B",
            })
            moved.add(pid)
            sim[hot] -= weight[pid]
            sim[cold] += weight[pid]

    # split suggestions: one partition of a space absorbing most of the
    # space's traffic is the signal a move cannot fix — its halves must
    # exist before they can spread
    for sp in spaces:
        if len(sp.partitions) < 1:
            continue
        heat: dict[int, float] = {}
        for p in sp.partitions:
            st = node_stats.get(p.leader, {}).get(str(p.id))
            if st is None:
                continue
            heat[p.id] = float(st.get("searches_total", 0) or 0) + \
                float(st.get("writes_total", 0) or 0)
        total = sum(heat.values())
        if total <= 0:
            continue
        pid, hottest = max(sorted(heat.items()), key=lambda kv: kv[1])
        if hottest / total < split_hot_share:
            continue
        if len(sp.partitions) == 1 and hottest == total and total < 2:
            continue  # a single barely-touched partition is not "hot"
        try:
            split_ranges(sp, pid)
        except ValueError:
            continue  # structurally unsplittable; don't suggest it
        plan["splits"].append({
            "partition_id": pid, "db_name": sp.db_name,
            "space_name": sp.name,
            "reason": f"partition {pid} carries "
                      f"{round(100 * hottest / total)}% of the "
                      f"space's traffic",
        })
    return plan

"""Distributed tracing: spans, cross-process propagation, local store.

The reference wires Jaeger/opentracing end-to-end (reference:
cmd/vearch/startup.go:66-85 initJaeger; ps/handler_document.go:123-126
extracts the span context from rpcx metadata; router request-id
middleware, router/server.go:63-80). This container is zero-egress, so
instead of shipping to a collector each process keeps a bounded ring of
finished spans, queryable via `GET /debug/traces` on every role, with
an optional JSONL file export in an OTLP-like shape.

Propagation rides the request envelope (`_trace_ctx` in the RPC body) —
the envelope is this framework's rpcx-metadata equivalent; handlers
never see transport headers.

A span is sampled when the client asked (`trace: true`) or the role's
`trace_sample` probability fires (reference: sampler type/param from the
[tracer] config block).
"""

from __future__ import annotations

import json
import random
import threading
import time
import uuid
from collections import deque
from typing import Any


class Span:
    __slots__ = (
        "tracer", "trace_id", "span_id", "parent_id", "name", "service",
        "start_us", "dur_us", "tags", "status",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, tags: dict | None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.name = name
        self.service = tracer.service
        self.start_us = int(time.time() * 1e6)
        self.dur_us = 0
        self.tags: dict[str, Any] = dict(tags or {})
        self.status = "ok"

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def ctx(self) -> dict:
        """The propagation payload for downstream RPC bodies."""
        return {"trace_id": self.trace_id, "parent": self.span_id}

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.status = f"error: {type(exc).__name__}"
        self.dur_us = int(time.time() * 1e6) - self.start_us
        self.tracer._finish(self)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start_us": self.start_us,
            "duration_us": self.dur_us,
            "tags": self.tags,
            "status": self.status,
        }


class Tracer:
    """Per-process span factory + bounded finished-span store."""

    def __init__(self, service: str, max_spans: int = 2048,
                 sample_rate: float = 0.0, export_path: str | None = None):
        self.service = service
        self.sample_rate = float(sample_rate)
        self.export_path = export_path
        self._spans: deque[dict] = deque(maxlen=max_spans)
        self._lock = threading.Lock()

    def should_sample(self, explicit: bool) -> bool:
        return explicit or (
            self.sample_rate > 0 and random.random() < self.sample_rate
        )

    def span(self, name: str, ctx: dict | None = None,
             tags: dict | None = None) -> Span:
        """Start a span; `ctx` is an incoming `_trace_ctx` payload (or
        None for a root span)."""
        trace_id = (ctx or {}).get("trace_id") or uuid.uuid4().hex
        parent = (ctx or {}).get("parent")
        return Span(self, name, trace_id, parent, tags)

    def _finish(self, span: Span) -> None:
        d = span.to_dict()
        with self._lock:
            self._spans.append(d)
        if self.export_path:
            try:
                with open(self.export_path, "a") as f:
                    f.write(json.dumps(d) + "\n")
            except OSError:
                pass

    def spans(self, trace_id: str | None = None,
              limit: int = 200) -> list[dict]:
        with self._lock:
            items = list(self._spans)
        if trace_id:
            items = [s for s in items if s["trace_id"] == trace_id]
        return items[-limit:]


class NullSpan:
    """No-op stand-in so call sites stay branch-free."""

    trace_id = ""
    span_id = ""

    def set_tag(self, key, value):
        pass

    def ctx(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        pass


NULL_SPAN = NullSpan()

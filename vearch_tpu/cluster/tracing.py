"""Distributed tracing: spans, cross-process propagation, local store,
and an OTLP-HTTP exporter to a real collector.

The reference wires Jaeger/opentracing end-to-end (reference:
cmd/vearch/startup.go:66-85 initJaeger; ps/handler_document.go:123-126
extracts the span context from rpcx metadata; router request-id
middleware, router/server.go:63-80). Each process keeps a bounded ring
of finished spans, queryable via `GET /debug/traces` on every role, an
optional JSONL file export, and — when `[tracer] collector_endpoint` is
set — ships batches as OTLP/HTTP JSON (`POST {endpoint}/v1/traces`),
the wire shape Jaeger >=1.35 and every OTel collector ingest natively
(the modern equivalent of the reference's jaeger-agent UDP path).

Propagation rides the request envelope (`_trace_ctx` in the RPC body) —
the envelope is this framework's rpcx-metadata equivalent; handlers
never see transport headers.

A span is sampled when the client asked (`trace: true`) or the role's
`trace_sample` probability fires (reference: sampler type/param from the
[tracer] config block).
"""

from __future__ import annotations

import json
import random
import threading
import time

from vearch_tpu.utils import mono_us
import uuid
from collections import deque
from typing import Any


class Span:
    __slots__ = (
        "tracer", "trace_id", "span_id", "parent_id", "name", "service",
        "start_us", "dur_us", "tags", "status", "_t0",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, tags: dict | None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.name = name
        self.service = tracer.service
        self._t0 = time.monotonic()
        self.start_us = mono_us(self._t0)
        self.dur_us = 0
        self.tags: dict[str, Any] = dict(tags or {})
        self.status = "ok"

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def ctx(self) -> dict:
        """The propagation payload for downstream RPC bodies."""
        return {"trace_id": self.trace_id, "parent": self.span_id}

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.status = f"error: {type(exc).__name__}"
        self.dur_us = int((time.monotonic() - self._t0) * 1e6)
        self.tracer._finish(self)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start_us": self.start_us,
            "duration_us": self.dur_us,
            "tags": self.tags,
            "status": self.status,
        }


def _otlp_attr(key: str, value: Any) -> dict:
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, int):
        return {"key": key, "value": {"intValue": str(value)}}
    if isinstance(value, float):
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


def span_to_otlp(d: dict) -> dict:
    """Ring-form span dict -> OTLP JSON span object."""
    start_ns = d["start_us"] * 1000
    return {
        "traceId": d["trace_id"],
        "spanId": d["span_id"],
        "parentSpanId": d.get("parent_id") or "",
        "name": d["name"],
        "kind": 2,  # SPAN_KIND_SERVER
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(start_ns + d["duration_us"] * 1000),
        "attributes": [
            _otlp_attr(k, v) for k, v in (d.get("tags") or {}).items()
        ],
        "status": (
            {"code": 1} if d.get("status") == "ok"
            else {"code": 2, "message": str(d.get("status"))}
        ),
    }


class OtlpHttpExporter:
    """Batching OTLP/HTTP JSON shipper (stdlib urllib, background
    thread). Export never blocks the request path: spans are queued and
    flushed every `flush_interval` seconds or `max_batch` spans; a dead
    collector costs a dropped batch and a counter, not latency."""

    def __init__(self, endpoint: str, service: str,
                 flush_interval: float = 2.0, max_batch: int = 512,
                 timeout: float = 5.0):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service = service
        self.flush_interval = float(flush_interval)
        self.max_batch = int(max_batch)
        self.timeout = float(timeout)
        self.dropped = 0
        self.exported = 0
        self._q: deque[dict] = deque(maxlen=8192)
        self._cond = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"otlp-export-{service}",
        )
        self._thread.start()

    def export(self, span_dict: dict) -> None:
        with self._cond:
            if len(self._q) == self._q.maxlen:
                self.dropped += 1  # eviction is loss too, count it
            self._q.append(span_dict)
            if len(self._q) >= self.max_batch:
                self._cond.notify()

    def _loop(self) -> None:
        while True:
            with self._cond:
                self._cond.wait(self.flush_interval)
                batch = list(self._q)
                self._q.clear()
                if self._stop and not batch:
                    return
            if batch:
                self._send(batch)

    def _send(self, batch: list[dict]) -> None:
        import urllib.request

        body = json.dumps({
            "resourceSpans": [{
                "resource": {"attributes": [
                    _otlp_attr("service.name", self.service),
                ]},
                "scopeSpans": [{
                    "scope": {"name": "vearch_tpu"},
                    "spans": [span_to_otlp(d) for d in batch],
                }],
            }],
        }).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
            self.exported += len(batch)
        except Exception:
            self.dropped += len(batch)

    def flush(self) -> None:
        """Synchronous drain for shutdown/tests (bounded by the
        constructor's send timeout)."""
        with self._cond:
            batch = list(self._q)
            self._q.clear()
        if batch:
            self._send(batch)

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        # the loop thread may be mid-_send with spans it already drained
        # from the queue — join it (bounded) or shutdown kills the POST
        self._thread.join(self.timeout + 1.0)
        self.flush()


class Tracer:
    """Per-process span factory + bounded finished-span store."""

    def __init__(self, service: str, max_spans: int = 2048,
                 sample_rate: float = 0.0, export_path: str | None = None,
                 collector_endpoint: str | None = None):
        self.service = service
        self.sample_rate = float(sample_rate)
        self.export_path = export_path
        self.exporter = (
            OtlpHttpExporter(collector_endpoint, service)
            if collector_endpoint else None
        )
        self._spans: deque[dict] = deque(maxlen=max_spans)
        self._lock = threading.Lock()

    def should_sample(self, explicit: bool) -> bool:
        return explicit or (
            self.sample_rate > 0 and random.random() < self.sample_rate
        )

    def span(self, name: str, ctx: dict | None = None,
             tags: dict | None = None) -> Span:
        """Start a span; `ctx` is an incoming `_trace_ctx` payload (or
        None for a root span)."""
        trace_id = (ctx or {}).get("trace_id") or uuid.uuid4().hex
        parent = (ctx or {}).get("parent")
        return Span(self, name, trace_id, parent, tags)

    def record(self, name: str, ctx: dict | None = None,
               start_us: int | None = None, dur_us: int = 0,
               tags: dict | None = None, status: str = "ok") -> Span:
        """Emit an already-measured span retroactively.

        The engine measures its phase windows inline (no tracer in
        scope) and ships them up as `[name, start_us, dur_us]` rows; the
        PS replays them here as child spans with their REAL wall
        windows, so /debug/traces shows coarse-quantize/scan/rerank
        timing nested under ps.search. Also used for rare raft events
        (elections, snapshot installs) that have no request context."""
        trace_id = (ctx or {}).get("trace_id") or uuid.uuid4().hex
        parent = (ctx or {}).get("parent")
        sp = Span(self, name, trace_id, parent, tags)
        if start_us is not None:
            sp.start_us = int(start_us)
        sp.dur_us = max(int(dur_us), 0)
        sp.status = status
        self._finish(sp)
        return sp

    def _finish(self, span: Span) -> None:
        d = span.to_dict()
        with self._lock:
            self._spans.append(d)
        if self.exporter is not None:
            self.exporter.export(d)
        if self.export_path:
            try:
                # lint: allow[serving-blocking] opt-in debug sink (export_path unset in serving configs); sampled spans only
                with open(self.export_path, "a") as f:
                    f.write(json.dumps(d) + "\n")
            except OSError:
                pass

    def spans(self, trace_id: str | None = None,
              limit: int = 200) -> list[dict]:
        with self._lock:
            items = list(self._spans)
        if trace_id:
            items = [s for s in items if s["trace_id"] == trace_id]
        return items[-limit:]


class SlowLog:
    """Per-role bounded ring of slow-request records, served at
    `GET /debug/slowlog` (reference: the PS slow-request marking around
    engine slow_search_time + the router access log's slow entries —
    here a structured ring instead of grep-able text).

    `threshold_ms <= 0` disables slow capture; killed requests are
    force-recorded regardless (a request the operator or a deadline had
    to abort is exactly what the slowlog exists to explain). Entries
    carry the PR-2 phase breakdown when the role has one in hand —
    schema in docs/OBSERVABILITY.md."""

    def __init__(self, maxlen: int = 256, threshold_ms: float = 0.0):
        self.threshold_ms = float(threshold_ms)
        self._entries: deque[dict] = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def should_log(self, elapsed_ms: float, killed: bool = False) -> bool:
        return killed or (
            self.threshold_ms > 0 and elapsed_ms > self.threshold_ms
        )

    def add(self, entry: dict) -> None:
        e = dict(entry)
        e.setdefault("ts", time.time())  # lint: allow[wall-clock] operator-facing slowlog stamp, display-only
        with self._lock:
            self._entries.append(e)

    def entries(self, limit: int = 100) -> list[dict]:
        with self._lock:
            items = list(self._entries)
        return items[-max(int(limit), 0):]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class NullSpan:
    """No-op stand-in so call sites stay branch-free."""

    trace_id = ""
    span_id = ""

    def set_tag(self, key, value):
        pass

    def ctx(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        pass


NULL_SPAN = NullSpan()

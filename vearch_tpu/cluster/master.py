"""Master (control plane): metadata CRUD, placement, failure detection.

TPU-native re-design of the reference's master role (reference:
internal/master/cluster_api.go:244 admin routes;
services/space_service.go:59 CreateSpace — schema validate, cluster lock,
slot carving, placement; master_cache.go lease-expiry failure detection).
Route names mirror the reference so SDKs port over directly.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any

from vearch_tpu.cluster import elastic, rpc
from vearch_tpu.cluster.entities import (
    PREFIX_DB,
    PREFIX_SERVER,
    PREFIX_SPACE,
    SEQ_NODE_ID,
    SEQ_PARTITION_ID,
    SEQ_SPACE_ID,
    Partition,
    Server,
    Space,
)
from vearch_tpu.cluster.hashing import carve_slots
from vearch_tpu.cluster.metastore import MetaStore
from vearch_tpu.cluster.rpc import JsonRpcServer, RpcError
from vearch_tpu.engine.types import DataType, ScalarIndexType, TableSchema
from vearch_tpu.utils import log

_log = log.get("master")


def _deepcopy_job(job: dict) -> dict:
    """Stable snapshot of a backup-job record for serving: the worker
    thread mutates the nested dicts while requests read them."""
    out = dict(job)
    out["partitions"] = {k: dict(v) for k, v in job["partitions"].items()}
    out["results"] = list(job["results"])
    return out


def _deepcopy_ejob(job: dict) -> dict:
    """Stable snapshot of an elastic-job record for serving (same
    reason as _deepcopy_job: the worker mutates nested state while
    requests read it)."""
    out = dict(job)
    out["detail"] = dict(job.get("detail") or {})
    out["steps"] = [dict(s) for s in job.get("steps") or []]
    return out

HEARTBEAT_TTL = 8.0


class MasterServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_path: str | None = None,
        heartbeat_ttl: float = HEARTBEAT_TTL,
        auth: bool = False,
        root_password: str = "secret",
        auto_recover: bool = True,
        recover_delay: float = 5.0,
        node_id: int = 1,
        peers: dict[int, str] | None = None,
        meta_dir: str | None = None,
        election_timeout: float = 1.0,
        meta_log_keep: int = 1000,
        meta_flush_every: int = 500,
        join: str | None = None,
        auto_rebalance: bool = False,
        rebalance_interval: float = 30.0,
    ):
        from vearch_tpu.cluster.auth import AuthService, parse_basic_auth

        self.heartbeat_ttl = heartbeat_ttl
        # meta checkpoint cadence + retained log tail (reference: etcd
        # snapshot-count / compaction knobs); small values in tests
        # force the far-behind-master snapshot path
        self.meta_log_keep = meta_log_keep
        self.meta_flush_every = meta_flush_every
        self.auto_recover = auto_recover
        self.recover_delay = recover_delay
        self.store = MetaStore(persist_path)
        self._stop = threading.Event()
        self._leases: dict[int, int] = {}  # node_id -> lease id
        # serialises every partition reconfiguration (lease-reaper
        # failover, auto-recover loop, /partitions/change_member):
        # two concurrent reconfigs could fence at the same term and
        # appoint two leaders, defeating the fencing safety argument
        self._reconfig_lock = threading.Lock()
        # async backup jobs (reference: backup progress endpoints)
        self._backup_jobs: dict[str, dict] = {}
        self._backup_jobs_lock = threading.Lock()
        # async elastic jobs: online splits, replica migrations, drains,
        # and rebalance applications (each a first-class observable
        # record: GET /cluster/jobs)
        self._elastic_jobs: dict[str, dict] = {}
        self._elastic_jobs_lock = threading.Lock()
        # load-aware auto-rebalance closed loop — default OFF: the
        # planner stays advisory until an operator opts in
        self.auto_rebalance = bool(auto_rebalance)
        self.rebalance_interval = float(rebalance_interval)

        # -- multi-master metadata group (reference: embedded etcd raft,
        # master/server.go:89). peers: {master_node_id: "host:port"}
        # including self; >1 entries = replicated mode with voted
        # elections (cluster/raft.py election mode). Followers proxy
        # non-GET API calls to the current leader and serve reads from
        # their replicated store.
        self.node_id = node_id
        self.peers = dict(peers) if peers else {node_id: ""}
        # `join`: address of any live master of an existing replicated
        # group — this node registers itself via POST /members/add at
        # start() and catches up by log replay or snapshot (reference:
        # etcd member add, cluster_api.go:344-354)
        self.join_addr = join
        self.replicated = len(self.peers) > 1 or join is not None
        self._members_lock = threading.RLock()  # guards the peers map
        # held across a member-change propose: one add/remove at a time
        # (NOT the same lock as _members_lock — the apply path takes
        # that, and apply may run on another thread mid-propose)
        self._member_change_gate = threading.Lock()
        self.meta_node = None
        self._was_leader = not self.replicated
        self.election_timeout = election_timeout
        # a restarted member's peers may have changed since its --peers
        # flag: the replicated membership key is authoritative
        saved = self.store.get("/meta/members")
        if self.replicated and saved:
            self.peers = {int(k): v for k, v in saved.items()}
        if self.replicated:
            assert meta_dir, "multi-master mode needs meta_dir for the WAL"
            # the WAL gets truncated behind checkpoints; without a
            # persisted store snapshot a restart would silently lose
            # everything before the truncation horizon
            assert persist_path, "multi-master mode needs persist_path"
            self.auth_service = AuthService(self.store, root_password,
                                            bootstrap=False)
        else:
            self.auth_service = AuthService(self.store, root_password)
            # a restarted master has persisted /server/ records but
            # empty in-memory leases; grant each a fresh short lease so
            # dead nodes expire through the normal reaper
            self._adopt_server_leases()

        # kept for outbound member RPCs (join below): the target's
        # /members/add is authenticated when auth is on
        self._root_password = root_password

        def authenticator(headers, method, path):
            # per-endpoint privilege enforcement (reference:
            # cluster_api.go:153 role.HasPermissionForResources)
            user, password = parse_basic_auth(headers)
            record = self.auth_service.check(user, password)
            self.auth_service.authorize(record, path, method)

        self._meta_dir = meta_dir
        self.server = JsonRpcServer(
            host,
            port,
            authenticator=authenticator if auth else None,
            # PS registration, internal auth checks, and the metadata
            # raft transport stay open (peer RPCs carry no credentials;
            # reference: /register is in the unauthenticated group and
            # etcd peer traffic is not BasicAuth'd)
            auth_exempt=("/register", "/register_router", "/auth/check",
                         "/", "/master/raft"),
        )
        s = self.server
        s.route("POST", "/auth/check", self._h_auth_check)
        s.route("POST", "/users", self._h_create_user)
        s.route("GET", "/users", self._h_get_user)
        s.route("DELETE", "/users", self._h_delete_user)
        s.route("POST", "/roles", self._h_create_role)
        s.route("GET", "/roles", self._h_get_role)
        s.route("GET", "/", self._h_cluster_info)
        s.route("POST", "/register", self._h_register)
        s.route("POST", "/register_router", self._h_register_router)
        s.route("GET", "/servers", self._h_servers)
        s.route("GET", "/routers", self._h_routers)
        s.route("GET", "/cluster/stats", self._h_cluster_stats)
        s.route("GET", "/cluster/usage", self._h_cluster_usage)
        s.route("GET", "/cluster/health", self._h_cluster_health)
        s.route("GET", "/members", self._h_members)
        s.route("POST", "/members/add", self._h_member_add)
        s.route("POST", "/members/remove", self._h_member_remove)
        s.route("GET", "/schedule/fail_server", self._h_fail_servers)
        s.route("DELETE", "/schedule/fail_server",
                self._h_fail_server_clear)
        s.route("POST", "/schedule/recover_server", self._h_recover_server)
        s.route("GET", "/clean_lock", self._h_clean_lock)
        s.route("PUT", "/users", self._h_update_user)
        s.route("PUT", "/roles", self._h_update_role)
        s.route("GET", "/watch", self._h_watch)
        s.route("POST", "/dbs", self._h_create_db)  # POST /dbs/{db}
        s.route("GET", "/dbs", self._h_get_db)
        s.route("PUT", "/dbs", self._h_update_space)
        s.route("DELETE", "/dbs", self._h_delete_db)
        s.route("GET", "/partitions", self._h_partitions)
        s.route("POST", "/partitions/change_member", self._h_change_member)
        s.route("POST", "/partitions/rule", self._h_partition_rule)
        s.route("POST", "/field_index", self._h_field_index)
        s.route("POST", "/config", self._h_set_config)
        s.route("GET", "/config", self._h_get_config)
        s.route("POST", "/backup/dbs", self._h_backup)
        s.route("GET", "/backup/jobs", self._h_backup_jobs)
        # elastic data plane: online split / migration / drain /
        # rebalance operator verbs + job progress
        s.route("POST", "/partitions/split", self._h_split)
        s.route("POST", "/partitions/migrate", self._h_migrate)
        s.route("POST", "/cluster/rebalance", self._h_rebalance)
        s.route("POST", "/cluster/drain", self._h_drain)
        s.route("GET", "/cluster/plan", self._h_plan)
        s.route("GET", "/cluster/jobs", self._h_elastic_jobs)
        s.route("POST", "/alias", self._h_create_alias)
        # PUT modifies (reference: modifyAlias) — same upsert semantics
        s.route("PUT", "/alias", self._h_create_alias)
        s.route("GET", "/alias", self._h_get_alias)
        s.route("DELETE", "/alias", self._h_delete_alias)

        # -- watch hub (reference: etcd watch streams that the client
        # caches in master_cache.go:414 hang off). Every store mutation
        # bumps a revision and records its key in a small ring; routers
        # long-poll GET /watch?rev=N and invalidate caches the moment
        # metadata changes instead of waiting out a TTL. Watches fire on
        # every master replica in log order, so any master serves them.
        self._watch_rev = 0
        # per-process instance id: revs are process-local counters, so a
        # router failing over between masters (or across a restart) must
        # not compare revs from different epochs by magnitude — it keys a
        # full resync on any epoch change instead
        self._watch_epoch = uuid.uuid4().hex[:12]
        self._watch_ring: list[tuple[int, str]] = []  # (rev, key)
        self._watch_cond = threading.Condition()

        def _on_meta_change(event: str, key: str, _value) -> None:
            if key.startswith("/router/"):
                # ops-only registry: no client caches hang off it, and
                # waking every watcher for each router lease re-grant
                # would reintroduce the churn the guarded heartbeat put
                # avoids
                return
            with self._watch_cond:
                self._watch_rev += 1
                self._watch_ring.append((self._watch_rev, key))
                del self._watch_ring[:-512]
                self._watch_cond.notify_all()

        self.store.watch_prefix("", _on_meta_change)

        # per-node partition stats riding PS heartbeats, in-memory only
        # (a quorum write per 2s heartbeat would be absurd); feeds the
        # cluster gauges below (reference: monitor_service.go:51-73)
        self._node_stats: dict[int, dict[str, dict]] = {}
        # runtime-truth digest riding the same heartbeat: per-node
        # {hbm_drift, drift_bytes, compiles_post_warmup} from the PS
        # device sampler + compile flight recorder
        self._node_obs: dict[int, dict] = {}
        # per-node load summary (search queue depth / inflight /
        # latency quantiles) riding the same heartbeat, merged into
        # /servers so routers can pick the least-loaded replica; also
        # in-memory only — it changes every heartbeat, persisting it
        # would churn the metastore (and fire every watch) at 0.5Hz
        # times the fleet size
        self._node_loads: dict[int, dict] = {}
        # per-tenant usage meters riding the same heartbeat
        # (docs/ACCOUNTING.md): node_id -> {scope_id, spaces, totals,
        # hbm_bytes, _mono}. In-memory like the rest — it changes every
        # heartbeat. The rollup dedups by scope_id: co-located PS nodes
        # share one process accountant and must not double-count.
        self._node_usage: dict[int, dict] = {}
        # previous per-space request counts per scope, for the QPS
        # estimate GET /cluster/usage derives from heartbeat deltas
        self._usage_prev: dict[str, dict] = {}
        # router SLO digests pulled on demand by /cluster/health,
        # memoized a few seconds so health probes stay cheap
        self._router_slo_memo: tuple[float, dict] = (0.0, {})
        self._register_cluster_gauges()

        if self.replicated:
            self._setup_meta_raft()

    def _register_cluster_gauges(self) -> None:
        """Cluster-level /metrics gauges an operator graphs: servers,
        dbs, spaces, partitions, per-space docs/sizes, leaders per node
        (reference: internal/monitor/monitor_service.go:51-77)."""
        m = self.server.metrics

        def count_prefix(prefix: str):
            return lambda: {(): float(len(self.store.prefix(prefix)))}

        m.callback_gauge("vearch_cluster_servers",
                         "registered PS servers", (),
                         count_prefix(PREFIX_SERVER))
        m.callback_gauge("vearch_cluster_fail_servers",
                         "PS servers marked failed", (),
                         count_prefix("/fail_server/"))
        m.callback_gauge("vearch_cluster_dbs", "databases", (),
                         count_prefix(PREFIX_DB))

        # one scrape renders several space-derived gauges; parse the
        # space metadata once per metadata revision instead of once per
        # gauge (any store mutation bumps _watch_rev, so the memo can
        # never serve a stale topology)
        space_memo: dict = {"rev": -1, "spaces": []}

        def _spaces():
            with self._watch_cond:
                rev = self._watch_rev
            if space_memo["rev"] != rev:
                space_memo["spaces"] = [
                    Space.from_dict(d)
                    for d in self.store.prefix(PREFIX_SPACE).values()
                ]
                space_memo["rev"] = rev
            return space_memo["spaces"]

        def spaces_per_db():
            out: dict[tuple, float] = {}
            for s in _spaces():
                out[(s.db_name,)] = out.get((s.db_name,), 0.0) + 1.0
            return out

        m.callback_gauge("vearch_cluster_spaces", "spaces per db",
                         ("db",), spaces_per_db)

        def partitions_per_space():
            return {(s.db_name, s.name): float(len(s.partitions))
                    for s in _spaces()}

        m.callback_gauge("vearch_cluster_partitions",
                         "partitions per space", ("db", "space"),
                         partitions_per_space)

        def leaders_per_node():
            out: dict[tuple, float] = {}
            for s in _spaces():
                for p in s.partitions:
                    if p.leader >= 0:
                        key = (str(p.leader),)
                        out[key] = out.get(key, 0.0) + 1.0
            return out

        m.callback_gauge("vearch_cluster_partition_leaders",
                         "partitions led per PS node", ("node_id",),
                         leaders_per_node)

        def _space_stat(field: str):
            def fn():
                out: dict[tuple, float] = {}
                for s in _spaces():
                    total = 0.0
                    for p in s.partitions:
                        # leader replica's report is authoritative; a
                        # mid-failover gap falls back to the largest
                        # replica report rather than dropping to zero
                        best = None
                        leader = self._node_stats.get(p.leader, {})
                        st = leader.get(str(p.id))
                        if st is not None:
                            best = float(st.get(field, 0))
                        else:
                            for nid in p.replicas:
                                st = self._node_stats.get(nid, {}).get(
                                    str(p.id))
                                if st is not None:
                                    v = float(st.get(field, 0))
                                    best = v if best is None else max(
                                        best, v)
                        total += best or 0.0
                    out[(s.db_name, s.name)] = total
                return out
            return fn

        m.callback_gauge("vearch_space_docs", "docs per space",
                         ("db", "space"), _space_stat("doc_count"))
        m.callback_gauge("vearch_space_size_bytes",
                         "engine bytes per space", ("db", "space"),
                         _space_stat("size_bytes"))

        def imbalance():
            loads = elastic.node_loads(self._alive_servers(),
                                       self._node_stats)
            return {(): elastic.imbalance_score(loads.values())}

        m.callback_gauge("vearch_cluster_imbalance_score",
                         "(max-min)/mean of per-PS engine bytes",
                         (), imbalance)

        def elastic_running():
            with self._elastic_jobs_lock:
                n = sum(1 for j in self._elastic_jobs.values()
                        if j["status"] == "running")
            return {(): float(n)}

        m.callback_gauge("vearch_elastic_jobs_running",
                         "elastic jobs (split/migrate/drain/rebalance) "
                         "in flight", (), elastic_running)

        # outcome counters pre-seed both label values so dashboards see
        # the full series set from the first scrape
        self._m_splits = m.counter(
            "vearch_partition_splits_total",
            "completed partition-split jobs by outcome", ("status",))
        self._m_migrations = m.counter(
            "vearch_replica_migrations_total",
            "completed replica-migration jobs by outcome", ("status",))
        for st in ("done", "error"):
            self._m_splits.inc(st, by=0.0)
            self._m_migrations.inc(st, by=0.0)

    # -- multi-master plumbing ----------------------------------------------

    def _setup_meta_raft(self) -> None:
        import os as _os

        from vearch_tpu.cluster.raft import RaftNode

        store = self.store

        def apply(op):
            # applied-index rides in the same persisted json as the kv
            # state, so recovery replays exactly the unapplied tail
            # (next_id is not idempotent — double-replay would skew ids)
            store.applied_index = self.meta_node.applied + 1
            if (op.get("t") or op.get("type")) == "member_change":
                return self._apply_member_change(op)
            return store.apply_op(op)

        def send(peer: int, path: str, body: dict) -> dict:
            # short timeout: a campaign sends votes sequentially — a
            # slow peer must not stall the candidate past every other
            # node's election timer
            return rpc.call(self.peers[peer], "POST", path, body,
                            timeout=3.0)

        def snapshot():
            node = self.meta_node
            with node._apply_lock:
                store.applied_index = node.applied
                return store.snapshot_bytes(), node.applied

        self.meta_node = RaftNode(
            pid=0, node_id=self.node_id,
            wal_dir=_os.path.join(self._meta_dir, "meta_raft"),
            apply_fn=apply, send_fn=send,
            members=sorted(self.peers),
            is_leader=False,
            snapshot_fn=snapshot,
            install_fn=lambda data, idx: self._install_meta_snapshot(data),
            quorum_timeout=5.0,
            election_timeout=self.election_timeout,
            route_prefix="/master/raft",
        )
        self.meta_node.applied = store.applied_index
        self.meta_node.recover_singleton_commit()
        self.meta_node._apply_to_commit()
        store.proposer = lambda op: self.meta_node.propose([op])[0]

        s = self.server
        s.route("POST", "/master/raft/append",
                lambda b, p: self.meta_node.handle_append(b))
        s.route("POST", "/master/raft/vote",
                lambda b, p: self.meta_node.handle_vote(b))
        s.route("POST", "/master/raft/snapshot",
                lambda b, p: self.meta_node.handle_install_snapshot(b))
        s.route("GET", "/master/raft/state",
                lambda b, p: self.meta_node.state())
        self.server.middleware = self._leader_proxy

    def _leader_proxy(self, method, path, body, headers):
        """Follower middleware: metadata raft RPCs and reads serve
        locally (replicated store; etcd-style serializable reads);
        everything else forwards to the current leader."""
        if not self.replicated or self.is_leader:
            return None
        if path.startswith("/master/raft") or method == "GET":
            return None
        if headers.get("X-Vearch-Forwarded"):
            raise RpcError(503, "no metadata leader (forward loop)")
        hint = self.meta_node.leader_hint
        if hint is None or hint == self.node_id or hint not in self.peers:
            raise RpcError(503, "no metadata leader known yet")
        fwd = {"X-Vearch-Forwarded": "1"}
        # the client's credentials must travel with the request or the
        # leader's authenticator rejects every proxied mutation
        if headers.get("Authorization"):
            fwd["Authorization"] = headers["Authorization"]
        return rpc.call(self.peers[hint], method, path, body,
                        extra_headers=fwd)

    @property
    def is_leader(self) -> bool:
        return self.meta_node.is_leader if self.replicated else True

    def _adopt_server_leases(self) -> None:
        for key, val in self.store.prefix(PREFIX_SERVER).items():
            nid = int(key[len(PREFIX_SERVER):])
            old = self._leases.get(nid)
            if old is not None:
                # a stale lease from a previous leadership would expire
                # later and delete the key the fresh lease now owns
                self.store.revoke_lease(old)
            lease = self.store.grant_lease(self.heartbeat_ttl)
            self._leases[nid] = lease
            self.store.put(key, val, lease=lease)
        # router registry entries age out the same way: without a fresh
        # lease on the NEW leader, a router that died across the
        # promotion would be listed forever
        leases = getattr(self, "_router_leases", None)
        if leases is None:
            leases = self._router_leases = {}
        for key, val in self.store.prefix("/router/").items():
            addr = key[len("/router/"):]
            old = leases.get(addr)
            if old is not None:
                self.store.revoke_lease(old)
            lease = self.store.grant_lease(60.0)
            leases[addr] = lease
            self.store.put(key, val, lease=lease)

    def _election_loop(self) -> None:
        keep = self.meta_log_keep  # log tail kept behind meta snapshots
        last_flush = 0
        while not self._stop.is_set():
            time.sleep(max(0.05, self.election_timeout / 4))
            try:
                self.meta_node.election_tick()
                if self.meta_node.is_leader:
                    # leader heartbeat: resets follower election timers
                    # and pushes the commit index
                    self.meta_node.tick()
                leader_now = self.meta_node.is_leader
                if leader_now and not self._was_leader:
                    # promotion work proposes log entries (quorum waits)
                    # — run it off-thread so heartbeats keep flowing, and
                    # retry while leadership holds
                    threading.Thread(target=self._on_promoted,
                                     daemon=True,
                                     name="master-promote").start()
                self._was_leader = leader_now
                # periodic meta checkpoint + log truncation
                node = self.meta_node
                if node.applied - last_flush >= self.meta_flush_every:
                    with node._apply_lock:
                        self.store.applied_index = node.applied
                        self.store._persist()
                        last_flush = node.applied
                    node.wal.save_meta(fsync=True)
                    node.wal.truncate_prefix(
                        max(node.wal.first_index, node.applied - keep + 1)
                    )
            except Exception as e:
                _log.error("master %s: election tick failed: %s: %s",
                           self.node_id, type(e).__name__, e)

    def _on_promoted(self) -> None:
        """Leadership acquisition: bootstrap auth records and re-lease
        persisted servers. Retries while we stay leader — each op is a
        quorum write that can transiently fail during churn."""
        for _ in range(40):
            if self._stop.is_set() or not self.is_leader:
                return
            try:
                self.auth_service.ensure_bootstrap()
                self._adopt_server_leases()
                return
            except (RpcError, ValueError) as e:
                # ValueError: wal closed by a concurrent stop()
                _log.warning("master %s: promotion work retrying: %s",
                             self.node_id, str(e)[:60])
                time.sleep(0.3)

    def _h_watch(self, body, _parts) -> dict:
        """Long-poll watch (reference: etcd Watch streams): blocks until
        the metadata revision passes the caller's `rev` or `timeout`
        elapses; returns the new revision plus the changed keys since
        `rev` (empty on timeout, `reset` when the caller is older than
        the 512-event ring — resync by full cache invalidation)."""
        body = body or {}
        rev = int(body.get("rev", 0))
        timeout = min(float(body.get("timeout", 25.0)), 55.0)
        with self._watch_cond:
            if rev > self._watch_rev:
                # the caller is AHEAD of this process (master restarted
                # or failed over — revs are per-process): make it resync
                # now, not after a full idle poll window during which
                # invalidations would be silently lost
                return {"rev": self._watch_rev, "epoch": self._watch_epoch,
                        "reset": True, "keys": []}
        deadline = time.monotonic() + timeout
        with self._watch_cond:
            while self._watch_rev <= rev and not self._stop.is_set():
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                self._watch_cond.wait(min(remain, 1.0))
            cur = self._watch_rev
            ring = list(self._watch_ring)
        if cur <= rev:
            return {"rev": cur, "epoch": self._watch_epoch, "keys": []}
        oldest = ring[0][0] if ring else cur + 1
        if rev + 1 < oldest:
            # the caller missed events beyond the ring: tell it to drop
            # everything rather than serve a partial delta as complete
            return {"rev": cur, "epoch": self._watch_epoch,
                    "reset": True, "keys": []}
        return {
            "rev": cur,
            "epoch": self._watch_epoch,
            "keys": sorted({k for r, k in ring if r > rev}),
        }

    def start(self) -> None:
        self.server.start()
        threading.Thread(target=self._lease_reaper, daemon=True,
                         name="master-lease-reaper").start()
        if self.auto_recover:
            threading.Thread(target=self._auto_recover_loop,
                             daemon=True, name="master-auto-recover").start()
        if self.auto_rebalance:
            threading.Thread(target=self._auto_rebalance_loop,
                             daemon=True, name="master-rebalance").start()
        if self.join_addr and len(self.peers) <= 1:
            # register with the existing group (any member forwards the
            # POST to the leader); the response carries the full member
            # map, and the leader starts replicating to us — catch-up
            # is ordinary log replay or a snapshot install
            from vearch_tpu.cluster.auth import ROOT_NAME

            # /members/add is NOT auth-exempt: joining an auth-enabled
            # group without credentials dies with an unhandled 401
            out = rpc.call(self.join_addr, "POST", "/members/add",
                           {"node_id": self.node_id, "addr": self.addr},
                           timeout=30.0,
                           auth=(ROOT_NAME, self._root_password))
            with self._members_lock:
                self.peers = {int(k): v for k, v in out["members"].items()}
                with self.meta_node._lock:
                    self.meta_node.members = sorted(self.peers)
        if self.replicated:
            threading.Thread(target=self._election_loop,
                             daemon=True, name="master-election").start()

    def stop(self) -> None:
        self._stop.set()
        if self.meta_node is not None:
            self.meta_node.close()
        self.server.stop()

    @property
    def addr(self) -> str:
        return self.server.addr

    # -- failure detection (reference: master_cache.go:963-1005) -------------

    def _lease_reaper(self) -> None:
        tick = min(1.0, self.heartbeat_ttl / 4)
        while not self._stop.is_set():
            time.sleep(tick)
            if not self.is_leader:
                continue  # leases are leader state
            try:
                for key in self.store.expire_leases():
                    if key.startswith(PREFIX_SERVER):
                        # durable FailServer record (reference:
                        # master_cache.go:963-1005) + immediate failover
                        node_id = int(key[len(PREFIX_SERVER):])
                        self.store.put(f"/fail_server/{node_id}", {
                            "node_id": node_id, "time": time.time(),  # lint: allow[wall-clock] durable failure stamp read across master restarts
                        })
                        # drop its last heartbeat stats: serving a dead
                        # node's doc/size report as current (via the
                        # replica-fallback in the space gauges) would
                        # show stale numbers for the process lifetime
                        self._node_stats.pop(node_id, None)
                        self._node_obs.pop(node_id, None)
                        self._node_loads.pop(node_id, None)
                        self._failover_node(node_id)
            except Exception as e:
                # store mutations propose through the meta log and can
                # transiently 421/503 during leadership churn — the
                # failure-detection thread must survive that
                _log.error("master %s: lease reap failed: %s: %s",
                           self.node_id, type(e).__name__, e)

    def _failover_node(self, dead_node: int) -> None:
        """Reconfigure every partition hosted on the dead node: fence all
        reachable replicas with a bumped term, promote the one with the
        max (last_term, last_index) log, and remove the dead node from
        the membership so quorum is computable again (reference:
        raft election + ChangeMember, services/server_service.go:95).

        Safety: promotion requires that the alive replicas intersect
        every possible commit quorum of the old membership — i.e. at
        least n - quorum(n) + 1 of n replicas reachable. The max-log
        replica among such a set holds every entry committed UNDER THE
        CURRENT membership. Entries committed under an earlier
        membership are only guaranteed in the log of the leader chosen
        at the previous reconfiguration, so each promotion also records
        that leader's (last_term, last_index) as `promoted_log` and a
        later promotion refuses any candidate behind it (see
        _reconfigure_partition). Below either threshold the partition
        stays unavailable (leaderless) rather than silently dropping
        acked data."""
        servers = {s.node_id: s for s in self._alive_servers()}
        with self._reconfig_lock:
            for key, sp in self.store.prefix(PREFIX_SPACE).items():
                changed = False
                for p in sp["partitions"]:
                    if dead_node not in p["replicas"]:
                        continue
                    if self._reconfigure_partition(p, servers,
                                                   drop=dead_node):
                        changed = True
                if changed:
                    self.store.put(key, sp)

    def _reconfigure_partition(self, p: dict, servers: dict,
                               drop: int | None = None) -> bool:
        """Fence alive replicas, pick the best leader, decree the new
        membership. Mutates the partition dict in place; returns whether
        anything changed."""
        replicas = list(p["replicas"])
        n = len(replicas)
        quorum = n // 2 + 1
        new_term = int(p.get("term", 1)) + 1
        states = {}
        for r in replicas:
            srv = servers.get(r)
            if srv is None or (drop is not None and r == drop):
                continue
            try:
                states[r] = rpc.call(srv.rpc_addr, "POST", "/ps/raft/fence",
                                     {"pid": p["id"], "term": new_term})
            except RpcError:
                continue
        # commit-quorum intersection bound (see _failover_node docstring)
        if len(states) < n - quorum + 1 or not states:
            return False
        best = max(
            states,
            key=lambda r: (states[r]["last_term"], states[r]["last_index"]),
        )
        best_log = (int(states[best]["last_term"]),
                    int(states[best]["last_index"]))
        # chained-reconfiguration floor: the intersection bound above
        # only covers entries committed under the CURRENT membership.
        # Entries committed under an earlier membership can live solely
        # in the log of the leader promoted at the previous reconfigure
        # until its peers catch up — fencing a set that excludes that
        # leader while a survivor still lags would promote a stale log
        # and discard acked writes. Refuse until some candidate reaches
        # the recorded watermark; WALs are durable, so the floor becomes
        # satisfiable again when the log-holder returns.
        floor = p.get("promoted_log")
        if floor is not None and best_log < (int(floor[0]), int(floor[1])):
            return False
        members = sorted(states)
        p["leader"] = best
        p["term"] = new_term
        p["replicas"] = members
        p["promoted_log"] = list(best_log)
        try:
            rpc.call(servers[best].rpc_addr, "POST", "/ps/raft/lead",
                     {"pid": p["id"], "term": new_term, "members": members})
        except RpcError:
            return False
        for r in members:
            if r == best:
                continue
            try:
                rpc.call(servers[r].rpc_addr, "POST", "/ps/raft/members",
                         {"pid": p["id"], "term": new_term,
                          "members": members, "leader": best})
            except RpcError:
                pass
        return True

    # -- auto-recover: re-place lost replicas (reference: AutoRecoverPs
    #    loop, client/master_cache.go:1154; ChangeMember to a healthy PS
    #    after replica_auto_recover_time) -----------------------------------

    def _auto_recover_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(1.0)
            if not self.is_leader:
                continue
            try:
                with self._reconfig_lock:
                    self._auto_recover_once()
            except Exception as e:
                _log.error("auto-recover pass failed: %s: %s",
                           type(e).__name__, e)

    def _auto_recover_once(self) -> None:
        servers = {s.node_id: s for s in self._alive_servers()}
        if not servers:
            return
        # replica re-placement only counts after the failure has aged
        # past recover_delay (a restarting node should rejoin, not be
        # rebuilt); leaderless reconciliation below runs regardless
        fails = self.store.prefix("/fail_server/")
        may_replace = not any(
            time.time() - v["time"] < self.recover_delay  # lint: allow[wall-clock] compares against the durable fail stamp, same clock
            for v in fails.values()
        )
        for key, sp in self.store.prefix(PREFIX_SPACE).items():
            replica_num = int(sp.get("replica_num", 1))
            changed = False
            for p in sp["partitions"]:
                # leaderless reconciliation: lease expiry fires failover
                # once; if promotion was unsafe then (too few alive
                # replicas to cover the commit quorum), retry here as
                # nodes return
                if p["leader"] not in servers and any(
                    r in servers for r in p["replicas"]
                ):
                    if self._reconfigure_partition(p, servers,
                                                   drop=p["leader"]):
                        changed = True
            for p in sp["partitions"]:
                if len(p["replicas"]) >= replica_num:
                    continue
                if p["leader"] not in servers:
                    continue  # no live leader to copy from
                candidates = [
                    s for nid, s in servers.items()
                    if nid not in p["replicas"]
                ]
                if not candidates:
                    continue
                # a RETURNING replica (the partition is already on its
                # disk) rejoins immediately — recover_delay exists to
                # avoid rebuilding data onto fresh nodes mid-restart,
                # not to keep a restarted member out of its own group
                returning = [s for s in candidates
                             if p["id"] in s.partition_ids]
                if returning:
                    target = returning[0]
                elif may_replace:
                    # least-loaded placement (reference: anti-affinity
                    # by node; fewest partitions wins)
                    target = min(candidates,
                                 key=lambda s: len(s.partition_ids))
                else:
                    continue
                if self._add_replica(sp, p, target, servers):
                    changed = True
            if changed:
                self.store.put(key, sp)

    def _add_replica(self, sp: dict, p: dict, target, servers) -> bool:
        """Create the partition on `target` as a follower and decree the
        widened membership; the leader's next tick catches it up by log
        replay or snapshot (reference: recover via raft snapshot)."""
        new_term = int(p.get("term", 1)) + 1
        members = sorted(set(p["replicas"]) | {target.node_id})
        part = dict(p)
        part["replicas"] = members
        part["term"] = new_term
        try:
            rpc.call(target.rpc_addr, "POST", "/ps/partition/create", {
                "partition": part,
                "schema": sp["schema"],
            })
        except RpcError as e:
            if e.code != 409:  # already hosted: continue with membership
                return False
        p["replicas"] = members
        p["term"] = new_term
        ok = True
        for r in members:
            srv = servers.get(r)
            if srv is None:
                continue
            path = "/ps/raft/lead" if r == p["leader"] else "/ps/raft/members"
            try:
                rpc.call(srv.rpc_addr, "POST", path,
                         {"pid": p["id"], "term": new_term,
                          "members": members, "leader": p["leader"]})
            except RpcError:
                ok = ok and r != p["leader"]
        if p["id"] not in target.partition_ids:
            target.partition_ids.append(p["id"])
            self.store.put(f"{PREFIX_SERVER}{target.node_id}",
                           target.to_dict())
        return ok

    def _h_change_member(self, body: dict, _parts) -> dict:
        """Manual membership admin (reference: /partitions/change_member,
        cluster_api.go:309-319; method 0=add, 1=remove)."""
        pid = int(body["partition_id"])
        node_id = int(body["node_id"])
        method = body.get("method", "add")
        servers = {s.node_id: s for s in self._alive_servers()}
        with self._reconfig_lock:
            return self._change_member_locked(pid, node_id, method, servers)

    def _change_member_locked(self, pid, node_id, method, servers) -> dict:
        for key, sp in self.store.prefix(PREFIX_SPACE).items():
            for p in sp["partitions"]:
                if p["id"] != pid:
                    continue
                if method in ("add", 0):
                    srv = servers.get(node_id)
                    if srv is None:
                        raise RpcError(404, f"node {node_id} not alive")
                    if not self._add_replica(sp, p, srv, servers):
                        raise RpcError(503, "add_member failed")
                else:
                    if node_id not in p["replicas"]:
                        raise RpcError(404,
                                       f"node {node_id} not a replica")
                    if not self._reconfigure_partition(p, servers,
                                                       drop=node_id):
                        raise RpcError(503, "remove_member failed")
                    srv = servers.get(node_id)
                    if srv is not None:
                        try:
                            rpc.call(srv.rpc_addr, "POST",
                                     "/ps/partition/delete",
                                     {"partition_id": pid})
                        except RpcError:
                            pass
                self.store.put(key, sp)
                return {"partition": p}
        raise RpcError(404, f"partition {pid} not found")

    # -- users / roles (reference: cluster_api.go user/role admin) -----------

    def _h_auth_check(self, body: dict, _parts) -> dict:
        return self.auth_service.check(body["name"], body["password"])

    def _h_create_user(self, body: dict, _parts) -> dict:
        return self.auth_service.create_user(
            body["name"], body["password"], body.get("role", "read")
        )

    def _h_get_user(self, _body, parts) -> dict:
        if parts:
            u = self.store.get(f"/user/{parts[0]}")
            if u is None:
                raise RpcError(404, f"user {parts[0]} not found")
            return {"name": u["name"], "role": u["role"]}
        return {"users": [
            {"name": u["name"], "role": u["role"]}
            for u in self.store.prefix("/user/").values()
        ]}

    def _h_delete_user(self, _body, parts) -> dict:
        if not parts:
            raise RpcError(404, "DELETE /users/{name}")
        self.auth_service.delete_user(parts[0])
        return {"name": parts[0]}

    def _h_create_role(self, body: dict, _parts) -> dict:
        return self.auth_service.create_role(
            body["name"], body.get("privileges", {})
        )

    def _h_get_role(self, _body, parts) -> dict:
        if parts:
            r = self.store.get(f"/role/{parts[0]}")
            if r is None:
                raise RpcError(404, f"role {parts[0]} not found")
            return r
        return {"roles": list(self.store.prefix("/role/").values())}

    def _h_update_user(self, body: dict, _parts) -> dict:
        """PUT /users — change a user's password and/or role
        (reference: cluster_api.go updateUser)."""
        return self.auth_service.update_user(
            body["name"], password=body.get("password"),
            role=body.get("role"),
        )

    def _h_update_role(self, body: dict, _parts) -> dict:
        """PUT /roles — replace a role's privilege map (reference:
        cluster_api.go changeRolePrivilege)."""
        return self.auth_service.update_role(
            body["name"], body.get("privileges", {})
        )

    # -- router registry (reference: register_router + GET /routers —
    #    lease-backed like PS registration, so ops can see the router
    #    fleet and dead routers age out) --------------------------------------

    def _h_register_router(self, body: dict, _parts) -> dict:
        addr = str(body["addr"])
        key = f"/router/{addr}"
        leases = getattr(self, "_router_leases", None)
        if leases is None:
            leases = self._router_leases = {}
        lease = leases.get(addr)
        ttl = 60.0  # routers re-register per watch-poll (<=20s cadence)
        if lease is None or not self.store.keepalive(lease, ttl):
            lease = self.store.grant_lease(ttl)
            leases[addr] = lease
            self.store.put(key, {"addr": addr, "register_time": time.time()},  # lint: allow[wall-clock] operator-facing registration stamp
                           lease=lease)
        return {"addr": addr}

    def _h_routers(self, _body, _parts) -> dict:
        return {"routers": list(self.store.prefix("/router/").values())}

    # -- cluster ops views (reference: /cluster/stats, /cluster/health,
    #    /members, /schedule/*, /clean_lock) ---------------------------------

    def _leader_get(self, path: str):
        """Forward a GET to the current meta leader when heartbeat-fed
        in-memory state is needed (heartbeats land on the leader only;
        followers would serve empty views). Returns None when THIS node
        leads (caller serves locally)."""
        if not self.replicated or self.is_leader:
            return None
        hint = self.meta_node.leader_hint
        if hint is None or hint == self.node_id or hint not in self.peers:
            raise RpcError(503, "no metadata leader known yet")
        # the caller's credentials must ride along (as _leader_proxy
        # does) or the leader's authenticator 401s the forwarded GET
        auth_hdr = rpc.current_auth_header()
        extra = {"Authorization": auth_hdr} if auth_hdr else None
        return rpc.call(self.peers[hint], "GET", path, extra_headers=extra)

    def _h_cluster_stats(self, _body, _parts) -> dict:
        """Per-node partition stats as last heartbeated (reference:
        cluster_api.go stats)."""
        fwd = self._leader_get("/cluster/stats")
        if fwd is not None:
            return fwd
        servers = {s.node_id: s for s in self._alive_servers()}
        return {"stats": [
            {"node_id": nid, "rpc_addr": srv.rpc_addr,
             "partitions": dict(self._node_stats.get(nid, {}))}
            for nid, srv in sorted(servers.items())
        ]}

    def _h_cluster_usage(self, _body, _parts) -> dict:
        """Cluster-wide per-tenant usage rollup (docs/ACCOUNTING.md):
        the heartbeat-fed per-node accountant snapshots summed by
        space, deduplicated by accountant scope (co-located PS nodes in
        one process share one accountant — billing each scope once
        keeps the rollup conservation-exact), plus a QPS estimate from
        consecutive heartbeat deltas and the top consumers by
        device time."""
        from vearch_tpu.obs import accounting

        fwd = self._leader_get("/cluster/usage")
        if fwd is not None:
            return fwd
        servers = {s.node_id: s for s in self._alive_servers()}
        spaces: dict[str, dict] = {}
        totals = {m: 0 for m in accounting.METERS}
        hbm: dict[str, int] = {}
        qps: dict[str, float] = {}
        seen_scopes: list[str] = []
        for nid in sorted(servers):
            u = self._node_usage.get(nid)
            if not u:
                continue
            # HBM residency is per-NODE (each PS models its own hosted
            # engines), so it sums across every reporter even when the
            # meter scope is shared
            for sp, n in (u.get("hbm_bytes") or {}).items():
                hbm[sp] = hbm.get(sp, 0) + int(n)
            scope = str(u.get("scope_id") or f"node-{nid}")
            if scope in seen_scopes:
                continue
            seen_scopes.append(scope)
            for sp, meters in (u.get("spaces") or {}).items():
                acc = spaces.setdefault(
                    sp, {m: 0 for m in accounting.METERS})
                for mname, v in meters.items():
                    if mname in acc:
                        acc[mname] += int(v)
            for mname, v in (u.get("totals") or {}).items():
                if mname in totals:
                    totals[mname] += int(v)
            # QPS from consecutive heartbeat deltas of the requests
            # meter, per scope (monotonic stamps; a re-sent snapshot
            # contributes zero, never a negative rate)
            mono = float(u.get("_mono") or 0.0)
            prev = self._usage_prev.get(scope)
            if prev is not None and mono > prev["mono"]:
                dt = mono - prev["mono"]
                for sp, meters in (u.get("spaces") or {}).items():
                    d = int(meters.get("requests", 0)) - int(
                        prev["req"].get(sp, 0))
                    if d > 0:
                        qps[sp] = qps.get(sp, 0.0) + d / dt
            if prev is None or mono > prev["mono"]:
                self._usage_prev[scope] = {
                    "mono": mono,
                    "req": {sp: int(m.get("requests", 0))
                            for sp, m in (u.get("spaces") or {}).items()},
                }
        ranked = sorted(spaces.items(),
                        key=lambda kv: kv[1]["device_us"], reverse=True)
        return {
            "spaces": {
                sp: {**m,
                     "device_ms": round(m["device_us"] / 1e3, 3),
                     "qps": round(qps.get(sp, 0.0), 2),
                     "hbm_bytes": hbm.get(sp, 0)}
                for sp, m in spaces.items()
            },
            "totals": {**totals,
                       "device_ms": round(totals["device_us"] / 1e3, 3)},
            "top_consumers": [
                {"space": sp,
                 "device_ms": round(m["device_us"] / 1e3, 3),
                 "dispatches": m["dispatches"],
                 "h2d_bytes": m["h2d_bytes"],
                 "requests": m["requests"],
                 "qps": round(qps.get(sp, 0.0), 2)}
                for sp, m in ranked[:10]
            ],
            "scopes": seen_scopes,
        }

    def _router_slo_digest(self) -> dict[str, dict]:
        """Merged per-space SLO burn state pulled from every registered
        router's /router/stats, memoized a few seconds so health probes
        stay cheap. Per space, the WORST burn across routers wins (each
        router only sees its own share of the traffic). Unreachable
        routers are skipped — health degradation must not depend on
        every router answering."""
        now = time.monotonic()
        ts, memo = self._router_slo_memo
        if now - ts < 5.0:
            return memo
        merged: dict[str, dict] = {}
        for rec in list(self.store.prefix("/router/").values()):
            addr = rec.get("addr")
            if not addr:
                continue
            try:
                stats = rpc.call(addr, "GET", "/router/stats",
                                 timeout=2.0)
            except RpcError:
                continue
            for space, s in (stats.get("slo") or {}).items():
                cur = merged.setdefault(space, {
                    "burn_fast": 0.0, "burn_slow": 0.0,
                    "fast_burn": False, "samples": 0,
                    "objective": s.get("objective"),
                })
                cur["burn_fast"] = max(cur["burn_fast"],
                                       float(s.get("burn_fast") or 0.0))
                cur["burn_slow"] = max(cur["burn_slow"],
                                       float(s.get("burn_slow") or 0.0))
                cur["fast_burn"] = bool(cur["fast_burn"]
                                        or s.get("fast_burn"))
                cur["samples"] += int(s.get("samples") or 0)
        self._router_slo_memo = (now, merged)
        return merged

    def _h_cluster_health(self, _body, _parts) -> dict:
        """Per-space health roll-up (reference: cluster_api.go health):
        green = every partition leader-alive and fully replicated,
        yellow = serving but under-replicated, red = leaderless.

        Also rolls up index-build job state from the heartbeat-fed
        partition stats: partitions with a build in flight (or whose
        last build failed) are annotated, and cluster-level
        builds_running / builds_failed counts surface stuck or broken
        background jobs without scraping every PS."""
        fwd = self._leader_get("/cluster/health")
        if fwd is not None:
            return fwd
        servers = {s.node_id for s in self._alive_servers()}
        # partition id -> build status, as last heartbeated by any node
        # hosting it (leader wins when both report)
        builds: dict[int, str] = {}
        splits: dict[int, str] = {}
        for nid, parts_stats in list(self._node_stats.items()):
            for pid_s, st in dict(parts_stats).items():
                bs = st.get("build_status")
                if bs and (st.get("leader") or int(pid_s) not in builds):
                    builds[int(pid_s)] = bs
                ss = st.get("split_status")
                if ss and (st.get("leader") or int(pid_s) not in splits):
                    splits[int(pid_s)] = ss
        builds_running = builds_failed = 0
        # elastic rollup: PS-side split jobs ride heartbeats; master-side
        # job records (splits, migrations, drains, rebalances) live here
        splits_running = sum(1 for v in splits.values() if v == "running")
        splits_failed = sum(1 for v in splits.values() if v == "error")
        with self._elastic_jobs_lock:
            el_running = sum(1 for j in self._elastic_jobs.values()
                             if j["status"] == "running")
            el_failed = sum(1 for j in self._elastic_jobs.values()
                            if j["status"] == "error")
            migrations_running = sum(
                1 for j in self._elastic_jobs.values()
                if j["status"] == "running"
                and j["op"] in ("migrate", "drain", "rebalance"))
        spaces = []
        worst = "green"
        rank = {"green": 0, "yellow": 1, "red": 2}
        for sp in self.store.prefix(PREFIX_SPACE).values():
            status = "green"
            parts = []
            for p in sp.get("partitions", []):
                alive = [r for r in p["replicas"] if r in servers]
                if p["leader"] not in servers:
                    pstat = "red"
                elif len(alive) < int(sp.get("replica_num", 1)):
                    pstat = "yellow"
                else:
                    pstat = "green"
                entry = {"id": p["id"], "status": pstat,
                         "alive_replicas": len(alive)}
                ss = splits.get(int(p["id"]))
                if ss:
                    entry["split"] = ss
                bs = builds.get(int(p["id"]))
                if bs:
                    entry["build"] = bs
                    if bs == "running":
                        builds_running += 1
                    elif bs == "error":
                        builds_failed += 1
                parts.append(entry)
                if rank[pstat] > rank[status]:
                    status = pstat
            spaces.append({"db_name": sp["db_name"], "name": sp["name"],
                           "status": status, "partitions": parts})
            if rank[status] > rank[worst]:
                worst = status
        status = worst if spaces else "green"
        # runtime-truth degradation: a node whose measured HBM has
        # drifted off the footprint model is still serving, but its
        # capacity math (rebalance placement, admission) is built on a
        # model that is now provably wrong — that is a yellow cluster
        # even when every partition is fully replicated
        drift_nodes = sorted(
            nid for nid, obs in list(self._node_obs.items())
            if obs.get("hbm_drift")
        )
        if drift_nodes and rank[status] < rank["yellow"]:
            status = "yellow"
        # SLO degradation: a space burning its declared error budget at
        # page rate (router-scored fast window) is a tenant-visible
        # incident even while every partition is green-replicated
        slo = self._router_slo_digest()
        slo_burn_spaces = sorted(
            sp for sp, rec in slo.items() if rec.get("fast_burn"))
        if slo_burn_spaces and rank[status] < rank["yellow"]:
            status = "yellow"
        # quality degradation: a space whose shadow-sampled recall sits
        # statistically under its declared floor is serving wrong
        # answers with green replication — that is a tenant-visible
        # incident exactly like an SLO burn (docs/QUALITY.md)
        recall_breach_spaces = sorted({
            s for obs in list(self._node_obs.values())
            for s in (obs.get("recall_breach_spaces") or [])
        })
        if recall_breach_spaces and rank[status] < rank["yellow"]:
            status = "yellow"
        needs_retrain = sorted({
            int(p) for obs in list(self._node_obs.values())
            for p in (obs.get("needs_retrain_pids") or [])
        })
        return {"status": status, "spaces": spaces,
                "recall_breach_spaces": recall_breach_spaces,
                "needs_retrain_partitions": needs_retrain,
                "slo_fast_burn_spaces": slo_burn_spaces,
                "hbm_drift_nodes": drift_nodes,
                "serving_compiles": sum(
                    int(obs.get("compiles_post_warmup") or 0)
                    for obs in list(self._node_obs.values())
                ),
                "builds_running": builds_running,
                "builds_failed": builds_failed,
                "splits_running": splits_running,
                "splits_failed": splits_failed,
                "migrations_running": migrations_running,
                "elastic_jobs_running": el_running,
                "elastic_jobs_failed": el_failed}

    def _h_members(self, _body, _parts) -> dict:
        """Metadata-raft membership (reference: GET /members +
        memberAdd/memberDelete, cluster_api.go:344-354). Dynamic:
        POST /members/add and /members/remove change it at runtime."""
        if self.replicated:
            leader_id = (self.node_id if self.is_leader
                         else self.meta_node.leader_hint)
        else:
            leader_id = self.node_id
        return {"members": [
            {"node_id": nid, "addr": addr, "leader": nid == leader_id}
            for nid, addr in sorted(self.peers.items())
        ]}

    # -- dynamic metadata-raft membership ------------------------------------
    #
    # Design choice (documented per r4 review next-6): SINGLE-SERVER
    # configuration changes through the replicated log (raft §4.2.2) —
    # one add/remove at a time, gated by _members_lock held across the
    # propose, applied at commit on every member. One-at-a-time keeps
    # old and new quorums overlapping without joint consensus; the
    # change entry itself commits under the OLD membership. A joiner
    # starts empty and catches up by log replay or snapshot install
    # (the snapshot carries /meta/members, reloaded on install).

    def _apply_member_change(self, op: dict):
        action = op["action"]
        nid = int(op["node_id"])
        # the op carries the FULL resulting member map, computed by the
        # proposing leader (which has the complete picture). Deriving it
        # from local self.peers here would be wrong on a joiner applying
        # its own add mid-catch-up (its peers map is just itself), and
        # that incomplete map would persist as authoritative — on
        # restart a quorum-of-1 split brain (review r5).
        new_peers = {int(k): str(v) for k, v in op["members"].items()}
        node = self.meta_node
        with self._members_lock:
            self.peers = new_peers
            with node._lock:
                node.members = sorted(new_peers)
                node._match = {p: v for p, v in node._match.items()
                               if p in node.members}
                if node.is_leader:
                    for p in node.members:
                        if p != self.node_id:
                            node._next.setdefault(
                                p, node.wal.last_index + 1)
                if action == "remove" and nid == self.node_id:
                    # removed self: stop leading/campaigning; the node
                    # stays up for reads until the operator retires it
                    node.is_leader = False
            # deterministic store write so restarts and snapshots carry
            # the membership (apply runs on every replica)
            self.store._do_put(
                "/meta/members",
                {str(i): a for i, a in sorted(new_peers.items())})
        return {"members": {str(i): a for i, a in sorted(new_peers.items())}}

    def _install_meta_snapshot(self, data: bytes) -> None:
        self.store.install_snapshot(data)
        saved = self.store.get("/meta/members")
        if saved:
            with self._members_lock:
                self.peers = {int(k): v for k, v in saved.items()}
                if self.meta_node is not None:
                    with self.meta_node._lock:
                        self.meta_node.members = sorted(self.peers)

    def _h_member_add(self, body: dict, _parts) -> dict:
        if not self.replicated:
            raise RpcError(400, "single-master mode has no member group")
        nid = int(body["node_id"])
        addr = str(body["addr"])
        with self._member_change_gate:
            with self._members_lock:
                cur = self.peers.get(nid)
                if cur is not None and cur != addr:
                    raise RpcError(
                        409, f"member {nid} exists at {cur!r}; remove it "
                             f"before re-adding at a new address")
                already = cur == addr
            if not already:
                with self._members_lock:
                    resulting = {str(i): a
                                 for i, a in sorted(self.peers.items())}
                    resulting[str(nid)] = addr
                self.meta_node.propose([{
                    "type": "member_change", "action": "add",
                    "node_id": nid, "addr": addr, "members": resulting,
                }])
        return {"members": {str(i): a
                            for i, a in sorted(self.peers.items())},
                "leader": self.node_id}

    def _h_member_remove(self, body: dict, _parts) -> dict:
        if not self.replicated:
            raise RpcError(400, "single-master mode has no member group")
        nid = int(body["node_id"])
        with self._member_change_gate:
            with self._members_lock:
                if nid not in self.peers:
                    raise RpcError(404, f"no member {nid}")
                if len(self.peers) <= 1:
                    raise RpcError(400, "cannot remove the last member")
            with self._members_lock:
                resulting = {str(i): a
                             for i, a in sorted(self.peers.items())
                             if i != nid}
            self.meta_node.propose([{
                "type": "member_change", "action": "remove",
                "node_id": nid, "members": resulting,
            }])
        return {"members": {str(i): a
                            for i, a in sorted(self.peers.items())}}

    def _h_fail_servers(self, _body, _parts) -> dict:
        return {"fail_servers": [
            {"node_id": int(k.rsplit("/", 1)[1]), **v}
            for k, v in sorted(self.store.prefix("/fail_server/").items())
        ]}

    def _h_fail_server_clear(self, _body, parts) -> dict:
        if not parts:
            raise RpcError(404, "DELETE /schedule/fail_server/{node_id}")
        node_id = int(parts[0])
        if not self.store.delete(f"/fail_server/{node_id}"):
            raise RpcError(404, f"no fail record for node {node_id}")
        return {"node_id": node_id}

    def _h_recover_server(self, body: dict, _parts) -> dict:
        """Kick replica re-placement NOW for a failed node instead of
        waiting out recover_delay (reference: RecoverFailServer)."""
        node_id = int(body["node_id"])
        key = f"/fail_server/{node_id}"
        rec = self.store.get(key)
        if rec is None:
            raise RpcError(404, f"no fail record for node {node_id}")
        # age the record past the delay gate, then run one recover pass.
        # NOTE: the pass's may_replace gate still holds re-placement
        # while ANY OTHER failure is younger than recover_delay — report
        # that honestly instead of claiming recovery started
        self.store.put(key, {**rec, "time": 0.0})
        others_fresh = any(
            int(k.rsplit("/", 1)[1]) != node_id
            and time.time() - v["time"] < self.recover_delay  # lint: allow[wall-clock] compares against the durable fail stamp, same clock
            for k, v in self.store.prefix("/fail_server/").items()
        )
        with self._reconfig_lock:
            self._auto_recover_once()
        return {"node_id": node_id,
                "recover_started": not others_fresh,
                **({"blocked_by_fresh_failures": True}
                   if others_fresh else {})}

    def _h_clean_lock(self, _body, _parts) -> dict:
        """List + clear expired space-mutation locks (reference:
        GET /clean_lock — ops escape hatch for locks orphaned by a
        crashed mutation; live locks are left alone)."""
        cleaned, held = self.store.clean_expired_locks()
        return {"cleaned": cleaned, "held": held}

    # -- servers -------------------------------------------------------------

    def _h_register(self, body: dict, _parts) -> dict:
        node_id = body.get("node_id")
        if node_id is None:
            node_id = self.store.next_id(SEQ_NODE_ID)
        node_id = int(node_id)
        key = f"{PREFIX_SERVER}{node_id}"
        existing = self.store.get(key)
        server = Server(
            node_id=node_id,
            rpc_addr=body["rpc_addr"],
            partition_ids=(existing or {}).get("partition_ids", []),
            labels=body.get("labels") or {},
        )
        lease = self._leases.get(node_id)
        refreshed = (
            lease is not None
            and self.store.keepalive(lease, self.heartbeat_ttl)
        )
        if not refreshed:
            lease = self.store.grant_lease(self.heartbeat_ttl)
            self._leases[node_id] = lease
        record = server.to_dict()
        if not refreshed or existing != record:
            # only write when something changed (or a fresh lease needs
            # binding): an unconditional put would fire a /server/ watch
            # event per 2s heartbeat, making every router clear its
            # server cache continuously and long-polls never idle
            self.store.put(key, record, lease=lease)
        if self.store.get(f"/fail_server/{node_id}") is not None:
            # guarded: an unconditional delete would cost a quorum
            # proposal on every heartbeat in replicated mode
            self.store.delete(f"/fail_server/{node_id}")
        if "partitions" in body:
            self._node_stats[node_id] = body["partitions"] or {}
        if "obs" in body:
            self._node_obs[node_id] = body["obs"] or {}
        if "load" in body:
            self._node_loads[node_id] = body["load"] or {}
        if "usage" in body:
            usage = dict(body["usage"] or {})
            usage["_mono"] = time.monotonic()
            self._node_usage[node_id] = usage
        # field-index + schema expectations for the partitions this node
        # hosts: heals replicas that missed a /field_index or
        # /ps/schema/field fan-out (transient RPC failure, or a restart
        # that reloaded a stale local schema)
        expect, schemas = self._field_index_expectations()
        hosted = {str(pid) for pid in server.partition_ids}
        # per-space recall floors (Space.slo.recall_floor) for the
        # spaces this node hosts — the PS quality monitor applies them
        # replace-not-merge, so removing a floor clears it node-side
        floors: dict[str, float] = {}
        for sp in self.store.prefix(PREFIX_SPACE).values():
            rf = (sp.get("slo") or {}).get("recall_floor")
            if rf is None:
                continue
            if any(str(p["id"]) in hosted
                   for p in sp.get("partitions", [])):
                floors[f"{sp['db_name']}/{sp['name']}"] = float(rf)
        return {"node_id": node_id,
                "field_indexes": {
                    pid: flags for pid, flags in expect.items()
                    if pid in hosted
                },
                "schema_fields": {
                    pid: flds for pid, flds in schemas.items()
                    if pid in hosted
                },
                "recall_floors": floors}

    def _h_servers(self, _body, _parts) -> dict:
        # merge the live heartbeat load into each record at read time:
        # the stored record stays heartbeat-stable (watch-quiet) while
        # routers still see queue depth / latency fresh to within one
        # heartbeat interval
        servers = []
        for d in self.store.prefix(PREFIX_SERVER).values():
            load = self._node_loads.get(int(d.get("node_id", -1)))
            servers.append({**d, "load": load} if load else dict(d))
        return {"servers": servers}

    def _alive_servers(self) -> list[Server]:
        return [
            Server.from_dict(d)
            for d in self.store.prefix(PREFIX_SERVER).values()
        ]

    # -- dbs / spaces --------------------------------------------------------

    def _h_create_db(self, body: dict, parts) -> dict:
        if len(parts) == 1:
            # POST /dbs/{db} — create db
            db = parts[0]
            if self.store.get(f"{PREFIX_DB}{db}") is not None:
                raise RpcError(409, f"db {db} exists")
            self.store.put(f"{PREFIX_DB}{db}", {"name": db, "create_time": time.time()})  # lint: allow[wall-clock] operator-facing creation stamp
            return {"name": db}
        if len(parts) == 2 and parts[1] == "spaces":
            return self._create_space(parts[0], body)
        raise RpcError(404, f"bad path {parts}")

    def _h_get_db(self, _body, parts) -> Any:
        if not parts:
            return {"dbs": list(self.store.prefix(PREFIX_DB).values())}
        db = parts[0]
        if len(parts) == 1:
            d = self.store.get(f"{PREFIX_DB}{db}")
            if d is None:
                raise RpcError(404, f"db {db} not found")
            return d
        if len(parts) == 2 and parts[1] == "spaces":
            return {"spaces": list(self.store.prefix(f"{PREFIX_SPACE}{db}/").values())}
        if len(parts) == 3 and parts[1] == "spaces":
            sp = self.store.get(f"{PREFIX_SPACE}{db}/{parts[2]}")
            if sp is None:
                raise RpcError(404, f"space {db}/{parts[2]} not found")
            detail = str(
                ((_body or {}).get("_query") or {}).get("detail", "")
            ).lower() in ("true", "1")
            if detail:
                # per-partition doc/size/status from heartbeat-borne
                # stats (reference: describe_space ?detail=true returns
                # partition doc/index counts). Heartbeats land on the
                # leader; followers forward rather than serve zeros.
                fwd = self._leader_get(
                    f"/dbs/{db}/spaces/{parts[2]}?detail=true")
                if fwd is not None:
                    return fwd
                sp = dict(sp)
                parts_out = []
                for p in sp.get("partitions", []):
                    st = {}
                    # list(): heartbeat threads mutate the dict under us
                    for node_stats in list(self._node_stats.values()):
                        got = node_stats.get(str(p["id"]))
                        if got and (not st or got.get("leader")):
                            st = got
                    parts_out.append({**p,
                                      "doc_count": st.get("doc_count", 0),
                                      "size_bytes": st.get("size_bytes", 0),
                                      "status": st.get("status")})
                sp["partitions"] = parts_out
            return sp
        raise RpcError(404, f"bad path {parts}")

    def _lock_space(self, db: str, name: str) -> str:
        """Per-space mutation lock. The lock NAME is the space (so two
        spaces mutate concurrently) and the owner a per-request token —
        try_lock re-grants to the SAME owner, so using the space as the
        owner (the old scheme) let two mutations of one space both
        acquire (reviewer-found lost-update race). Raises 409 when the
        space is already being mutated."""
        token = uuid.uuid4().hex
        if not self.store.try_lock(f"space_mutate/{db}/{name}", token):
            raise RpcError(409, "space mutation in progress")
        return token

    def _unlock_space(self, db: str, name: str, token: str) -> None:
        self.store.unlock(f"space_mutate/{db}/{name}", token)

    def _h_update_space(self, body: dict, parts) -> dict:
        """PUT /dbs/{db}/spaces/{space} — online space update (reference:
        space_service.go:520 UpdateSpace): partition_num expansion and
        new-scalar-field addition; immutable properties rejected."""
        if len(parts) != 3 or parts[1] != "spaces":
            raise RpcError(404, "PUT /dbs/{db}/spaces/{space}")
        db, _, name = parts[0], parts[1], parts[2]
        key = f"{PREFIX_SPACE}{db}/{name}"
        token = self._lock_space(db, name)
        try:
            sp = self.store.get(key)
            if sp is None:
                raise RpcError(404, f"space {db}/{name} not found")
            space = Space.from_dict(sp)
            if body.get("replica_num") and \
                    int(body["replica_num"]) != space.replica_num:
                raise RpcError(400, "replica_num can not change")
            new_fields = []
            if body.get("fields"):
                new_fields = self._merge_new_fields(space, body["fields"])
            pn = int(body.get("partition_num", 0))
            if pn:
                if space.partition_rule:
                    raise RpcError(
                        400, "rule spaces grow via /partitions/rule ADD")
                if pn < space.partition_num:
                    raise RpcError(
                        400,
                        f"partition_num {pn} should be greater than "
                        f"current {space.partition_num}",
                    )
                if pn > space.partition_num:
                    # pn == current is a no-op, like echoing back an
                    # unchanged replica_num: read-modify-write clients
                    # resubmit the whole space config
                    self._expand_partitions(space, pn)
            if "slo" in body:
                # declared objective is online-mutable: routers pick
                # the change up on their next metadata fetch (one
                # cache TTL) and rescore from there
                space.slo = self._validate_slo(body.get("slo"))
            self.store.put(key, space.to_dict())
        finally:
            self._unlock_space(db, name, token)
        # fan the new fields out to live engines (a replica that misses
        # this converges via the schema expectations riding heartbeats)
        acked, failed = [], []
        if new_fields:
            servers = {s.node_id: s for s in self._alive_servers()}
            for part in space.partitions:
                for node_id in part.replicas:
                    srv = servers.get(node_id)
                    try:
                        if srv is None:
                            raise RpcError(503, "down")
                        rpc.call(srv.rpc_addr, "POST", "/ps/schema/field",
                                 {"partition_id": part.id,
                                  "fields": new_fields})
                        acked.append([part.id, node_id])
                    except RpcError:
                        failed.append([part.id, node_id])
        out = space.to_dict()
        if new_fields:
            out["fields_acked"] = acked
            out["fields_failed"] = failed
        return out

    def _merge_new_fields(self, space: Space, fields: list[dict]) -> list:
        """Append-only schema evolution: brand-new scalar fields are
        added; existing fields may not change (index changes go through
        /field_index). Returns the new fields' dicts (reference:
        updateSpaceFields, space_service.go:801 — only additions and
        index-option changes allowed)."""
        from vearch_tpu.engine.types import FieldSchema

        existing = {f.name: f for f in space.schema.fields}
        added = []
        for d in fields:
            f = FieldSchema.from_dict(d)
            cur = existing.get(f.name)
            if cur is not None:
                if cur.to_dict() != f.to_dict():
                    raise RpcError(
                        400,
                        f"field {f.name!r} exists; only new fields can "
                        f"be added (index changes: POST /field_index)",
                    )
                continue
            if f.data_type is DataType.VECTOR:
                raise RpcError(
                    400, "vector fields cannot be added to a live space")
            space.schema.fields.append(f)
            added.append(f.to_dict())
        return added

    def _expand_partitions(self, space: Space, pn: int) -> None:
        """Grow a slot-sharded space to pn partitions: slots re-carve
        evenly over the new count (existing partitions keep their id,
        replicas, and data) and the new partitions are placed/created
        (reference: expandPartitions, space_service.go:785-798)."""
        servers = self._alive_servers()
        if len(servers) < max(space.replica_num, 1):
            raise RpcError(
                503,
                f"need {space.replica_num} alive servers, "
                f"have {len(servers)}",
            )
        old = space.partition_num
        space.partition_num = pn
        slots = carve_slots(pn)
        # every partition that exists BEFORE this carve may hold rows
        # that land off-slot under the new carve; record them so
        # id-routed writes probe only these (new partitions can only
        # hold correctly-slotted rows). Accumulates across repeated
        # expansions: partitions added by an earlier expansion existed
        # before this one.
        pre = set(space.pre_expand_pids)
        pre.update(p.id for p in space.partitions[:old])
        space.pre_expand_pids = sorted(pre)
        # the group creator rolls back on failure, so re-carve the
        # existing partitions' slots only after the new ones exist —
        # a failed expansion must leave the old routing intact
        self._create_partition_group(space, servers, None,
                                     slots=slots[old:])
        for i, part in enumerate(space.partitions[:old]):
            part.slot = slots[i]
        # pre-expansion rows may now live off their slot's partition:
        # id-routed reads must fan out from here on
        space.expanded = True

    def _h_delete_db(self, _body, parts) -> dict:
        if len(parts) == 1:
            db = parts[0]
            if self.store.prefix(f"{PREFIX_SPACE}{db}/"):
                raise RpcError(409, f"db {db} still has spaces")
            self.store.delete(f"{PREFIX_DB}{db}")
            return {"name": db}
        if len(parts) == 3 and parts[1] == "spaces":
            return self._delete_space(parts[0], parts[2])
        raise RpcError(404, f"bad path {parts}")

    def _h_partitions(self, _body, _parts) -> dict:
        out = []
        for sp in self.store.prefix(PREFIX_SPACE).values():
            out.extend(sp["partitions"])
        return {"partitions": out}

    def _h_cluster_info(self, _body, _parts) -> dict:
        return {
            "name": "vearch-tpu",
            "version": "0.1.0",
            "status": "green" if self._alive_servers() else "yellow",
            # which master answered, and whether it currently leads the
            # metadata raft (ops + the cluster smoke profile use this)
            "node_id": self.node_id,
            "meta_leader": self.is_leader,
        }

    # -- runtime config (reference: cluster_api.go:294-307 modifySpaceConfig)

    def _h_set_config(self, body: dict, parts) -> dict:
        if len(parts) != 2:
            raise RpcError(404, "POST /config/{db}/{space}")
        db, name = parts
        sp = self.store.get(f"{PREFIX_SPACE}{db}/{name}")
        if sp is None:
            raise RpcError(404, f"space {db}/{name} not found")
        if "log_level" in body:
            # validate BEFORE persisting/fanning out: a typo'd level
            # must reject the whole request, not store junk config
            try:
                log.parse_level(str(body["log_level"]))
            except ValueError as e:
                raise RpcError(400, str(e)) from None
        self.store.put(f"/config/{db}/{name}", body)
        if "log_level" in body:
            # the master applies the flip to itself too before fanning
            # the config out to the space's PS nodes
            log.set_level(str(body["log_level"]))
        space = Space.from_dict(sp)
        servers = {s.node_id: s for s in self._alive_servers()}
        applied = []
        for part in space.partitions:
            for node_id in part.replicas:
                srv = servers.get(node_id)
                if srv is None:
                    continue
                try:
                    applied.append(rpc.call(
                        srv.rpc_addr, "POST", "/ps/engine/config",
                        {"partition_id": part.id, "config": body},
                    ))
                except RpcError:
                    pass
        return {"applied": applied}

    def _h_get_config(self, _body, parts) -> dict:
        if len(parts) != 2:
            raise RpcError(404, "GET /config/{db}/{space}")
        return self.store.get(f"/config/{parts[0]}/{parts[1]}") or {}

    # -- aliases (reference: master alias service + entity/Alias;
    #    POST /alias/{alias}/dbs/{db}/spaces/{space}) ------------------------

    def _h_create_alias(self, _body, parts) -> dict:
        if len(parts) != 5 or parts[1] != "dbs" or parts[3] != "spaces":
            raise RpcError(404, "POST /alias/{alias}/dbs/{db}/spaces/{space}")
        alias, _, db, _, space = parts
        if self.store.get(f"{PREFIX_SPACE}{db}/{space}") is None:
            raise RpcError(404, f"space {db}/{space} not found")
        self.store.put(f"/alias/{alias}", {"name": alias, "db_name": db,
                                           "space_name": space})
        return {"name": alias}

    def _h_get_alias(self, _body, parts) -> dict:
        if parts:
            a = self.store.get(f"/alias/{parts[0]}")
            if a is None:
                raise RpcError(404, f"alias {parts[0]} not found")
            return a
        return {"aliases": list(self.store.prefix("/alias/").values())}

    def _h_delete_alias(self, _body, parts) -> dict:
        if not parts or not self.store.delete(f"/alias/{parts[0]}"):
            raise RpcError(404, "alias not found")
        return {"name": parts[0]}

    # -- backup/restore (reference: services/backup_service.go — versioned
    #    space backup to object storage, cross-cluster restore) --------------

    def _h_backup(self, body: dict, parts) -> dict:
        if len(parts) != 3 or parts[1] != "spaces":
            raise RpcError(404, "POST /backup/dbs/{db}/spaces/{space}")
        db, _, name = parts
        sp = self.store.get(f"{PREFIX_SPACE}{db}/{name}")
        if sp is None:
            raise RpcError(404, f"space {db}/{name} not found")
        space = Space.from_dict(sp)
        command = body.get("command", "create")
        # `store` spec selects the backend (local root or s3 —
        # reference: minio-configured PSShardManager); legacy
        # `store_root` remains the local-filesystem shorthand
        store_spec = body.get("store") or body["store_root"]
        from vearch_tpu.cluster.objectstore import make_object_store

        ostore = make_object_store(store_spec)
        servers = {s.node_id: s for s in self._alive_servers()}
        base_prefix = f"backup/{db}/{name}"

        import json as _json
        import re as _re

        # content-addressed dedup across versions is the default
        # (reference: ref-counted shard files, ps/backup/
        # ref_count_manager.go); dedup=false keeps the flat layout
        dedup = bool(body.get("dedup", True))

        if command in ("create", "delete"):
            # serialise pool mutations per space: refs.json is a read-
            # modify-write on the PSes (create) and here (delete); two
            # concurrent commands would drop each other's ref updates
            # and a later GC could orphan a valid version
            import uuid as _uuid

            lock_owner = _uuid.uuid4().hex
            if not self.store.try_lock(f"backup/{db}/{name}", lock_owner,
                                       ttl_s=600.0):
                raise RpcError(409, f"backup for {db}/{name} in progress")
        if command == "create" and body.get("async"):
            # async create: shard jobs dispatched in parallel, progress
            # polled into a master job record, caller returns at once
            # (reference: async backups w/ progress endpoints,
            # master/cluster_api.go:330-340 + ps_backup_service.go:113).
            # The worker owns the space lock from here.
            try:
                return self._backup_create_async(
                    db, name, space, body, ostore, servers,
                    base_prefix, dedup, lock_owner)
            except BaseException:
                self.store.unlock(f"backup/{db}/{name}", lock_owner)
                raise
        try:
            if command == "create":
                version = self.store.next_id(f"/seq/backup/{db}/{name}")
                prefix = f"{base_prefix}/v{version}"
                # space metadata rides with the backup for
                # cross-cluster restore
                ostore.put_bytes(f"{prefix}/space.json",
                                 _json.dumps(space.to_dict()).encode())
                results = []
                for i, part in enumerate(sorted(space.partitions,
                                                key=lambda p: p.slot)):
                    srv = servers.get(part.leader)
                    if srv is None:
                        raise RpcError(
                            503, f"leader of partition {part.id} down"
                        )
                    results.append(
                        rpc.call(srv.rpc_addr, "POST", "/ps/backup", {
                            "partition_id": part.id,
                            "store_root": body.get("store_root"),
                            "store": body.get("store"),
                            "key_prefix": f"{prefix}/shard_{i}",
                            "pool_prefix": (
                                f"{base_prefix}/pool/shard_{i}"
                                if dedup else None
                            ),
                        })
                    )
                return {"version": version, "partitions": results}

            if command == "delete":
                version = int(body["version"])
                prefix = f"{base_prefix}/v{version}"
                try:
                    bmeta = _json.loads(
                        ostore.get_bytes(f"{prefix}/space.json")
                    )
                except (FileNotFoundError, KeyError) as e:
                    raise RpcError(
                        404, f"backup v{version} not found"
                    ) from e
                results = []
                # shard count from the BACKUP's metadata: the live
                # space may have been recreated with a different
                # partition_num, and missing a shard would leak its
                # blobs' refs forever
                for i in range(len(bmeta["partitions"])):
                    shard = f"{prefix}/shard_{i}"
                    # always decref: delete_tree_dedup scrubs this
                    # version from every pool ref (a crash between
                    # incref and manifest write leaves refs with no
                    # manifest — gating on the manifest would pin those
                    # blobs forever); flat backups have an empty pool,
                    # so the scrub is a no-op for them
                    results.append(ostore.delete_tree_dedup(
                        shard, f"{base_prefix}/pool/shard_{i}"
                    ))
                for key in ostore.list(prefix.rstrip("/") + "/"):
                    try:
                        ostore.delete(key)
                    except (FileNotFoundError, IOError):
                        pass
                return {"version": version, "shards": results}
        finally:
            if command in ("create", "delete"):
                self.store.unlock(f"backup/{db}/{name}", lock_owner)

        if command == "list":
            versions = sorted({
                int(m.group(1))
                for k in ostore.list(base_prefix)
                if (m := _re.search(rf"{_re.escape(base_prefix)}/v(\d+)/", k))
            })
            return {"versions": versions}

        if command == "restore":
            version = int(body["version"])
            prefix = f"{base_prefix}/v{version}"
            try:
                bmeta = _json.loads(ostore.get_bytes(f"{prefix}/space.json"))
            except FileNotFoundError as e:
                raise RpcError(404, f"backup v{version} not found") from e
            except IOError as e:
                # transient store trouble is NOT "backup not found"
                raise RpcError(503, f"backup store error: {e}") from e
            if len(bmeta["partitions"]) != len(space.partitions):
                raise RpcError(
                    400,
                    f"backup has {len(bmeta['partitions'])} shards but "
                    f"space has {len(space.partitions)} partitions",
                )
            results = []
            for i, part in enumerate(sorted(space.partitions,
                                            key=lambda p: p.slot)):
                if servers.get(part.leader) is None:
                    raise RpcError(503, f"leader of partition {part.id} down")
                # restore is a point-in-time rewind: every replica resets
                # to the backup state (each clears its own log), or the
                # followers would silently keep the pre-restore data
                out = None
                from vearch_tpu.cluster.objectstore import DEDUP_MANIFEST

                # layout auto-detection: versions written with dedup
                # carry a dedup manifest; flat ones a plain MANIFEST
                dd = ostore.exists(
                    f"{prefix}/shard_{i}/{DEDUP_MANIFEST}"
                )
                for r in part.replicas:
                    srv = servers.get(r)
                    if srv is None:
                        continue
                    res = rpc.call(srv.rpc_addr, "POST", "/ps/restore", {
                        "partition_id": part.id,
                        "store_root": body.get("store_root"),
                        "store": body.get("store"),
                        "key_prefix": f"{prefix}/shard_{i}",
                        "pool_prefix": (
                            f"{base_prefix}/pool/shard_{i}" if dd else None
                        ),
                    })
                    if r == part.leader:
                        out = res
                results.append(out)
            # a restore rewrites partition data OUT OF BAND of the
            # write path, so router merged-result entries validated by
            # apply version can still look "current" while describing
            # pre-restore data. Re-put the space key (every router's
            # watch evicts through it) and synchronously evict entries
            # touching the restored partitions on each live router —
            # the next search recomputes against restored data.
            self.store.put(f"{PREFIX_SPACE}{db}/{name}", space.to_dict())
            pids = [p.id for p in space.partitions]
            for rt in self.store.prefix("/router/").values():
                try:
                    rpc.call(rt["addr"], "POST", "/cache/invalidate",
                             {"pids": pids}, timeout=5.0)
                except RpcError:
                    # unreachable router: its watch + entry TTL still
                    # converge, just not synchronously
                    continue
            return {"version": version, "partitions": results}

        raise RpcError(400, f"unknown backup command {command!r}")

    def _backup_create_async(self, db, name, space, body, ostore,
                             servers, base_prefix, dedup,
                             lock_owner) -> dict:
        import json as _json

        version = self.store.next_id(f"/seq/backup/{db}/{name}")
        prefix = f"{base_prefix}/v{version}"
        ostore.put_bytes(f"{prefix}/space.json",
                         _json.dumps(space.to_dict()).encode())
        job_id = f"{db}:{name}:v{version}"
        job = {
            "job_id": job_id, "db": db, "space": name, "version": version,
            "status": "running", "started": time.time(),  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            "updated": time.time(), "error": None,  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            "partitions": {}, "results": [],
        }
        shards = []
        for i, part in enumerate(sorted(space.partitions,
                                        key=lambda p: p.slot)):
            srv = servers.get(part.leader)
            if srv is None:
                self.store.unlock(f"backup/{db}/{name}", lock_owner)
                raise RpcError(503, f"leader of partition {part.id} down")
            shards.append((i, part, srv))
            job["partitions"][str(part.id)] = {
                "status": "pending", "files_done": 0, "files_total": None,
                "node_id": part.leader,
                # pre-seeded so later updates never RESIZE the dict — a
                # concurrent _deepcopy_job iterates it without the GIL
                # saving us from 'changed size during iteration'
                "error": None,
            }
        from vearch_tpu.utils import prune_job_registry

        with self._backup_jobs_lock:
            self._backup_jobs[job_id] = job
            prune_job_registry(self._backup_jobs)
        lock_name = f"backup/{db}/{name}"
        job_timeout = float(body.get("timeout_s", 3600.0))

        def worker():
            # every job/partition mutation happens under
            # _backup_jobs_lock so the deep-copying read path
            # (_h_backup_jobs -> _deepcopy_job) sees a consistent
            # record instead of relying on GIL timing; the lock is
            # never held across an RPC — only around the dict writes
            shards_still_running = False
            try:
                running = {}
                for i, part, srv in shards:
                    sid = f"{job_id}:shard_{i}"
                    pj = job["partitions"][str(part.id)]
                    try:
                        rpc.call(srv.rpc_addr, "POST", "/ps/backup", {
                            "partition_id": part.id,
                            "store_root": body.get("store_root"),
                            "store": body.get("store"),
                            "key_prefix": f"{prefix}/shard_{i}",
                            "pool_prefix": (
                                f"{base_prefix}/pool/shard_{i}"
                                if dedup else None
                            ),
                            "job_id": sid,
                        })
                        with self._backup_jobs_lock:
                            pj["status"] = "dumping"
                        running[part.id] = (sid, srv)
                    except RpcError as e:
                        with self._backup_jobs_lock:
                            pj["status"] = "error"
                            pj["error"] = e.msg
                deadline = time.monotonic() + job_timeout
                while running and time.monotonic() < deadline:
                    # keep the space lock alive for the job's real
                    # duration (same-owner try_lock refreshes the TTL):
                    # a long upload must not let the lock lapse while
                    # PS shards still mutate the pool's refs.json
                    self.store.try_lock(lock_name, lock_owner,
                                        ttl_s=600.0)
                    for pid_, (sid, srv) in list(running.items()):
                        pj = job["partitions"][str(pid_)]
                        try:
                            st = rpc.call(
                                srv.rpc_addr, "GET",
                                f"/ps/backup/progress?job_id={sid}")
                        except RpcError:
                            continue  # transient; keep polling
                        with self._backup_jobs_lock:
                            pj.update(
                                status=st["status"],
                                files_done=st.get("files_done", 0),
                                files_total=st.get("files_total"),
                            )
                            if st["status"] == "done":
                                job["results"].append(st.get("result"))
                                del running[pid_]
                            elif st["status"] == "error":
                                pj["error"] = st.get("error")
                                del running[pid_]
                            job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
                    # CLI refreshes at 0.5s; polling much faster only
                    # burns RPCs (review r5)
                    time.sleep(0.25)
                with self._backup_jobs_lock:
                    errs = [p for p in job["partitions"].values()
                            if p["status"] == "error"]
                    if running:
                        shards_still_running = True
                        job["status"] = "error"
                        job["error"] = (
                            "timed out waiting for shards "
                            + str(sorted(running)))
                    elif errs:
                        job["status"] = "error"
                        job["error"] = "; ".join(
                            str(p.get("error")) for p in errs)
                    else:
                        job["status"] = "done"
                    job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            except Exception as e:  # job record must never stick "running"
                with self._backup_jobs_lock:
                    job.update(status="error",
                               error=f"{type(e).__name__}: {e}",
                               updated=time.time())  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            finally:
                if not shards_still_running:
                    self.store.unlock(lock_name, lock_owner)
                # else: PS shards may still be mutating the pool's
                # refs.json — leave the lock to its TTL rather than
                # open a concurrent-create window (the timeout error
                # already tells the operator what happened)

        threading.Thread(target=worker, daemon=True,
                         name=f"backup-{job_id}").start()
        return {"version": version, "job_id": job_id, "status": "running"}

    def _h_backup_jobs(self, body, parts) -> dict:
        """Master backup-job progress (reference: backup progress routes,
        master/cluster_api.go:330-340). GET /backup/jobs lists; GET
        /backup/jobs/{job_id} details one (job ids contain ':', so they
        arrive as a single path part). Job records live on the leader
        (the worker runs there), so followers forward like the other
        leader-state GETs."""
        fwd = self._leader_get(
            "/backup/jobs" + (f"/{parts[0]}" if parts else ""))
        if fwd is not None:
            return fwd
        with self._backup_jobs_lock:
            if parts:
                job = self._backup_jobs.get(parts[0])
                if job is None:
                    raise RpcError(404, f"no backup job {parts[0]}")
                return _deepcopy_job(job)
            return {"jobs": [_deepcopy_job(j)
                             for j in self._backup_jobs.values()]}

    # -- elastic data plane: online split, snapshot-streamed replica
    #    migration, load-aware rebalancing (reference: the partition
    #    admin verbs in master/cluster_api.go + etcd-raft learner
    #    promotion). Every verb runs as an observable async job:
    #    GET /cluster/jobs, /cluster/health rollup, and the
    #    vearch_partition_splits_total / vearch_replica_migrations_total
    #    / vearch_elastic_jobs_running metrics. ---------------------------

    def _new_elastic_job(self, op: str, detail: dict) -> dict:
        from vearch_tpu.utils import prune_job_registry

        job_id = f"{op}-{self.store.next_id('/seq/elastic_job')}"
        job = {
            "job_id": job_id, "op": op, "status": "running",
            "phase": "init", "error": None,
            "started": time.time(),  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            "updated": time.time(),  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            "detail": dict(detail), "steps": [],
        }
        with self._elastic_jobs_lock:
            self._elastic_jobs[job_id] = job
            prune_job_registry(self._elastic_jobs)
        return job

    def _ejob_update(self, job: dict, phase: str | None = None,
                     **detail) -> None:
        with self._elastic_jobs_lock:
            if phase is not None:
                job["phase"] = phase
            job["detail"].update(detail)
            job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally

    def _ejob_finish(self, job: dict, error: str | None) -> None:
        with self._elastic_jobs_lock:
            job["status"] = "error" if error else "done"
            job["error"] = error
            job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally

    def _h_elastic_jobs(self, _body, parts) -> dict:
        """GET /cluster/jobs[/{job_id}] — elastic-job progress. Records
        live on the leader (the workers run there), so followers forward
        like the other leader-state GETs."""
        fwd = self._leader_get(
            "/cluster/jobs" + (f"/{parts[0]}" if parts else ""))
        if fwd is not None:
            return fwd
        with self._elastic_jobs_lock:
            if parts:
                job = self._elastic_jobs.get(parts[0])
                if job is None:
                    raise RpcError(404, f"no elastic job {parts[0]}")
                return _deepcopy_ejob(job)
            return {"jobs": [_deepcopy_ejob(j)
                             for j in self._elastic_jobs.values()]}

    def _find_partition(self, pid: int):
        """(space key, space dict, partition dict) or None."""
        for key, sp in self.store.prefix(PREFIX_SPACE).items():
            for p in sp["partitions"]:
                if int(p["id"]) == pid:
                    return key, sp, p
        return None

    def _load_spaces(self) -> list[Space]:
        return [Space.from_dict(d)
                for d in self.store.prefix(PREFIX_SPACE).values()]

    # -- online partition split ----------------------------------------------

    def _h_split(self, body: dict, _parts) -> dict:
        """POST /partitions/split {db_name, space_name, partition_id} —
        split a hot partition online: hash-range halves, PS-side
        copy + double-write mirror, atomic versioned router-map flip.
        Validates synchronously, then runs as an observable async job
        (poll GET /cluster/jobs/{job_id})."""
        db, name = body["db_name"], body["space_name"]
        pid = int(body["partition_id"])
        key = f"{PREFIX_SPACE}{db}/{name}"
        sp = self.store.get(key)
        if sp is None:
            raise RpcError(404, f"space {db}/{name} not found")
        space = Space.from_dict(sp)
        try:
            elastic.split_ranges(space, pid)
        except ValueError as e:
            raise RpcError(400, str(e)) from None
        parent = next(p for p in space.partitions if p.id == pid)
        servers = {s.node_id: s for s in self._alive_servers()}
        if parent.leader not in servers:
            raise RpcError(503, f"leader of partition {pid} down")
        timeout_s = float(body.get("timeout_s", 600.0))
        # the worker owns the space lock from here (the async-backup
        # idiom: held with TTL refresh for the job's real duration)
        token = self._lock_space(db, name)
        job = self._new_elastic_job("split", {
            "db": db, "space": name, "partition_id": pid,
            "children": [], "ps_phase": None,
            "docs_done": 0, "docs_total": 0,
        })
        try:
            threading.Thread(
                target=self._run_split_job,
                args=(job, db, name, pid, token, timeout_s),
                daemon=True, name=f"elastic-{job['job_id']}").start()
        except BaseException:
            self._unlock_space(db, name, token)
            raise
        return {"job_id": job["job_id"], "status": "running",
                "partition_id": pid}

    def _run_split_job(self, job, db, name, pid, token,
                       timeout_s) -> None:
        key = f"{PREFIX_SPACE}{db}/{name}"
        lock_name = f"space_mutate/{db}/{name}"
        children: list[Partition] = []
        leader_addr = None
        started = flipped = False
        err = None
        try:
            # re-read under the held lock: the handler's check was
            # advisory and the space may have mutated since
            sp = self.store.get(key)
            if sp is None:
                raise RpcError(404, f"space {db}/{name} vanished")
            space = Space.from_dict(sp)
            parent = next(
                (p for p in space.partitions if p.id == pid), None)
            if parent is None:
                raise RpcError(404, f"partition {pid} not in {db}/{name}")
            try:
                lo, mid, hi = elastic.split_ranges(space, pid)
            except ValueError as e:
                raise RpcError(400, str(e)) from None
            servers = {s.node_id: s for s in self._alive_servers()}
            leader_srv = servers.get(parent.leader)
            if leader_srv is None:
                raise RpcError(503, f"leader of partition {pid} down")
            leader_addr = leader_srv.rpc_addr

            # 1. mint + place + create the children. NOT yet routed:
            # they join the space record only at the atomic flip below,
            # so a crash before that leaves the parent serving alone
            # and the children as garbage the error path collects.
            self._ejob_update(job, phase="create_children")
            bounds = ((lo, mid), (mid, hi))
            for slo, _shi in bounds:
                cid = self.store.next_id(SEQ_PARTITION_ID)
                replicas = self._place_replicas(
                    space, list(servers.values()))
                child = Partition(
                    id=cid, space_id=space.id, db_name=db,
                    space_name=name, slot=slo, replicas=replicas,
                    leader=replicas[0], group=parent.group,
                    # minted under the post-flip epoch: responses from
                    # the children tell stale routers to reload
                    map_version=space.map_version + 1,
                )
                children.append(child)
                for nid in replicas:
                    srv = servers[nid]
                    rpc.call(srv.rpc_addr, "POST",
                             "/ps/partition/create",
                             {"partition": child.to_dict(),
                              "schema": space.schema.to_dict()})
                    srv.partition_ids.append(cid)
                    self.store.put(f"{PREFIX_SERVER}{nid}",
                                   srv.to_dict())
            self._ejob_update(job, children=[c.id for c in children])

            # 2. PS-side pipeline on the parent leader: bulk copy →
            # mirror catch-up → synchronous double-writes → cutover_ready
            self._ejob_update(job, phase="copy")
            wire = [{"id": c.id, "slot_lo": b[0], "slot_hi": b[1],
                     "leader": c.leader}
                    for c, b in zip(children, bounds)]
            rpc.call(leader_addr, "POST", "/ps/partition/split/start",
                     {"partition_id": pid, "children": wire},
                     timeout=30.0)
            started = True
            deadline = time.monotonic() + timeout_s
            misses = 0
            while True:
                # same-owner try_lock refreshes the space-lock TTL for
                # the job's real duration (the backup worker's idiom)
                self.store.try_lock(lock_name, token, ttl_s=600.0)
                try:
                    st = rpc.call(
                        leader_addr, "GET",
                        f"/ps/partition/split/progress?partition_id={pid}")
                    misses = 0
                except RpcError:
                    # tolerate transient poll failures; a dead parent
                    # leader surfaces as 10 consecutive misses
                    misses += 1
                    if misses >= 10:
                        raise
                    time.sleep(0.3)
                    continue
                self._ejob_update(
                    job, ps_phase=st.get("phase"),
                    docs_done=st.get("docs_done", 0),
                    docs_total=st.get("docs_total", 0),
                    mirrored=st.get("mirrored", 0))
                if st.get("status") == "error":
                    raise RpcError(
                        503, f"ps split failed: {st.get('error')}")
                if st.get("phase") == "cutover_ready":
                    break
                if time.monotonic() > deadline:
                    raise RpcError(503, "split copy/catch-up timed out")
                time.sleep(0.25)

            # 3. atomic router flip: ONE versioned store.put swaps the
            # parent for its children — the watch fires and routers
            # reload; a stale router that still writes to the parent
            # converges via the response-carried map_version (the
            # parent keeps sync-mirroring until deleted)
            self._ejob_update(job, phase="cutover")
            sp = self.store.get(key)
            space = Space.from_dict(sp)
            keep = [p for p in space.partitions if p.id != pid]
            space.partitions = sorted(keep + children,
                                      key=lambda p: p.slot)
            if not space.partition_rule:
                space.partition_num = len(space.partitions)
            space.map_version += 1
            self.store.put(key, space.to_dict())
            flipped = True

            # 4. commit on the parent leader (releases the sync-write
            # window), then retire the parent everywhere — the delete
            # IS the PS job's finalization (it drains the mirror first)
            rpc.call(leader_addr, "POST", "/ps/partition/split/finish",
                     {"partition_id": pid, "commit": True}, timeout=60.0)
            self._ejob_update(job, phase="retire_parent")
            self._drop_partitions([parent], list(servers.values()))
        except RpcError as e:
            err = e.msg
        except Exception as e:  # the record must never stick "running"
            _log.error("split job %s failed: %s: %s", job["job_id"],
                       type(e).__name__, e)
            err = f"{type(e).__name__}: {e}"
        finally:
            if err is not None and not flipped:
                # failed before the flip: abort the PS-side mirror and
                # garbage-collect the children so a retry starts clean;
                # the parent keeps serving untouched
                if started and leader_addr:
                    try:
                        rpc.call(leader_addr, "POST",
                                 "/ps/partition/split/finish",
                                 {"partition_id": pid, "commit": False},
                                 timeout=60.0)
                    except RpcError:
                        pass  # parent PS gone: its job died with it
                try:
                    self._drop_partitions(children,
                                          self._alive_servers())
                except Exception as e:
                    _log.error("split %s: child GC failed: %s: %s",
                               job["job_id"], type(e).__name__, e)
            self._m_splits.inc("error" if err else "done")
            self._ejob_finish(job, err)
            self._unlock_space(db, name, token)

    # -- snapshot-streamed replica migration ---------------------------------

    def _h_migrate(self, body: dict, _parts) -> dict:
        """POST /partitions/migrate {partition_id, to_node[, from_node]}
        — move one replica via raft-learner catch-up (chunked engine
        snapshot when behind the WAL horizon), promote to voter, retire
        the source. The serving leader never stops; routers retry the
        brief swap window, so clients see zero failed queries."""
        pid = int(body["partition_id"])
        to_node = int(body["to_node"])
        located = self._find_partition(pid)
        if located is None:
            raise RpcError(404, f"partition {pid} not found")
        _key, _sp, p = located
        replicas = [int(r) for r in p["replicas"]]
        if "from_node" in body:
            from_node = int(body["from_node"])
        else:
            # default: prefer moving a follower so leadership stays put
            others = [r for r in replicas if r != int(p["leader"])]
            from_node = others[0] if others else int(p["leader"])
        if from_node not in replicas:
            raise RpcError(400, f"node {from_node} holds no replica of "
                                f"partition {pid}")
        if to_node in replicas:
            raise RpcError(400, f"node {to_node} already holds a "
                                f"replica of partition {pid}")
        if not any(s.node_id == to_node for s in self._alive_servers()):
            raise RpcError(404, f"node {to_node} not alive")
        timeout_s = float(body.get("timeout_s", 600.0))
        job = self._new_elastic_job("migrate", {
            "partition_id": pid, "from_node": from_node,
            "to_node": to_node, "lag": None})

        def worker():
            try:
                self._migrate_one(
                    pid, from_node, to_node,
                    lambda **kw: self._ejob_update(job, **kw),
                    timeout_s=timeout_s)
                self._m_migrations.inc("done")
                self._ejob_finish(job, None)
            except RpcError as e:
                self._m_migrations.inc("error")
                self._ejob_finish(job, e.msg)
            except Exception as e:
                _log.error("migrate job %s failed: %s: %s",
                           job["job_id"], type(e).__name__, e)
                self._m_migrations.inc("error")
                self._ejob_finish(job, f"{type(e).__name__}: {e}")

        threading.Thread(target=worker, daemon=True,
                         name=f"elastic-{job['job_id']}").start()
        return {"job_id": job["job_id"], "status": "running",
                "partition_id": pid, "from_node": from_node,
                "to_node": to_node}

    def _migrate_one(self, pid: int, from_node: int, to_node: int,
                     upd, timeout_s: float = 600.0) -> None:
        """Move one replica of `pid` from `from_node` to `to_node`:

        1. create on the target as a raft LEARNER — it receives appends
           and (when behind the WAL compaction horizon) the chunked
           engine snapshot stream, but never votes or counts toward
           quorum, so a slow catch-up cannot stall serving;
        2. poll the leader's per-peer lag until the learner caught up;
        3. swap: fence at a bumped term, verify the target's log covers
           the leader's last entry (every committed write lives on the
           leader, so this proves no acked write can be lost), decree
           the new membership with the target as a voter and the source
           removed; writes that raced the lag check re-appoint the old
           leader for another catch-up round;
        4. retire the source replica.

        Raises RpcError on failure; the partition keeps serving from
        its original members in every failure mode (the learner is
        outside the quorum until step 3's decree)."""
        located = self._find_partition(pid)
        if located is None:
            raise RpcError(404, f"partition {pid} not found")
        key, sp, p = located
        replicas = sorted(int(r) for r in p["replicas"])
        if from_node not in replicas:
            raise RpcError(400, f"node {from_node} holds no replica of "
                                f"partition {pid}")
        if to_node in replicas:
            raise RpcError(400, f"node {to_node} already holds a "
                                f"replica of partition {pid}")
        servers = {s.node_id: s for s in self._alive_servers()}
        target = servers.get(to_node)
        if target is None:
            raise RpcError(404, f"node {to_node} not alive")
        leader = int(p["leader"])
        leader_srv = servers.get(leader)
        if leader_srv is None:
            raise RpcError(503, f"partition {pid} is leaderless")

        upd(phase="prepare", partition_id=pid, from_node=from_node,
            to_node=to_node)
        learners = sorted(set(int(x) for x in p.get("learners", []))
                          | {to_node})
        part = dict(p)
        part["learners"] = learners
        try:
            rpc.call(target.rpc_addr, "POST", "/ps/partition/create",
                     {"partition": part, "schema": sp["schema"]})
        except RpcError as e:
            if e.code != 409:  # already hosted (a resumed job): go on
                raise
        with self._reconfig_lock:
            term1 = int(p.get("term", 1)) + 1
            rpc.call(leader_srv.rpc_addr, "POST", "/ps/raft/lead",
                     {"pid": pid, "term": term1, "members": replicas,
                      "learners": learners})
            for r in replicas + [to_node]:
                if r == leader:
                    continue
                srv = servers.get(r)
                if srv is None:
                    continue
                try:
                    rpc.call(srv.rpc_addr, "POST", "/ps/raft/members",
                             {"pid": pid, "term": term1,
                              "members": replicas, "leader": leader,
                              "learners": learners})
                except RpcError:
                    pass  # a missed follower converges on the swap decree
            p["term"] = term1
            p["learners"] = learners
            self.store.put(key, sp)

        upd(phase="catchup")
        deadline = time.monotonic() + timeout_s
        misses = 0
        while True:
            try:
                st = rpc.call(leader_srv.rpc_addr, "GET",
                              f"/ps/raft/state/{pid}")
                misses = 0
            except RpcError:
                misses += 1
                if misses >= 10:
                    raise
                time.sleep(0.3)
                continue
            info = (st.get("peers") or {}).get(str(to_node)) or {}
            lag = info.get("lag")
            upd(lag=lag)
            if lag == 0:
                break
            if time.monotonic() > deadline:
                raise RpcError(503, f"learner {to_node} catch-up timed "
                                    f"out (lag={lag})")
            time.sleep(0.2)

        upd(phase="swap")
        new_members = sorted(set(replicas) - {from_node} | {to_node})
        new_leader = leader if leader != from_node else to_node
        term = int(p["term"])
        for _attempt in range(20):
            term += 1
            states = {}
            for r in sorted(set(replicas) | {to_node}):
                srv = servers.get(r)
                if srv is None:
                    continue
                try:
                    states[r] = rpc.call(srv.rpc_addr, "POST",
                                         "/ps/raft/fence",
                                         {"pid": pid, "term": term})
                except RpcError:
                    continue
            if leader not in states or to_node not in states:
                raise RpcError(503,
                               f"fence failed for partition {pid}")
            gap = (int(states[leader]["last_index"])
                   - int(states[to_node]["last_index"]))
            if gap <= 0:
                break
            # writes raced the lag check: resume the old leadership so
            # replication continues, then fence again next round
            rpc.call(leader_srv.rpc_addr, "POST", "/ps/raft/lead",
                     {"pid": pid, "term": term, "members": replicas,
                      "learners": learners})
            upd(lag=gap)
            time.sleep(0.2)
        else:
            raise RpcError(503, f"learner {to_node} kept lagging "
                                f"through the swap window")
        with self._reconfig_lock:
            rpc.call(servers[new_leader].rpc_addr, "POST",
                     "/ps/raft/lead",
                     {"pid": pid, "term": term, "members": new_members,
                      "learners": []})
            for r in new_members:
                if r == new_leader:
                    continue
                srv = servers.get(r)
                if srv is None:
                    continue
                try:
                    rpc.call(srv.rpc_addr, "POST", "/ps/raft/members",
                             {"pid": pid, "term": term,
                              "members": new_members,
                              "leader": new_leader, "learners": []})
                except RpcError:
                    pass
            p["replicas"] = new_members
            p["leader"] = new_leader
            p["term"] = term
            p["learners"] = []
            # promotion watermark for later reconfigures (same contract
            # as _reconfigure_partition): the new leader was verified to
            # cover the incumbent's log, so its fenced position bounds
            # everything committed so far
            p["promoted_log"] = [int(states[new_leader]["last_term"]),
                                 int(states[new_leader]["last_index"])]
            self.store.put(key, sp)
            if pid not in target.partition_ids:
                target.partition_ids.append(pid)
                self.store.put(f"{PREFIX_SERVER}{to_node}",
                               target.to_dict())
            src = servers.get(from_node)
            if src is not None and pid in src.partition_ids:
                src.partition_ids.remove(pid)
                self.store.put(f"{PREFIX_SERVER}{from_node}",
                               src.to_dict())

        # retire the source replica (best-effort: a dead source's
        # on-disk copy is inert — it is no longer in the membership)
        upd(phase="retire_source")
        src = servers.get(from_node)
        if src is not None:
            try:
                rpc.call(src.rpc_addr, "POST", "/ps/partition/delete",
                         {"partition_id": pid})
            except RpcError:
                pass
        upd(phase="done", lag=0)

    # -- load-aware rebalancing + drain --------------------------------------

    def _h_plan(self, _body, _parts) -> dict:
        """GET /cluster/plan — the load-aware plan, read-only: imbalance
        score, suggested replica moves, suggested splits. Heartbeat
        stats live on the leader; followers forward."""
        fwd = self._leader_get("/cluster/plan")
        if fwd is not None:
            return fwd
        return elastic.compute_plan(self._load_spaces(),
                                    self._alive_servers(),
                                    self._node_stats)

    def _h_rebalance(self, body: dict, _parts) -> dict:
        """POST /cluster/rebalance {apply} — compute the plan; with
        apply=true, execute its moves as one sequential job. Splits are
        returned as suggestions for the operator (POST
        /partitions/split) and never auto-run: they rewrite the routing
        map."""
        body = body or {}
        plan = elastic.compute_plan(
            self._load_spaces(), self._alive_servers(),
            self._node_stats,
            max_moves=int(body.get("max_moves", 4)))
        if not bool(body.get("apply")) or not plan["moves"]:
            return {**plan, "applied": False}
        job = self._new_elastic_job(
            "rebalance", {"imbalance": plan["imbalance"],
                          "total": len(plan["moves"])})
        with self._elastic_jobs_lock:
            job["steps"] = [{**m, "status": "pending", "error": None}
                            for m in plan["moves"]]
        threading.Thread(target=self._run_moves_job, args=(job,),
                         daemon=True,
                         name=f"elastic-{job['job_id']}").start()
        return {**plan, "applied": True, "job_id": job["job_id"]}

    def _h_drain(self, body: dict, _parts) -> dict:
        """POST /cluster/drain {node_id, apply} — plan (default) or run
        migrating every replica off a PS so it can be retired. 409 when
        any partition has nowhere to go without co-locating."""
        node_id = int(body["node_id"])
        servers = {s.node_id: s for s in self._alive_servers()}
        if node_id not in servers:
            raise RpcError(404, f"node {node_id} not registered")
        moves = self._drain_plan(node_id, servers)
        if not bool(body.get("apply")):
            return {"node_id": node_id, "moves": moves,
                    "applied": False}
        job = self._new_elastic_job("drain", {"node_id": node_id,
                                              "total": len(moves)})
        with self._elastic_jobs_lock:
            job["steps"] = [{**m, "status": "pending", "error": None}
                            for m in moves]
        threading.Thread(target=self._run_moves_job, args=(job,),
                         daemon=True,
                         name=f"elastic-{job['job_id']}").start()
        return {"node_id": node_id, "job_id": job["job_id"],
                "status": "running", "moves": moves}

    def _drain_plan(self, node_id: int, servers: dict) -> list[dict]:
        moves = []
        loads = elastic.node_loads(list(servers.values()),
                                   self._node_stats)
        # simulate against a moving load map so successive picks spread
        # over the targets instead of dogpiling the single coldest node
        sim = dict(loads)
        for _key, sp in sorted(self.store.prefix(PREFIX_SPACE).items()):
            for p in sp["partitions"]:
                if node_id not in p["replicas"]:
                    continue
                cands = [n for n in servers
                         if n != node_id and n not in p["replicas"]]
                if not cands:
                    raise RpcError(
                        409,
                        f"partition {p['id']}: no alive node outside "
                        f"its replica set — draining node {node_id} "
                        f"would co-locate replicas")
                st = self._node_stats.get(node_id, {}).get(
                    str(p["id"]))
                w = float((st or {}).get("size_bytes", 0) or 0)
                tgt = min(cands, key=lambda n: (sim.get(n, 0.0), n))
                sim[tgt] = sim.get(tgt, 0.0) + w
                sim[node_id] = sim.get(node_id, 0.0) - w
                moves.append({"partition_id": int(p["id"]),
                              "from_node": node_id, "to_node": tgt,
                              "reason": "drain"})
        return moves

    def _run_moves_job(self, job: dict) -> None:
        """Sequential executor for a list of migration steps (drain and
        rebalance-apply share it): one partition in flight at a time,
        so at most one extra copy of any partition's data exists."""
        failed = 0

        def upd(**kw):
            with self._elastic_jobs_lock:
                if "phase" in kw:
                    job["phase"] = kw.pop("phase")
                job["detail"].update(kw)
                job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally

        for step in list(job["steps"]):
            with self._elastic_jobs_lock:
                step["status"] = "running"
                job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            try:
                self._migrate_one(step["partition_id"],
                                  step["from_node"], step["to_node"],
                                  upd)
                self._m_migrations.inc("done")
                with self._elastic_jobs_lock:
                    step["status"] = "done"
            except RpcError as e:
                failed += 1
                self._m_migrations.inc("error")
                with self._elastic_jobs_lock:
                    step["status"] = "error"
                    step["error"] = e.msg
            except Exception as e:
                failed += 1
                self._m_migrations.inc("error")
                _log.error("elastic job %s step p%s failed: %s: %s",
                           job["job_id"], step["partition_id"],
                           type(e).__name__, e)
                with self._elastic_jobs_lock:
                    step["status"] = "error"
                    step["error"] = f"{type(e).__name__}: {e}"
        self._ejob_finish(
            job, f"{failed} move(s) failed" if failed else None)

    def _auto_rebalance_loop(self) -> None:
        """Opt-in closed loop (auto_rebalance=True, default off):
        periodically apply the planner's moves when the cluster is
        imbalanced and no elastic job is already in flight. Splits stay
        operator-driven even here."""
        while not self._stop.is_set():
            self._stop.wait(self.rebalance_interval)
            if self._stop.is_set() or not self.is_leader:
                continue
            try:
                with self._elastic_jobs_lock:
                    busy = any(j["status"] == "running"
                               for j in self._elastic_jobs.values())
                if busy:
                    continue
                plan = elastic.compute_plan(self._load_spaces(),
                                            self._alive_servers(),
                                            self._node_stats)
                if not plan["moves"]:
                    continue
                job = self._new_elastic_job(
                    "rebalance", {"imbalance": plan["imbalance"],
                                  "total": len(plan["moves"]),
                                  "auto": True})
                with self._elastic_jobs_lock:
                    job["steps"] = [{**m, "status": "pending",
                                     "error": None}
                                    for m in plan["moves"]]
                self._run_moves_job(job)
            except Exception as e:
                _log.error("auto-rebalance pass failed: %s: %s",
                           type(e).__name__, e)

    # -- space create (reference: services/space_service.go:59) --------------

    def _create_space(self, db: str, body: dict) -> dict:
        if self.store.get(f"{PREFIX_DB}{db}") is None:
            raise RpcError(404, f"db {db} not found")
        name = body["name"]
        key = f"{PREFIX_SPACE}{db}/{name}"
        if self.store.get(key) is not None:
            raise RpcError(409, f"space {db}/{name} exists")
        token = self._lock_space(db, name)
        try:
            schema = TableSchema.from_dict(
                {"name": name, **{k: body[k] for k in ("fields",) if k in body},
                 "training_threshold": body.get("training_threshold", 0),
                 "refresh_interval_ms": body.get("refresh_interval_ms", 1000)}
            )
            partition_num = int(body.get("partition_num", 1))
            replica_num = int(body.get("replica_num", 1))
            servers = self._alive_servers()
            if not servers:
                raise RpcError(503, "no partition servers registered")
            if replica_num > len(servers):
                raise RpcError(
                    400,
                    f"replica_num {replica_num} > {len(servers)} servers",
                )
            rule = body.get("partition_rule")
            if rule is not None:
                self._validate_rule(rule, schema)
            space_id = self.store.next_id(SEQ_SPACE_ID)
            anti = str(body.get("anti_affinity", "none"))
            if anti not in ("none", "host", "rack", "zone"):
                raise RpcError(
                    400, f"anti_affinity {anti!r} must be one of "
                         f"none/host/rack/zone"
                )
            slo = self._validate_slo(body.get("slo"))
            space = Space(
                id=space_id, name=name, db_name=db, schema=schema,
                partition_num=partition_num, replica_num=replica_num,
                partition_rule=rule, anti_affinity=anti,
                enable_id_cache=bool(body.get("enable_id_cache", True)),
                slo=slo,
            )
            # with a partition rule, every range backs its own group of
            # partition_num slot-sharded partitions (reference: a 3-range
            # rule with partition_num=2 yields 6 partitions)
            groups = [r["name"] for r in rule["ranges"]] if rule else [None]
            for group in groups:
                self._create_partition_group(space, servers, group)
            self.store.put(key, space.to_dict())
            return space.to_dict()
        finally:
            self._unlock_space(db, name, token)

    @staticmethod
    def _validate_slo(slo) -> dict | None:
        """Sanity-check a declared space SLO at admission time so the
        router's burn-rate math never divides by a nonsense budget."""
        if not slo:
            return None
        if not isinstance(slo, dict):
            raise RpcError(400, "slo must be an object")
        out: dict = {}
        if slo.get("latency_ms") is not None:
            lat = float(slo["latency_ms"])
            if lat <= 0:
                raise RpcError(400, "slo.latency_ms must be > 0")
            out["latency_ms"] = lat
        if slo.get("availability") is not None:
            avail = float(slo["availability"])
            if not 0.0 < avail < 1.0:
                raise RpcError(
                    400, "slo.availability must be in (0, 1)")
            out["availability"] = avail
        if slo.get("fast_burn_threshold") is not None:
            thr = float(slo["fast_burn_threshold"])
            if thr <= 0:
                raise RpcError(400, "slo.fast_burn_threshold must be > 0")
            out["fast_burn_threshold"] = thr
        if slo.get("recall_floor") is not None:
            # shadow-sampled recall objective: PS nodes receive it via
            # the register response and flag a statistical breach
            # (docs/QUALITY.md); /cluster/health degrades to yellow
            floor = float(slo["recall_floor"])
            if not 0.0 < floor <= 1.0:
                raise RpcError(400, "slo.recall_floor must be in (0, 1]")
            out["recall_floor"] = floor
        if not any(k in out for k in
                   ("latency_ms", "availability", "recall_floor")):
            raise RpcError(
                400, "slo must declare latency_ms, availability "
                     "and/or recall_floor")
        return out

    def _validate_rule(self, rule: dict, schema: TableSchema) -> None:
        from vearch_tpu.cluster.entities import rule_value_ns

        if rule.get("type") != "RANGE":
            raise RpcError(400, "only partition rule type RANGE supported")
        fname = rule.get("field", "")
        fields = {f.name for f in schema.scalar_fields()}
        if fname not in fields:
            raise RpcError(400, f"partition rule field {fname!r} not in "
                                f"space fields")
        ranges = rule.get("ranges") or []
        if not ranges:
            raise RpcError(400, "empty partition rule ranges")
        names = [r.get("name") for r in ranges]
        if len(set(names)) != len(names) or not all(names):
            raise RpcError(400, f"range names must be unique/non-empty: "
                                f"{names}")
        try:
            vals = [rule_value_ns(r["value"]) for r in ranges]
        except (ValueError, KeyError) as e:
            raise RpcError(400, f"bad range value: {e}") from e
        if vals != sorted(vals) or len(set(vals)) != len(vals):
            raise RpcError(400, "range values must be strictly increasing")

    def _place_replicas(self, space: Space, servers) -> list[int]:
        """Replica placement: least-loaded with anti-affinity by the
        space's strategy (reference: config.go:389 none/host/rack/zone;
        space_service.go:1272 placement). Delegates to the pure planner
        (elastic.place_replicas): strict no-co-location by node — the
        old inline version could either co-locate two replicas on one
        PS or crash, depending on pool order — plus least-loaded-by-
        reported-bytes preference and a deterministic tie-break. Load
        spreads across successive placements because the caller appends
        to partition_ids between calls."""
        try:
            return elastic.place_replicas(space, list(servers),
                                          self._node_stats)
        except ValueError as e:
            raise RpcError(400, str(e)) from None

    def _create_partition_group(self, space: Space, servers, group,
                                slots: list[int] | None = None) -> None:
        """Create one group of slot-sharded partitions with anti-affine
        least-loaded replica placement (reference:
        space_service.go:141-149). `slots` defaults to a fresh carve of
        partition_num; expansion passes just the new tail. A mid-way PS
        failure rolls the whole group back — already-created engines are
        dropped and server records restored — so a failed create/expand
        leaves no orphan engines or phantom partition_ids behind."""
        if slots is None:
            slots = carve_slots(space.partition_num)
        created: list[Partition] = []
        by_id = {s.node_id: s for s in servers}
        try:
            for slot in slots:
                pid = self.store.next_id(SEQ_PARTITION_ID)
                replicas = self._place_replicas(space, servers)
                part = Partition(
                    id=pid, space_id=space.id, db_name=space.db_name,
                    space_name=space.name, slot=slot, replicas=replicas,
                    leader=replicas[0], group=group,
                )
                created.append(part)
                for node_id in replicas:
                    srv = by_id[node_id]
                    rpc.call(srv.rpc_addr, "POST", "/ps/partition/create", {
                        "partition": part.to_dict(),
                        "schema": space.schema.to_dict(),
                    })
                    srv.partition_ids.append(pid)
                    self.store.put(f"{PREFIX_SERVER}{node_id}",
                                   srv.to_dict())
                space.partitions.append(part)
        except RpcError:
            self._drop_partitions(created, servers)
            space.partitions = [
                p for p in space.partitions
                if p.id not in {c.id for c in created}
            ]
            raise

    def _h_partition_rule(self, body: dict, _parts) -> dict:
        """Online add/drop of rule partitions (reference:
        test_module_partition.py:268 update_space_partition_rule with
        operator_type ADD/DROP)."""
        from vearch_tpu.cluster.entities import rule_value_ns

        db, name = body["db_name"], body["space_name"]
        key = f"{PREFIX_SPACE}{db}/{name}"
        # same lock as space create: concurrent ADD/DROP (or a racing
        # space delete) would read-modify-write over each other
        token = self._lock_space(db, name)
        try:
            return self._partition_rule_locked(body, db, name, key)
        finally:
            self._unlock_space(db, name, token)

    def _partition_rule_locked(self, body, db, name, key) -> dict:
        from vearch_tpu.cluster.entities import rule_value_ns

        sp = self.store.get(key)
        if sp is None:
            raise RpcError(404, f"space {db}/{name} not found")
        space = Space.from_dict(sp)
        if not space.partition_rule:
            raise RpcError(400, f"space {db}/{name} has no partition rule")
        op = body.get("operator_type", "ADD").upper()
        servers = self._alive_servers()
        if op == "DROP":
            pname = body["partition_name"]
            ranges = space.partition_rule["ranges"]
            if pname not in {r["name"] for r in ranges}:
                raise RpcError(404, f"rule partition {pname!r} not found")
            space.partition_rule["ranges"] = [
                r for r in ranges if r["name"] != pname
            ]
            doomed = [p for p in space.partitions if p.group == pname]
            space.partitions = [
                p for p in space.partitions if p.group != pname
            ]
            self._drop_partitions(doomed, servers)
        elif op == "ADD":
            new_ranges = (body.get("partition_rule") or {}).get("ranges", [])
            if not new_ranges:
                raise RpcError(400, "ADD requires partition_rule.ranges")
            if len(servers) < max(space.replica_num, 1):
                raise RpcError(
                    503,
                    f"need {space.replica_num} alive servers for new "
                    f"partitions, have {len(servers)}",
                )
            merged = space.partition_rule["ranges"] + list(new_ranges)
            merged.sort(key=lambda r: rule_value_ns(r["value"]))
            probe = {**space.partition_rule, "ranges": merged}
            self._validate_rule(probe, space.schema)
            space.partition_rule = probe
            for r in new_ranges:
                self._create_partition_group(space, servers, r["name"])
        else:
            raise RpcError(400, f"unknown operator_type {op!r}")
        self.store.put(key, space.to_dict())
        return space.to_dict()

    def _h_field_index(self, body: dict, _parts) -> dict:
        """Online scalar field-index add/remove (reference:
        c_api/gamma_api.h:166,181 AddFieldIndexWithParams/RemoveFieldIndex;
        Go seam gammacb/gamma.go:538,591). The master persists the schema
        change first — so recovered or newly placed replicas build the
        index at load — then fans the op out to EVERY replica of every
        partition: scalar indexes are engine-local structures, not
        replicated state, so each engine builds its own."""
        db, name = body["db_name"], body["space_name"]
        fname = body["field"]
        op = str(body.get("operator_type", "ADD")).upper()
        itype = str(body.get("index_type", "INVERTED")).upper()
        if op == "DROP":
            itype = "NONE"
        elif op != "ADD":
            raise RpcError(400, f"unknown operator_type {op!r}")
        key = f"{PREFIX_SPACE}{db}/{name}"
        # lock covers ONLY the schema read-modify-write: the fan-out below
        # can outlive the lock TTL (sync builds, slow replicas) and does
        # not touch the space record
        token = self._lock_space(db, name)
        try:
            sp = self.store.get(key)
            if sp is None:
                raise RpcError(404, f"space {db}/{name} not found")
            space = Space.from_dict(sp)
            f = next(
                (x for x in space.schema.fields if x.name == fname), None
            )
            if f is None:
                raise RpcError(404, f"field {fname!r} not found")
            if f.data_type is DataType.VECTOR:
                raise RpcError(400, f"{fname!r} is a vector field")
            try:
                f.scalar_index = ScalarIndexType(itype)
            except ValueError:
                raise RpcError(400, f"unknown index_type {itype!r}") from None
            self.store.put(key, space.to_dict())
        finally:
            self._unlock_space(db, name, token)

        # best-effort fan-out: a replica that misses it (dead, or a
        # transient RPC failure) converges anyway — field-index
        # expectations ride every heartbeat response and the PS
        # reconciles its engines against them (_h_register below)
        servers = {s.node_id: s for s in self._alive_servers()}
        acked: list[list[int]] = []
        failed: list[list[int]] = []
        req = {
            "field": fname,
            "index_type": itype,
            "background": bool(body.get("background", True)),
        }
        for part in space.partitions:
            for node_id in part.replicas:
                srv = servers.get(node_id)
                if srv is None:
                    failed.append([part.id, node_id])
                    continue
                try:
                    rpc.call(srv.rpc_addr, "POST", "/ps/field_index",
                             {**req, "partition_id": part.id})
                    acked.append([part.id, node_id])
                except RpcError:
                    failed.append([part.id, node_id])
        return {"field": fname, "index_type": itype,
                "acked": acked, "failed": failed}

    def _field_index_expectations(
        self,
    ) -> tuple[dict[str, dict[str, str]], dict[str, list]]:
        """({partition_id: {field: index_type}}, {partition_id: [scalar
        field dicts]}) over all spaces — the master-side truth PS nodes
        reconcile against each heartbeat (missed /field_index or
        /ps/schema/field fan-outs converge here). Cached on the watch
        revision (bumped by every store mutation) so the per-2s-heartbeat
        cost is a dict lookup, not a space scan."""
        with self._watch_cond:
            rev = self._watch_rev
        cached = getattr(self, "_fidx_cache", None)
        if cached is not None and cached[0] == rev:
            return cached[1], cached[2]
        out: dict[str, dict[str, str]] = {}
        schemas: dict[str, list] = {}
        for sp in self.store.prefix(PREFIX_SPACE).values():
            space = Space.from_dict(sp)
            flags = {
                f.name: f.scalar_index.value
                for f in space.schema.fields
                if f.data_type is not DataType.VECTOR
                and f.scalar_index is not ScalarIndexType.NONE
            }
            scalars = [
                f.to_dict() for f in space.schema.fields
                if f.data_type is not DataType.VECTOR
            ]
            for part in space.partitions:
                out[str(part.id)] = flags
                schemas[str(part.id)] = scalars
        self._fidx_cache = (rev, out, schemas)
        return out, schemas

    def _drop_partitions(self, parts: list[Partition], servers) -> None:
        """Delete partitions on their replicas and trim the ids from the
        server records (a stale partition_ids list would skew the
        least-loaded placement metric forever under retention churn)."""
        by_id = {s.node_id: s for s in servers}
        touched = set()
        for part in parts:
            for node_id in part.replicas:
                srv = by_id.get(node_id)
                if srv is None:
                    continue
                try:
                    rpc.call(srv.rpc_addr, "POST", "/ps/partition/delete",
                             {"partition_id": part.id})
                except RpcError:
                    pass
                if part.id in srv.partition_ids:
                    srv.partition_ids.remove(part.id)
                    touched.add(node_id)
        for node_id in touched:
            self.store.put(f"{PREFIX_SERVER}{node_id}",
                           by_id[node_id].to_dict())

    def _delete_space(self, db: str, name: str) -> dict:
        key = f"{PREFIX_SPACE}{db}/{name}"
        sp = self.store.get(key)
        if sp is None:
            raise RpcError(404, f"space {db}/{name} not found")
        space = Space.from_dict(sp)
        self._drop_partitions(space.partitions, self._alive_servers())
        self.store.delete(key)
        return {"name": name}

"""Standalone cluster: master + router + N partition servers in-process.

The reference ships an all-in-one mode where one binary runs every role
(reference: cmd/vearch/startup.go:112-120 role tags, CI standalone env).
Used by tests and the quickstart; production runs the roles as separate
processes on separate hosts with the same classes.
"""

from __future__ import annotations

import tempfile

from vearch_tpu.cluster.master import MasterServer
from vearch_tpu.cluster.ps import PSServer
from vearch_tpu.cluster.router import RouterServer


class StandaloneCluster:
    def __init__(
        self,
        data_dir: str | None = None,
        n_ps: int = 1,
        ps_kwargs: dict | None = None,
        router_kwargs: dict | None = None,
    ):
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="vearch_tpu_")
        self.master = MasterServer()
        self.ps_nodes: list[PSServer] = []
        self.router: RouterServer | None = None
        self.n_ps = n_ps
        # extra PSServer ctor args, applied to every node — lets tests
        # tighten observability knobs (drift slack, sample interval)
        # without reaching into started servers
        self.ps_kwargs = dict(ps_kwargs or {})
        # extra RouterServer ctor args — tail-latency tests tune the
        # hedge delay clamps and flip replica_read the same way
        self.router_kwargs = dict(router_kwargs or {})

    def start(self) -> "StandaloneCluster":
        self.master.start()
        for i in range(self.n_ps):
            ps = PSServer(
                data_dir=f"{self.data_dir}/ps{i}",
                master_addr=self.master.addr,
                **self.ps_kwargs,
            )
            ps.start()
            self.ps_nodes.append(ps)
        self.router = RouterServer(master_addr=self.master.addr,
                                   **self.router_kwargs)
        self.router.start()
        return self

    def add_ps(self) -> PSServer:
        """Join one more partition server to the running cluster — the
        target for migration/drain tests and live scale-out. Returns
        the started PS (it registers with the master on its own)."""
        ps = PSServer(
            data_dir=f"{self.data_dir}/ps{len(self.ps_nodes)}",
            master_addr=self.master.addr,
            **self.ps_kwargs,
        )
        ps.start()
        self.ps_nodes.append(ps)
        return ps

    def stop(self) -> None:
        if self.router:
            self.router.stop()
        for ps in self.ps_nodes:
            ps.stop()
        self.master.stop()

    @property
    def router_addr(self) -> str:
        return self.router.addr

    @property
    def master_addr(self) -> str:
        return self.master.addr

    def __enter__(self) -> "StandaloneCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

"""JSON+binary-tensor RPC layer over HTTP (stdlib only).

Plays the role of the reference's rpcx+protobuf transport (reference:
internal/pkg/server/rpc/rpc_server.go:33 — custom codec, handler chains
with panic recovery, per-handler timeouts). Control payloads are JSON;
numpy arrays anywhere in a body are extracted into raw little-endian
buffers appended after a JSON skeleton (`_encode`/`_decode`), so a
[1024, 128] f32 query batch rides the wire as 512 KB of bytes instead
of ~1.4 MB of parsed-float JSON — the reference's custom rpcx codec
serves the same purpose for its vector payloads.

Wire format (Content-Type: application/x-vearch-tensors):
    [u32 header_len][header json][tensor 0 bytes][tensor 1 bytes]...
header = {"body": <json, ndarray leaves replaced by {"__tensor__": i}>,
          "tensors": [{"dtype", "shape"}, ...]}
"""

from __future__ import annotations

import json
import struct
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

import numpy as np

from vearch_tpu.cluster.metrics import Registry, register_process_gauges
from vearch_tpu.utils import log

_log = log.get("rpc")

JSON_CT = "application/json"

# Terminal per-request abort (deadline exceeded, slow-request killer,
# operator /ps/kill). Distinct from the transient failover codes
# (-1/421/503) so the router NEVER retries a killed request as if the
# cluster were mid-failover — retrying would re-run the exact work the
# kill was meant to shed. 499 follows the nginx "client closed request"
# convention.
ERR_REQUEST_KILLED = 499

# Per-request context (the server is a ThreadingHTTPServer: one thread
# per in-flight request). Handlers that make secondary RPCs on behalf of
# the caller — e.g. a master follower forwarding a GET to the meta
# leader — read the caller's credentials here so auth travels with the
# forwarded call (reference: the BasicAuth header rides rpcx metadata).
_request_ctx = threading.local()


def current_auth_header() -> str | None:
    """Authorization header of the request the current thread is
    serving, or None outside a request."""
    return getattr(_request_ctx, "auth", None)
# v2: path-directed tensor restore (header carries "paths"). The BASE
# name changes (not a suffix — v1 peers match with startswith, so any
# "...tensors<suffix>" would still be claimed by them and silently
# mis-restored): an old peer seeing v2 falls to json.loads and fails
# loudly. THIS side still decodes v1 marker frames for the reverse skew.
BIN_CT = "application/x-vtensors2"
BIN_CT_V1 = "application/x-vearch-tensors"
_U32 = struct.Struct("<I")


def _extract_tensors(obj: Any, out: list, paths: list, path: tuple) -> Any:
    """Replace ndarray leaves with null placeholders, collecting the
    buffers and their key-paths (so restore navigates straight to each
    tensor instead of walking the whole tree)."""
    if isinstance(obj, np.ndarray):
        out.append(obj)
        paths.append(list(path))
        return None
    if isinstance(obj, dict):
        # record the POST-JSON key (always a string): the decoded tree
        # the paths navigate has stringified keys
        return {k: _extract_tensors(v, out, paths, path + (str(k),))
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_extract_tensors(v, out, paths, path + (i,))
                for i, v in enumerate(obj)]
    return obj


def _probably_has_tensor(body: Any) -> bool:
    """Shallow probe (two-ish levels) for ndarray leaves — catches the
    hot shapes ({"scores": arr}, {"vectors": [{"feature": arr}]}) so
    _encode skips the doomed json.dumps attempt instead of serializing
    a large prefix just to throw it away."""
    if isinstance(body, np.ndarray):
        return True
    if isinstance(body, dict):
        vals = body.values()
    elif isinstance(body, (list, tuple)):
        vals = body[:4]
    else:
        return False
    for v in vals:
        if isinstance(v, np.ndarray):
            return True
        if isinstance(v, dict):
            if any(isinstance(x, np.ndarray) for x in v.values()):
                return True
        elif isinstance(v, (list, tuple)):
            if any(isinstance(x, (np.ndarray, dict))
                   and _probably_has_tensor(x) for x in v[:4]):
                return True
    return False


def _encode(body: Any) -> tuple[str, bytes]:
    """JSON when tensor-free; binary framing otherwise. The tensor-free
    case is detected by letting json.dumps fail on the first ndarray —
    pure-JSON bodies (the vast majority of control traffic and most
    responses) serialize at C speed with no Python tree walk."""
    if not _probably_has_tensor(body):
        try:
            return JSON_CT, json.dumps(body).encode()
        except TypeError:
            pass  # a deeply nested tensor the probe missed
    tensors: list[np.ndarray] = []
    paths: list[list] = []
    skeleton = _extract_tensors(body, tensors, paths, ())
    arrays = [np.ascontiguousarray(t) for t in tensors]
    header = json.dumps({
        "body": skeleton,
        "paths": paths,
        "tensors": [
            {"dtype": a.dtype.str, "shape": list(a.shape)} for a in arrays
        ],
    }).encode()
    parts = [_U32.pack(len(header)), header]
    parts.extend(a.tobytes() for a in arrays)
    return BIN_CT, b"".join(parts)


def _restore_markers_v1(obj: Any, tensors: list[np.ndarray]) -> Any:
    """v1 compat: full-tree walk replacing {"__tensor__": i} markers."""
    if isinstance(obj, dict):
        if "__tensor__" in obj and len(obj) == 1:
            return tensors[obj["__tensor__"]]
        return {k: _restore_markers_v1(v, tensors) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_restore_markers_v1(v, tensors) for v in obj]
    return obj


def _decode(content_type: str, raw: bytes) -> Any:
    if not raw:
        return None
    if not (content_type.startswith(BIN_CT)
            or content_type.startswith(BIN_CT_V1)):
        return json.loads(raw)
    hlen = _U32.unpack_from(raw, 0)[0]
    header = json.loads(raw[4 : 4 + hlen])
    off = 4 + hlen
    tensors = []
    for meta in header["tensors"]:
        dt = np.dtype(meta["dtype"])
        n = int(np.prod(meta["shape"], dtype=np.int64)) if meta["shape"] \
            else 1
        nbytes = n * dt.itemsize
        arr = np.frombuffer(raw, dtype=dt, count=n, offset=off).reshape(
            meta["shape"]
        )
        off += nbytes
        tensors.append(arr)
    body = header["body"]
    if "paths" not in header:
        return _restore_markers_v1(body, tensors)
    for path, arr in zip(header["paths"], tensors):
        if not path:
            return arr  # the body IS the tensor
        node = body
        for step in path[:-1]:
            node = node[step]
        node[path[-1]] = arr
    return body


class RpcError(Exception):
    def __init__(self, code: int, msg: str,
                 retry_after: float | None = None):
        super().__init__(msg)
        self.code = code
        self.msg = msg
        # overload backpressure hint (seconds): set on 429 sheds so the
        # SDK can back off for exactly as long as the server asked
        # instead of guessing; rides the error payload end to end
        self.retry_after = retry_after


def _sample_profile(seconds: float, interval: float = 0.01) -> str:
    """Stdlib sampling profiler: aggregate thread stacks over a window
    (the pprof-CPU-profile analogue; py-spy-style, no native deps).
    Returns a text report of the hottest (function, file:line) frames
    and the hottest full stacks."""
    import collections
    import sys

    me = threading.get_ident()
    frame_counts: collections.Counter = collections.Counter()
    stack_counts: collections.Counter = collections.Counter()
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < 40:
                co = f.f_code
                entry = f"{co.co_name} ({co.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})"
                stack.append(entry)
                f = f.f_back
            if stack:
                frame_counts[stack[0]] += 1
                stack_counts[" <- ".join(stack[:10])] += 1
        samples += 1
        time.sleep(interval)
    lines = [f"# sampling profile: {seconds:.1f}s, {samples} samples, "
             f"{interval * 1e3:.0f}ms interval", "",
             "## hottest frames (leaf)"]
    for entry, cnt in frame_counts.most_common(25):
        lines.append(f"{cnt / max(samples, 1) * 100:6.1f}%  {entry}")
    lines.append("")
    lines.append("## hottest stacks")
    for stack, cnt in stack_counts.most_common(10):
        lines.append(f"{cnt / max(samples, 1) * 100:6.1f}%  {stack}")
    return "\n".join(lines)


class JsonRpcServer:
    """Route table of (method, path-prefix) -> handler(body, path_parts).

    Handlers return a JSON-serialisable object or raise RpcError; panics
    are caught and surfaced as 500s (reference: handler chains with panic
    recovery, pkg/server/rpc/handler/).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        authenticator: Callable | None = None,
        auth_exempt: tuple[str, ...] = (),
    ):
        self._routes: list[tuple[str, str, Callable]] = []
        # authenticator(headers, method, path) raises RpcError(401/403)
        # (reference: BasicAuth middleware, cluster_api.go:252)
        self.authenticator = authenticator
        self.auth_exempt = ("/metrics",) + auth_exempt
        # middleware(method, path, body, headers) -> None to continue,
        # or a result object served instead of the routed handler (the
        # multi-master follower->leader proxy hangs here)
        self.middleware: Callable | None = None
        self.metrics = Registry()
        register_process_gauges(self.metrics)
        from vearch_tpu.cluster.metrics import INTERNAL_ERRORS

        # process-wide: the swallowed-exception counter raft/WAL feed
        # has no server of its own; every role's /metrics exposes it
        self.metrics.attach(INTERNAL_ERRORS)
        self._m_requests = self.metrics.counter(
            "vearch_request_total", "RPC requests",
            ("method", "path", "code"),
        )
        self._m_latency = self.metrics.histogram(
            "vearch_request_duration_seconds", "RPC latency",
            ("method", "path"),
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # small JSON responses on kept-alive sockets must not sit in
            # Nagle's buffer waiting for the client's delayed ACK
            disable_nagle_algorithm = True

            def log_message(self, *args):  # quiet
                pass

            def _serve(self, method: str):
                plain_path = self.path.split("?")[0]
                if method == "GET" and plain_path in ("/metrics",
                                                      "/debug/stacks",
                                                      "/debug/profile",
                                                      "/debug/heap",
                                                      "/debug/traces"):
                    # /metrics stays open (scrapers); the debug
                    # endpoints burn CPU / dump internals, so they go
                    # through the authenticator like any other route
                    if (plain_path.startswith("/debug")
                            and outer.authenticator is not None):
                        try:
                            outer.authenticator(self.headers, method,
                                                plain_path)
                        except RpcError as e:
                            self._reply(200, {"code": e.code,
                                              "msg": e.msg})
                            return
                    if plain_path == "/metrics":
                        data = outer.metrics.render().encode()
                    elif plain_path == "/debug/profile":
                        # sampling CPU profile (reference: pprof UI CPU
                        # profiles, debugutil/): sample all thread
                        # stacks for ?seconds=N, render hot frames
                        from urllib.parse import parse_qs, urlparse

                        qs = parse_qs(urlparse(self.path).query)
                        try:
                            secs = min(
                                float(qs.get("seconds", ["2"])[0]), 30.0
                            )
                            if not (secs == secs and secs >= 0):  # NaN/neg
                                raise ValueError(secs)
                        except (TypeError, ValueError):
                            self._reply(200, {
                                "code": 400,
                                "msg": "seconds must be a number in "
                                       "[0, 30]",
                            })
                            return
                        data = _sample_profile(secs).encode()
                    elif plain_path == "/debug/heap":
                        # heap profile (reference: pprof heap via
                        # debugutil/): tracemalloc top allocation sites.
                        # First call arms tracing (small overhead until
                        # ?stop=1); subsequent calls report top sites.
                        from urllib.parse import parse_qs, urlparse

                        import tracemalloc

                        qs = parse_qs(urlparse(self.path).query)
                        if qs.get("stop", ["0"])[0] in ("1", "true"):
                            tracemalloc.stop()
                            data = b"tracemalloc stopped\n"
                        elif not tracemalloc.is_tracing():
                            tracemalloc.start(12)
                            data = (b"tracemalloc started; call again "
                                    b"for the report (?stop=1 to end)\n")
                        else:
                            snap = tracemalloc.take_snapshot()
                            stats = snap.statistics("lineno")
                            total = sum(s.size for s in stats)
                            lines = [
                                f"heap: {total / 1048576:.1f} MiB traced "
                                f"across {len(stats)} sites; top 50:"
                            ]
                            for s in stats[:50]:
                                lines.append(
                                    f"{s.size / 1024:10.1f} KiB "
                                    f"{s.count:8d} objs  "
                                    f"{s.traceback[0].filename}:"
                                    f"{s.traceback[0].lineno}"
                                )
                            data = "\n".join(lines).encode()
                    elif plain_path == "/debug/traces":
                        # finished-span store (reference: Jaeger query
                        # UI; zero-egress container -> local ring +
                        # this endpoint instead of a collector)
                        from urllib.parse import parse_qs, urlparse

                        qs = parse_qs(urlparse(self.path).query)
                        tid = qs.get("trace_id", [None])[0]
                        spans = (
                            outer.tracer.spans(trace_id=tid)
                            if getattr(outer, "tracer", None) is not None
                            else []
                        )
                        data = json.dumps({"spans": spans}).encode()
                    else:
                        # pprof-style live thread dump (reference:
                        # debugutil/pprofui goroutine profiles)
                        import sys

                        names = {
                            t.ident: t.name for t in threading.enumerate()
                        }
                        lines = []
                        for tid, frame in sys._current_frames().items():
                            lines.append(
                                f"--- thread {tid} ({names.get(tid, '?')}) ---"
                            )
                            lines.extend(
                                s.rstrip()
                                for s in traceback.format_stack(frame)
                            )
                        data = "\n".join(lines).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                t0 = time.monotonic()
                code = 0
                prefix = self.path.split("?")[0]
                _request_ctx.auth = self.headers.get("Authorization")
                try:
                    # drain the request body BEFORE anything that can
                    # raise (auth): with keep-alive clients an unread
                    # body stays in the stream and desyncs the next
                    # request on the pooled connection
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    if outer.authenticator is not None and not any(
                        prefix == p or prefix.startswith(p + "/")
                        for p in outer.auth_exempt
                    ):
                        outer.authenticator(self.headers, method, prefix)
                    body = _decode(
                        self.headers.get("Content-Type") or JSON_CT, raw
                    )
                    if "?" in self.path:
                        # URL query params ride into dict bodies under
                        # "_query" (reference: ?detail=true etc.);
                        # handlers opt in by reading it
                        from urllib.parse import parse_qs, urlparse

                        q = {k: v[-1] for k, v in parse_qs(
                            urlparse(self.path).query).items()}
                        if q and (body is None or isinstance(body, dict)):
                            body = {**(body or {}), "_query": q}
                    if outer.middleware is not None:
                        short = outer.middleware(
                            method, self.path.split("?")[0], body,
                            self.headers,
                        )
                        if short is not None:
                            self._reply(200, {"code": 0, "data": short})
                            return
                    match = outer._match(method, self.path)
                    handler, parts = match
                    if handler is not None:
                        prefix = outer._matched_prefix(method, self.path)
                    if handler is None:
                        code = 404
                        self._reply(404, {"code": 404, "msg": f"no route {method} {self.path}"})
                        return
                    result = handler(body, parts)
                    self._reply(200, {"code": 0, "data": result})
                except RpcError as e:
                    code = e.code
                    payload = {"code": e.code, "msg": e.msg}
                    if e.retry_after is not None:
                        payload["retry_after"] = float(e.retry_after)
                    self._reply(200, payload)
                except Exception as e:  # panic recovery
                    code = 500
                    _log.error("panic in %s %s: %s: %s\n%s", method,
                               prefix, type(e).__name__, e,
                               traceback.format_exc(limit=8))
                    self._reply(
                        500,
                        {"code": 500, "msg": f"{type(e).__name__}: {e}",
                         "trace": traceback.format_exc(limit=8)},
                    )
                finally:
                    _request_ctx.auth = None
                    dt = time.monotonic() - t0
                    # access log at debug (reference: request logs are
                    # debug-gated; IsDebugEnabled avoids the format cost)
                    if log.is_debug_enabled():
                        _log.debug("%s %s -> %s %.1fms", method, prefix,
                                   code, dt * 1e3)
                    outer._m_requests.inc(method, prefix, str(code))
                    outer._m_latency.observe(dt, method, prefix)

            def _reply(self, status: int, obj: dict):
                ct, data = _encode(obj)
                self.send_response(status)
                self.send_header("Content-Type", ct)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

            def do_PUT(self):
                self._serve("PUT")

            def do_DELETE(self):
                self._serve("DELETE")

        class Server(ThreadingHTTPServer):
            # stdlib default backlog is 5: a burst of concurrent
            # clients (each rpc.call opens a fresh TCP connection)
            # overflows it and the kernel RSTs the excess — observed as
            # flaky "connection reset by peer" at ~64 parallel callers
            request_queue_size = 512

        self._httpd = Server((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread: threading.Thread | None = None

    def route(self, method: str, prefix: str, handler: Callable) -> None:
        """Register handler(body, parts) where parts = path segments after
        the prefix."""
        self._routes.append((method, prefix.rstrip("/"), handler))

    def _matched_prefix(self, method: str, path: str) -> str:
        """Longest matching route prefix (metric label — bounded
        cardinality, unlike raw paths)."""
        path = path.split("?")[0].rstrip("/")
        best = ""
        for m, prefix, _ in self._routes:
            if m != method:
                continue
            if (path == prefix or path.startswith(prefix + "/")) and len(
                prefix
            ) > len(best):
                best = prefix
        return best or path

    def _match(self, method: str, path: str):
        path = path.split("?")[0].rstrip("/")
        best = None
        best_len = -1
        for m, prefix, h in self._routes:
            if m != method:
                continue
            if path == prefix or path.startswith(prefix + "/"):
                if len(prefix) > best_len:
                    rest = path[len(prefix):].strip("/")
                    parts = rest.split("/") if rest else []
                    best = (h, parts)
                    best_len = len(prefix)
        return best if best else (None, None)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"rpc-httpd-{self.addr}",
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def call(
    addr: str,
    method: str,
    path: str,
    body: Any = None,
    timeout: float = 120.0,
    auth: tuple[str, str] | None = None,
    extra_headers: dict[str, str] | None = None,
) -> Any:
    """Client side: raises RpcError on non-zero code. Bodies containing
    numpy arrays ride the binary tensor codec automatically.

    `addr` may be a comma-separated list (a multi-master endpoint): each
    address is tried in turn on unreachable/leaderless errors — any
    master proxies to the current leader, so the first healthy one
    answers."""
    import base64

    if "," in addr:
        last: RpcError | None = None
        for a in addr.split(","):
            try:
                return call(a.strip(), method, path, body,
                            timeout=timeout, auth=auth,
                            extra_headers=extra_headers)
            except RpcError as e:
                if e.code not in (-1, 503):
                    raise
                last = e
        raise last
    if body is not None:
        ct, data = _encode(body)
    else:
        ct, data = JSON_CT, None
    headers = {"Content-Type": ct}
    if extra_headers:
        headers.update(extra_headers)
    if auth is not None:
        token = base64.b64encode(f"{auth[0]}:{auth[1]}".encode()).decode()
        headers["Authorization"] = f"Basic {token}"
    status, resp_ct, raw = _pooled_request(addr, method, path, data,
                                           headers, timeout)
    if status >= 400:
        try:
            payload = json.loads(raw)
        except Exception:
            raise RpcError(status, f"HTTP {status}")
    else:
        payload = _decode(resp_ct, raw)
    if payload.get("code", 0) != 0:
        ra = payload.get("retry_after")
        raise RpcError(payload["code"], payload.get("msg", "rpc error"),
                       retry_after=float(ra) if ra is not None else None)
    return payload.get("data")


# -- keep-alive connection pool ---------------------------------------------
# One pooled HTTPConnection per (thread, addr): profiling showed a fresh
# TCP handshake per hop dominating small-request latency (client->router
# ->PS = 3 connects per b=1 search). Connections are not thread-safe, so
# the pool is thread-local; the server side already speaks HTTP/1.1 with
# Content-Length responses, so keep-alive just works. A stale pooled
# socket (peer restarted, idle timeout) gets ONE transparent retry on a
# fresh connection.

_conn_pool = threading.local()


def _pooled_request(addr, method, path, data, headers, timeout):
    import http.client

    pool = getattr(_conn_pool, "conns", None)
    if pool is None:
        pool = _conn_pool.conns = {}
    for attempt in (0, 1):
        conn = pool.get(addr)
        fresh = conn is None
        if fresh:
            host, _, port = addr.rpartition(":")
            try:
                conn = http.client.HTTPConnection(
                    host, int(port), timeout=timeout)
            except ValueError:
                raise RpcError(-1, f"bad address {addr!r}") from None
            pool[addr] = conn
        elif conn.sock is not None:
            conn.sock.settimeout(timeout)
        if conn.sock is None:
            conn.timeout = timeout  # not the timeout it was created with
            try:
                # lint: allow[serving-blocking] the transport boundary itself, bounded by the caller's timeout set just above
                conn.connect()
            except OSError as e:
                conn.close()
                pool.pop(addr, None)
                raise RpcError(-1, f"unreachable {addr}: {e}") from e
            # keep-alive + small request/response pairs hit Nagle vs
            # delayed-ACK (~40ms per hop on loopback); fresh-connection
            # clients never noticed because the handshake reset timing
            import socket as _socket

            conn.sock.setsockopt(_socket.IPPROTO_TCP,
                                 _socket.TCP_NODELAY, 1)

        def _drop():
            conn.close()
            pool.pop(addr, None)

        # SEND phase: a send-side failure proves the request never
        # executed, so retrying cannot duplicate a non-idempotent op
        try:
            conn.request(method, path, body=data, headers=headers)
        except (BrokenPipeError, ConnectionResetError,
                http.client.CannotSendRequest) as e:
            _drop()
            if fresh or attempt:
                raise RpcError(-1, f"unreachable {addr}: {e}") from e
            continue  # stale keep-alive socket: one fresh-connection retry
        except (http.client.HTTPException, OSError) as e:
            _drop()
            raise RpcError(-1, f"unreachable {addr}: {e}") from e
        # RECEIVE phase: only RemoteDisconnected (server closed without
        # sending ANY response — the canonical idle-keep-alive reap) is
        # retried; a timeout or mid-response error may mean the server
        # is still executing the request, and re-sending would run a
        # non-idempotent op twice
        try:
            resp = conn.getresponse()
            raw = resp.read()
        except http.client.RemoteDisconnected as e:
            _drop()
            if fresh or attempt:
                raise RpcError(-1, f"unreachable {addr}: {e}") from e
            continue
        except (http.client.HTTPException, OSError) as e:
            _drop()
            raise RpcError(-1, f"unreachable {addr}: {e}") from e
        ct = resp.headers.get("Content-Type") or JSON_CT
        if resp.headers.get("Connection", "").lower() == "close":
            _drop()
        return resp.status, ct, raw

"""Minimal JSON-over-HTTP RPC layer (stdlib only).

Plays the role of the reference's rpcx+protobuf transport (reference:
internal/pkg/server/rpc/rpc_server.go:33 — custom codec, handler chains
with panic recovery, per-handler timeouts). JSON keeps round 1 dependency
-free; the wire format is isolated behind `call()` / `JsonRpcServer` so a
binary codec (C++ extension) can replace it without touching services.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from vearch_tpu.cluster.metrics import Registry


class RpcError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code
        self.msg = msg


class JsonRpcServer:
    """Route table of (method, path-prefix) -> handler(body, path_parts).

    Handlers return a JSON-serialisable object or raise RpcError; panics
    are caught and surfaced as 500s (reference: handler chains with panic
    recovery, pkg/server/rpc/handler/).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        authenticator: Callable | None = None,
        auth_exempt: tuple[str, ...] = (),
    ):
        self._routes: list[tuple[str, str, Callable]] = []
        # authenticator(headers, method, path) raises RpcError(401/403)
        # (reference: BasicAuth middleware, cluster_api.go:252)
        self.authenticator = authenticator
        self.auth_exempt = ("/metrics",) + auth_exempt
        self.metrics = Registry()
        self._m_requests = self.metrics.counter(
            "vearch_request_total", "RPC requests",
            ("method", "path", "code"),
        )
        self._m_latency = self.metrics.histogram(
            "vearch_request_duration_seconds", "RPC latency",
            ("method", "path"),
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _serve(self, method: str):
                plain_path = self.path.split("?")[0]
                if method == "GET" and plain_path in ("/metrics",
                                                      "/debug/stacks"):
                    if plain_path == "/metrics":
                        data = outer.metrics.render().encode()
                    else:
                        # pprof-style live thread dump (reference:
                        # debugutil/pprofui goroutine profiles)
                        import sys

                        names = {
                            t.ident: t.name for t in threading.enumerate()
                        }
                        lines = []
                        for tid, frame in sys._current_frames().items():
                            lines.append(
                                f"--- thread {tid} ({names.get(tid, '?')}) ---"
                            )
                            lines.extend(
                                s.rstrip()
                                for s in traceback.format_stack(frame)
                            )
                        data = "\n".join(lines).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                t0 = time.time()
                code = 0
                prefix = self.path.split("?")[0]
                try:
                    if outer.authenticator is not None and not any(
                        prefix == p or prefix.startswith(p + "/")
                        for p in outer.auth_exempt
                    ):
                        outer.authenticator(self.headers, method, prefix)
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    body = json.loads(raw) if raw else None
                    match = outer._match(method, self.path)
                    handler, parts = match
                    if handler is not None:
                        prefix = outer._matched_prefix(method, self.path)
                    if handler is None:
                        code = 404
                        self._reply(404, {"code": 404, "msg": f"no route {method} {self.path}"})
                        return
                    result = handler(body, parts)
                    self._reply(200, {"code": 0, "data": result})
                except RpcError as e:
                    code = e.code
                    self._reply(200, {"code": e.code, "msg": e.msg})
                except Exception as e:  # panic recovery
                    code = 500
                    self._reply(
                        500,
                        {"code": 500, "msg": f"{type(e).__name__}: {e}",
                         "trace": traceback.format_exc(limit=8)},
                    )
                finally:
                    outer._m_requests.inc(method, prefix, str(code))
                    outer._m_latency.observe(time.time() - t0, method, prefix)

            def _reply(self, status: int, obj: dict):
                data = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

            def do_PUT(self):
                self._serve("PUT")

            def do_DELETE(self):
                self._serve("DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread: threading.Thread | None = None

    def route(self, method: str, prefix: str, handler: Callable) -> None:
        """Register handler(body, parts) where parts = path segments after
        the prefix."""
        self._routes.append((method, prefix.rstrip("/"), handler))

    def _matched_prefix(self, method: str, path: str) -> str:
        """Longest matching route prefix (metric label — bounded
        cardinality, unlike raw paths)."""
        path = path.split("?")[0].rstrip("/")
        best = ""
        for m, prefix, _ in self._routes:
            if m != method:
                continue
            if (path == prefix or path.startswith(prefix + "/")) and len(
                prefix
            ) > len(best):
                best = prefix
        return best or path

    def _match(self, method: str, path: str):
        path = path.split("?")[0].rstrip("/")
        best = None
        best_len = -1
        for m, prefix, h in self._routes:
            if m != method:
                continue
            if path == prefix or path.startswith(prefix + "/"):
                if len(prefix) > best_len:
                    rest = path[len(prefix):].strip("/")
                    parts = rest.split("/") if rest else []
                    best = (h, parts)
                    best_len = len(prefix)
        return best if best else (None, None)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def call(
    addr: str,
    method: str,
    path: str,
    body: Any = None,
    timeout: float = 120.0,
    auth: tuple[str, str] | None = None,
) -> Any:
    """Client side: raises RpcError on non-zero code."""
    import base64

    url = f"http://{addr}{path}"
    data = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"}
    if auth is not None:
        token = base64.b64encode(f"{auth[0]}:{auth[1]}".encode()).decode()
        headers["Authorization"] = f"Basic {token}"
    req = urllib.request.Request(
        url, data=data, method=method, headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:
            raise RpcError(e.code, str(e)) from e
    except urllib.error.URLError as e:
        raise RpcError(-1, f"unreachable {addr}: {e}") from e
    except OSError as e:
        # connection reset mid-read surfaces as a bare OSError, not URLError
        raise RpcError(-1, f"unreachable {addr}: {e}") from e
    if payload.get("code", 0) != 0:
        raise RpcError(payload["code"], payload.get("msg", "rpc error"))
    return payload.get("data")

"""TOML config loader (reference: config/config.toml parsed by
internal/config/config.go — one file for all roles with [global],
[masters], [router], [ps] sections + per-role Validate).

Example:

    [global]
    name = "vearch-tpu"
    data = "./vearch_data"
    auth = false
    root_password = "secret"

    [master]
    host = "127.0.0.1"
    port = 8817
    heartbeat_ttl = 8.0

    [router]
    port = 9001
    fanout_workers = 0        # 0 = auto-size with partition count
    cache_entries = 512       # merged-result cache; 0 disables
    cache_ttl_s = 10.0        # safety net for unseen writers
    hedge_quantile = 0.95     # adaptive hedge delay quantile; 0 disables
    hedge_budget_pct = 10.0   # hedges stay <= this % of scatter RPCs
    replica_read = false      # reads to the least-loaded live replica

    [ps]
    port = 8081
    max_concurrent_searches = 256
    search_cache_entries = 256  # partition result cache; 0 disables
    admission_queue_limit = 0   # shed (429) past this many waiters; 0 off
"""

from __future__ import annotations

import tomllib
from dataclasses import dataclass, field
from typing import Any


@dataclass
class Config:
    global_: dict[str, Any] = field(default_factory=dict)
    master: dict[str, Any] = field(default_factory=dict)
    router: dict[str, Any] = field(default_factory=dict)
    ps: dict[str, Any] = field(default_factory=dict)
    # reference: [tracer] block (sampler type/param), startup.go:66-85
    tracer: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        cfg = cls(
            global_=raw.get("global", {}),
            master=raw.get("master", {}),
            router=raw.get("router", {}),
            ps=raw.get("ps", {}),
            tracer=raw.get("tracer", {}),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        """Per-role sanity checks (reference: per-role Validate,
        cmd/vearch/startup.go:168)."""
        for section, d in (("master", self.master), ("router", self.router),
                           ("ps", self.ps)):
            port = d.get("port")
            if port is not None and not (0 <= int(port) < 65536):
                raise ValueError(f"[{section}] port {port} out of range")
        ttl = self.master.get("heartbeat_ttl")
        if ttl is not None and float(ttl) <= 0:
            raise ValueError("[master] heartbeat_ttl must be positive")
        rate = self.tracer.get("sample_rate")
        if rate is not None and not (0.0 <= float(rate) <= 1.0):
            raise ValueError("[tracer] sample_rate must be in [0, 1]")
        for key in ("fanout_workers", "cache_entries"):
            v = self.router.get(key)
            if v is not None and int(v) < 0:
                raise ValueError(f"[router] {key} must be >= 0")
        ttl = self.router.get("cache_ttl_s")
        if ttl is not None and float(ttl) < 0:
            raise ValueError("[router] cache_ttl_s must be >= 0")
        sce = self.ps.get("search_cache_entries")
        if sce is not None and int(sce) < 0:
            raise ValueError("[ps] search_cache_entries must be >= 0")
        hq = self.router.get("hedge_quantile")
        if hq is not None and not (0.0 <= float(hq) < 1.0):
            raise ValueError("[router] hedge_quantile must be in [0, 1) "
                             "(0 disables hedging)")
        hb = self.router.get("hedge_budget_pct")
        if hb is not None and not (0.0 <= float(hb) <= 100.0):
            raise ValueError("[router] hedge_budget_pct must be in "
                             "[0, 100]")
        aql = self.ps.get("admission_queue_limit")
        if aql is not None and int(aql) < 0:
            raise ValueError("[ps] admission_queue_limit must be >= 0 "
                             "(0 disables shedding)")

    @property
    def data_dir(self) -> str:
        return self.global_.get("data", "./vearch_data")

    @property
    def log_level(self) -> str:
        """Reference: [global] level (config.go GetLogInfoWriteSwitch)."""
        return str(self.global_.get("log_level", "info"))

    @property
    def log_dir(self) -> str:
        return self.log_dir_for(self.data_dir)

    def log_dir_for(self, data_dir: str) -> str:
        """Log directory given the EFFECTIVE data dir (a --data-dir CLI
        override may differ from the TOML value): explicit [global] log
        wins, otherwise logs live under the data dir."""
        import os

        explicit = self.global_.get("log")
        return str(explicit) if explicit else os.path.join(data_dir, "logs")

    @property
    def auth(self) -> bool:
        return bool(self.global_.get("auth", False))

    @property
    def root_password(self) -> str:
        return str(self.global_.get("root_password", "secret"))

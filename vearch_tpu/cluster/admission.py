"""Admission control: bounded wait queue in front of the search gates.

Overload on a partition server degrades in two stages today: requests
queue on the concurrency gate (latency climbs), then the gate's 30s
acquire times out (latency has already collapsed for everyone). This
controller adds the missing first line: when the number of requests
*waiting* for a gate crosses ``queue_limit``, new arrivals are shed
immediately with 429 + Retry-After instead of joining a queue that is
already longer than anyone will wait for. Shed work never touches the
engine, so it costs zero device dispatches.

Three priority classes make the bounded queue a priority queue: normal
traffic sheds at ``queue_limit``; high-priority requests
(``priority >= 1`` in the search body — replica catch-up probes,
operator diagnostics) are allowed to queue up to twice that depth, so a
saturated node stays debuggable; low-priority work (``priority < 0`` —
the quality monitor's shadow ground-truth searches, obs/quality.py)
sheds at HALF the depth, so background truth sampling is the first
thing a loaded node drops and can never crowd out tenant traffic.

``queue_limit == 0`` disables shedding entirely (the default): the
behavior is exactly the pre-admission-control gate.
"""

from __future__ import annotations

from vearch_tpu.tools import lockcheck


@lockcheck.guarded
class AdmissionController:
    """Counts waiters and sheds past the bound; the actual concurrency
    limit stays with the semaphore gates behind it."""

    _guarded_by = {
        "_waiting": "_lock",
        "shed_total": "_lock",
        "admitted_total": "_lock",
    }

    def __init__(self, queue_limit: int = 0, name: str = "ps.admission"):
        self.queue_limit = int(queue_limit)
        self._lock = lockcheck.make_lock(name)
        self._waiting = 0
        self.shed_total = 0
        self.admitted_total = 0

    def try_admit(self, priority: int = 0) -> bool:
        """Reserve a queue slot. Returns False (and counts a shed) when
        the wait queue is full for this priority class; the caller must
        pair a True return with exactly one :meth:`leave`."""
        limit = self.queue_limit
        if limit > 0 and int(priority) >= 1:
            limit *= 2
        elif limit > 0 and int(priority) < 0:
            limit = max(1, limit // 2)
        with self._lock:
            if limit > 0 and self._waiting >= limit:
                self.shed_total += 1
                return False
            self._waiting += 1
            self.admitted_total += 1
            return True

    def leave(self) -> None:
        """Release the queue slot (the request got a gate permit, timed
        out, or errored — the slot frees in every case)."""
        with self._lock:
            self._waiting -= 1

    @property
    def waiting(self) -> int:
        with self._lock:
            return self._waiting

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "queue_limit": self.queue_limit,
                "waiting": self._waiting,
                "shed_total": self.shed_total,
                "admitted_total": self.admitted_total,
            }

"""Router (stateless query tier): doc parse, scatter/gather, merge.

TPU-native re-design of the reference's router role (reference:
internal/router/document/doc_http.go:306-335 routes /document/{upsert,
search,query,delete} + /index/{flush,forcemerge,rebuild};
doc_query.go:165 parseSearch; client/client.go:382 Execute scatter /
:779 SearchFieldSortExecute gather-merge). Document routing is
murmur3-slot compatible with the reference; the per-partition fan-out
runs on a thread pool (one worker per partition RPC, like the
reference's goroutine-per-partition).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.entities import Server, Space
from vearch_tpu.cluster.rpc import ERR_REQUEST_KILLED, JsonRpcServer, RpcError
from vearch_tpu.obs import accounting

SPACE_CACHE_TTL = 3.0


class RouterServer:
    def __init__(
        self,
        master_addr: str,
        host: str = "127.0.0.1",
        port: int = 0,
        auth: bool = False,
        master_auth: tuple[str, str] | None = None,
        trace_sample: float = 0.0,
        trace_export: str | None = None,
        trace_collector: str | None = None,
        grpc_port: int | None = None,
        fanout_workers: int = 0,
        cache_entries: int = 512,
        cache_ttl_s: float = 10.0,
        hedge_quantile: float = 0.95,
        hedge_budget_pct: float = 10.0,
        replica_read: bool = False,
        hedge_min_delay_ms: float = 10.0,
        hedge_max_delay_ms: float = 2000.0,
    ):
        from vearch_tpu.cluster.tracing import SlowLog, Tracer

        self.master_addr = master_addr
        # per-role slow-query ring (threshold settable at runtime);
        # killed requests are force-recorded regardless of threshold
        self.slowlog = SlowLog()
        # span tracer (reference: Jaeger init, startup.go:66; sampler
        # rate + collector endpoint from the [tracer] config block)
        self.tracer = Tracer("router", sample_rate=trace_sample,
                             export_path=trace_export,
                             collector_endpoint=trace_collector)
        self._grpc_port = grpc_port
        self._host = host
        self.grpc = None
        # service-account credentials for master calls when auth is on
        self.master_auth = master_auth
        self._space_cache: dict[str, tuple[float, Space]] = {}
        self._server_cache: tuple[float, dict[int, Server]] = (0.0, {})
        self._auth_cache: dict[tuple[str, str], tuple[float, dict]] = {}
        self._cache_lock = threading.Lock()
        # fan-out pool: config-driven (`fanout_workers`); 0 = auto,
        # growing with the partition count seen at serve time (4 RPCs
        # in flight per partition, floor 32, cap 256) so a wide space
        # is not serialized behind a fixed 32-worker pool
        self.fanout_workers = int(fanout_workers)
        self._pool_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=self.fanout_workers or 32)
        # merged-result cache + single-flight (caching tentpole).
        # Entries record the per-partition apply versions they were
        # computed against; `_part_versions` tracks the newest version
        # each partition has acknowledged to THIS router (responses to
        # searches AND writes carry it), so a write through this
        # router invalidates exactly the entries computed before it —
        # read-your-writes holds with no TTL guesswork. The TTL is
        # only the safety net for writes this router never saw
        # (another router, direct-PS callers).
        from vearch_tpu.cluster.querycache import (
            SingleFlight, VersionedLRUCache,
        )

        self.result_cache = VersionedLRUCache(
            max_entries=cache_entries, ttl_s=cache_ttl_s)
        self._search_flight = SingleFlight()
        # streaming tail quantiles over per-partition scatter RTTs
        # (P^2 sketches, fixed memory): the router-side half of the
        # latency story — PS quantiles say how long the engine took,
        # these say what the fan-out actually cost this router
        from vearch_tpu.obs.quantiles import QuantileRegistry

        self.latency_quantiles = QuantileRegistry(
            name="router.quantiles")
        # adaptive hedged scatter (tail-latency tentpole): when a
        # partition RPC outlives the partition's own observed tail (the
        # configured quantile of its scatter sketch, clamped to
        # [min, max] delay), a second attempt fires at a DIFFERENT live
        # replica; first success wins and the loser is cancelled
        # through /ps/kill. hedge_quantile == 0 disables. The token
        # bucket keeps hedges under hedge_budget_pct of primary scatter
        # volume so a cluster-wide slowdown cannot double its own load.
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_budget_pct = float(hedge_budget_pct)
        self.hedge_min_delay_ms = float(hedge_min_delay_ms)
        self.hedge_max_delay_ms = float(hedge_max_delay_ms)
        # no hedging off a cold sketch: the first requests against a
        # partition carry no tail evidence worth acting on
        self.hedge_min_samples = 20
        self._hedge_lock = threading.Lock()
        self._hedge_token_cap = 10.0  # burst allowance
        self._hedge_tokens = self._hedge_token_cap
        self.hedge_stats = {"fired": 0, "won": 0, "cancelled": 0,
                            "budget_denied": 0}
        # load-aware replica reads: when on, reads without an explicit
        # load_balance go to the least-loaded live replica, scored from
        # the queue/latency digest each PS heartbeats to the master
        self.replica_read = bool(replica_read)
        # per-destination-node RPC counts (topology-bounded labels);
        # _servers() zero-fills newly seen nodes so the series exist
        # from the first metadata fetch, not the first routed request
        self._route_lock = threading.Lock()
        self._route_counts: dict[int, int] = {}
        self._part_versions: dict[int, int] = {}
        self._part_versions_lock = threading.Lock()
        # partition-map hot reload (elasticity): newest map version
        # observed per "db/space" (every PS search/upsert/delete
        # response stamps the version it served under) plus the
        # last-known pid set, so a split cutover or migration becomes
        # visible through ANY response — the stale space entry is
        # evicted immediately instead of waiting out the TTL, and only
        # the merged-result entries touching remapped partitions die
        self._map_versions: dict[str, int] = {}
        self._space_pids: dict[str, set[int]] = {}
        # TTL is the fallback freshness bound; the watch loop below
        # usually invalidates within one long-poll round trip
        self.space_cache_ttl = SPACE_CACHE_TTL
        # faulty-node tracking (reference: client/master_cache.go
        # faulty-server list): a node whose RPC just failed is skipped
        # by read load-balancing until its penalty expires, instead of
        # every request re-discovering the failure via timeout
        self._faulty: dict[int, float] = {}  # node_id -> penalty expiry
        self.faulty_ttl = 5.0
        # canonical "db/space" -> alias cache keys resolved through it
        self._alias_backmap: dict[str, set[str]] = {}
        self._watch_rev = 0
        self._watch_epoch: str | None = None
        self._watch_stop = threading.Event()

        self.server = JsonRpcServer(
            host, port,
            authenticator=self._authenticate if auth else None,
            # /cache/invalidate is exempt like the health probe: it
            # carries no data in either direction and evicting cache
            # entries is always safe — the master's restore fanout must
            # work without holding router credentials
            auth_exempt=("/cluster/health", "/cache/invalidate"),
        )
        s = self.server
        s.route("POST", "/document/upsert", self._h_upsert)
        s.route("POST", "/document/search", self._h_search)
        s.route("POST", "/document/query", self._h_query)
        s.route("POST", "/document/delete", self._h_delete)
        s.route("POST", "/index/flush", self._h_flush)
        s.route("POST", "/index/forcemerge", self._h_forcemerge)
        s.route("POST", "/index/rebuild", self._h_rebuild)
        # master proxy (reference: doc_http.go:189-251 master-proxy routes)
        for method in ("GET", "POST", "PUT", "DELETE"):
            s.route(method, "/dbs", self._proxy_master(method, "/dbs"))
        for method in ("GET", "POST", "PUT", "DELETE"):
            s.route(method, "/alias", self._proxy_master(method, "/alias"))
        s.route("GET", "/servers", self._proxy_master("GET", "/servers"))
        s.route("POST", "/partitions/rule", self._h_partition_rule)
        s.route("POST", "/field_index", self._h_field_index)
        s.route("GET", "/cache/dbs", self._h_cache_space)
        s.route("GET", "/cluster/health", self._h_health)
        s.route("GET", "/router/stats", self._h_router_stats)
        s.route("POST", "/cache/invalidate", self._h_cache_invalidate)
        s.route("GET", "/debug/slowlog", self._h_slowlog)
        s.tracer = self.tracer  # serves GET /debug/traces
        from vearch_tpu.cluster.metrics import register_tracer_metrics

        register_tracer_metrics(s.metrics, self.tracer)

        # fan-out saturation + result-cache observability. Callback
        # metrics read pre-initialized sources, so the full label set
        # renders from the first scrape (cardinality-soak contract).
        m = s.metrics
        m.callback_gauge(
            "vearch_router_fanout_pool_size",
            "current worker capacity of the scatter thread pool", (),
            lambda: {(): float(self._pool._max_workers)})
        m.callback_gauge(
            "vearch_router_fanout_queue_depth",
            "scatter RPCs queued waiting for a pool worker "
            "(sustained >0 means the pool is saturated)", (),
            lambda: {(): float(self._pool._work_queue.qsize())})

        def _cache_events():
            return {(e,): float(v)
                    for e, v in self.result_cache.stats.items()}

        m.callback_counter(
            "vearch_router_cache_events_total",
            "merged-result cache events (hit/miss/coalesced/bypass/"
            "eviction/invalidated)", ("event",), _cache_events)
        m.callback_gauge(
            "vearch_router_cache_entries",
            "live entries in the merged-result cache", (),
            lambda: {(): float(len(self.result_cache))})
        m.callback_gauge(
            "vearch_router_partition_map_version",
            "newest partition-map version this router has observed "
            "per space (fed by metadata fetches and the map_version "
            "stamped on every PS response)", ("db", "space"),
            self._map_version_series)
        self._m_map_reloads = m.counter(
            "vearch_router_map_reloads_total",
            "partition-map hot reloads by trigger (version = a PS "
            "response carried a newer map than the cached one; moved "
            "= a partition RPC 404ed after a remap)", ("trigger",))
        for t in ("version", "moved"):
            self._m_map_reloads.inc(t, by=0.0)

        def _router_quantiles():
            from vearch_tpu.obs.quantiles import (
                TRACKED_QUANTILES, _qlabel,
            )

            snap = self.latency_quantiles.snapshot()
            out = {}
            for op in ("scatter",):
                rec = snap.get(("_node", op)) or {"q": {}}
                for q in TRACKED_QUANTILES:
                    out[(op, _qlabel(q))] = float(
                        rec["q"].get(_qlabel(q), 0.0))
            return out

        m.callback_gauge(
            "vearch_router_latency_quantile",
            "streaming tail-latency quantiles of per-partition scatter "
            "RPCs as this router sees them (P^2 sketch, ms)",
            ("op", "q"), _router_quantiles)
        self._m_hedges = m.counter(
            "vearch_router_hedges_total",
            "hedged scatter attempts by event (fired/won/cancelled/"
            "budget_denied)", ("event",))
        for e in ("fired", "won", "cancelled", "budget_denied"):
            self._m_hedges.inc(e, by=0.0)
        self._m_replica_refetch = m.counter(
            "vearch_router_replica_refetch_total",
            "replica answers discarded for a stale apply_version and "
            "re-fetched from the leader (read-your-writes guard)", ())
        self._m_replica_refetch.inc(by=0.0)

        def _route_series():
            with self._route_lock:
                return {(str(n),): float(c)
                        for n, c in self._route_counts.items()}

        m.callback_counter(
            "vearch_router_replica_route_total",
            "partition RPCs routed per destination node (hedges "
            "included) — the replica-routing decision audit",
            ("node",), _route_series)

        # per-space SLO engine (docs/ACCOUNTING.md): objectives are
        # declared on the Space entity and reconciled on every metadata
        # fetch; each *logical* search observes exactly once in
        # _h_search (hedge attempts never reach that layer, so a won
        # hedge bills once)
        self.slo = accounting.SpaceSLOEngine()
        m.callback_gauge(
            "vearch_space_slo_burn_rate",
            "fast-window (5m) error-budget burn rate per space with a "
            "declared SLO (sustained >= 14.4 exhausts a 30-day budget "
            "in ~2 days and turns cluster health yellow)",
            ("space",), self.slo.burn_gauge)

    def start(self) -> None:
        self.server.start()
        if self._grpc_port is not None:
            # gRPC front door next to HTTP (reference: router gRPC port,
            # router/server.go:92); shares this router's handler stack
            from vearch_tpu.cluster.grpc_server import GrpcRouter

            self.grpc = GrpcRouter(self, host=self._host,
                                   port=self._grpc_port)
            self.grpc.start()
        threading.Thread(target=self._watch_loop, daemon=True,
                         name="router-watch").start()

    def stop(self) -> None:
        self._watch_stop.set()
        if self.grpc is not None:
            self.grpc.stop()
        self.server.stop()
        if self.tracer.exporter is not None:
            self.tracer.exporter.close()  # ship the last buffered spans
        self._pool.shutdown(wait=False)

    # -- watch-driven cache invalidation (reference: master_cache.go:414
    #    etcd watch streams keeping client caches fresh) ---------------------

    def _watch_loop(self) -> None:
        while not self._watch_stop.is_set():
            try:
                # lease-backed registry entry (reference: register_router
                # + GET /routers); the <=20s poll cadence keeps the 60s
                # lease alive, dead routers age out
                self._master_call("POST", "/register_router",
                                  {"addr": self.addr})
            except RpcError:
                pass
            try:
                out = self._master_call("GET", "/watch", {
                    "rev": self._watch_rev, "timeout": 20.0,
                })
            except RpcError:
                # master unreachable/failing over: TTL expiry covers
                # freshness until the watch reconnects
                self._watch_stop.wait(1.0)
                continue
            new_rev = int(out.get("rev", self._watch_rev))
            epoch = out.get("epoch")
            if epoch != self._watch_epoch:
                # a different master process answered (failover across
                # the multi-master list, or a restart): its rev counter
                # shares no history with ours, so magnitude comparison
                # is meaningless — adopt the new epoch and resync fully
                self._watch_epoch = epoch
                self._watch_rev = new_rev
                self._invalidate_caches()
                continue
            if new_rev < self._watch_rev:
                # same process, revision went BACKWARDS (shouldn't
                # happen; defensive): drop everything
                self._watch_rev = new_rev
                self._invalidate_caches()
                continue
            self._watch_rev = new_rev
            if out.get("reset"):
                self._invalidate_caches()
                continue
            self._apply_watch_keys(out.get("keys") or [])

    def _apply_watch_keys(self, keys: list[str]) -> None:
        """Selective invalidation by changed key prefix."""
        spaces: set[str] = set()
        servers = False
        everything = False
        for key in keys:
            if key.startswith("/space/"):
                spaces.add(key[len("/space/"):])  # "db/name"
            elif key.startswith(("/server/", "/fail_server/")):
                servers = True
            elif key.startswith(("/db/", "/alias/")):
                # db drop / alias retarget change space resolution in
                # ways a space-key diff does not capture
                everything = True
        doomed_pids: set[int] = set()
        with self._cache_lock:
            if everything:
                self._space_cache.clear()
                self._server_cache = (0.0, {})
            for sk in spaces:
                self._space_cache.pop(sk, None)
                # alias-resolved entries cache under the ALIAS key but
                # watch events name the canonical space — evict through
                # the back-map or alias users would stay stale
                for alias_key in self._alias_backmap.pop(sk, ()):
                    self._space_cache.pop(alias_key, None)
                # a space-key change can mean an out-of-band data
                # rewrite (restore re-puts the key): merged results
                # computed over its partitions are no longer evidence
                doomed_pids |= self._space_pids.get(sk, set())
            if servers:
                self._server_cache = (0.0, {})
        if everything:
            self.result_cache.clear()
        elif doomed_pids:
            self.result_cache.evict_pids(doomed_pids)

    def _h_router_stats(self, _body, _parts) -> dict:
        now = time.monotonic()
        # computed outside _cache_lock: the SLO engine has its own lock
        slo = self.slo.summary()
        # merged latency view: the node-level scatter sketch plus the
        # per-partition breakdown, keyed "pid/op" for wire transport
        quant = {
            f"{key[0]}/{key[1]}": rec
            for key, rec in self.latency_quantiles.snapshot().items()
        }
        with self._hedge_lock:
            hedges = dict(self.hedge_stats)
            hedge_tokens = round(self._hedge_tokens, 2)
        with self._route_lock:
            routes = {str(n): c for n, c in self._route_counts.items()}
        with self._cache_lock:
            return {
                "watch_rev": self._watch_rev,
                "faulty_nodes": {
                    str(n): round(t - now, 2)
                    for n, t in self._faulty.items() if t > now
                },
                "space_cache": len(self._space_cache),
                "server_cache": len(self._server_cache[1]),
                "map_versions": dict(self._map_versions),
                "fanout_pool_size": self._pool._max_workers,
                "fanout_queue_depth": self._pool._work_queue.qsize(),
                "result_cache": {
                    "entries": len(self.result_cache),
                    **self.result_cache.stats,
                },
                "latency_quantiles": quant,
                "hedges": hedges,
                "hedge_tokens": hedge_tokens,
                "replica_routes": routes,
                # per-space SLO state: objective, burn rates, latency
                # sketch — the doctor's slo_burn check and the master's
                # health rollup both read this block
                "slo": slo,
            }

    def _h_cache_invalidate(self, body, _parts) -> dict:
        """Targeted merged-result eviction, called by the master after
        an out-of-band data rewrite (restore) so stale entries die NOW
        instead of at TTL/next-version check. ``pids`` evicts entries
        touching those partitions; no pids clears everything."""
        body = body or {}
        pids = body.get("pids")
        if pids:
            dropped = self.result_cache.evict_pids(
                {int(p) for p in pids})
        else:
            dropped = self.result_cache.clear()
        return {"evicted": dropped}

    def _ensure_pool_capacity(self, n_partitions: int) -> None:
        """Auto-size the fan-out pool to the widest space served so
        far: 4 in-flight RPCs per partition (retries + concurrent
        requests), floor 32, cap 256. Growth-only — CPython's
        ThreadPoolExecutor reads _max_workers at submit time, so
        raising it takes effect without rebuilding the pool (and
        without abandoning queued work). A nonzero `fanout_workers`
        config pins the size and disables auto-growth."""
        if self.fanout_workers:
            return
        want = min(max(32, 4 * n_partitions), 256)
        if want <= self._pool._max_workers:
            return
        with self._pool_lock:
            if want > self._pool._max_workers:
                self._pool._max_workers = want

    def _note_apply_version(self, pid: int, version) -> None:
        """Record the newest apply version a partition acknowledged to
        this router. Monotonic max: scatter responses complete out of
        order, and a late search response carrying an older version
        must not roll the map back past a write already acked."""
        if version is None:
            return
        v = int(version)
        with self._part_versions_lock:
            if v > self._part_versions.get(pid, -1):
                self._part_versions[pid] = v

    def _map_version_series(self) -> dict:
        with self._cache_lock:
            return {tuple(k.split("/", 1)): float(v)
                    for k, v in self._map_versions.items()}

    def _track_space(self, key: str, space: Space) -> None:
        """Feed the map-version gauge and retire remapped partitions.

        Pids present in the last map for this space but gone from the
        fresh one (a split cutover retired the parent; a drain removed
        a partition) lose their merged-result entries and validity-map
        slots NOW, not at TTL expiry — and ONLY those: entries computed
        purely over surviving partitions keep serving."""
        pids = {p.id for p in space.partitions}
        old: set[int] | None
        with self._cache_lock:
            if space.map_version > self._map_versions.get(key, -1):
                self._map_versions[key] = space.map_version
            old = self._space_pids.get(key)
            self._space_pids[key] = pids
        removed = (old - pids) if old else set()
        if removed:
            self.result_cache.evict_pids(removed)
            with self._part_versions_lock:
                for pid in removed:
                    self._part_versions.pop(pid, None)
            for pid in removed:  # retire their latency sketches too
                self.latency_quantiles.drop((pid, "scatter"))

    def _observe_map_version(self, skey: tuple[str, str],
                             version) -> None:
        """Hot reload on a response-carried map version: every PS
        search/upsert/delete response stamps the partition-map version
        it served under, so a router holding a pre-cutover map learns
        of the remap from the first response that crosses it — the
        cached space is evicted and the next routing decision fetches
        the fresh map instead of waiting out the TTL or a watch round
        trip. Monotonic: late responses with older versions are
        ignored."""
        if version is None:
            return
        v = int(version)
        key = f"{skey[0]}/{skey[1]}"
        stale = False
        with self._cache_lock:
            if v > self._map_versions.get(key, -1):
                self._map_versions[key] = v
                hit = self._space_cache.get(key)
                if hit is not None and hit[1].map_version < v:
                    del self._space_cache[key]
                    stale = True
        if stale:
            self._m_map_reloads.inc("version")

    def _retry_moved(self, skey: tuple[str, str], fn):
        """One route-level retry when a scatter hits a retired
        partition: a 404 "partition N not on this node" means this
        router routed with a map from before a split cutover or
        migration finished. Drop the cached space and re-run the whole
        handler body — the retry re-fetches the map and re-routes docs
        to the surviving partitions. One retry only: a second 404 is a
        real error and propagates. (`_call_partition` deliberately does
        NOT retry 404s itself — re-asking the same retired pid can
        never succeed; the fix is re-routing, which only the route
        layer can do.)"""
        try:
            return fn()
        except RpcError as e:
            if e.code != 404 or "partition" not in str(e.msg):
                raise
            key = f"{skey[0]}/{skey[1]}"
            with self._cache_lock:
                self._space_cache.pop(key, None)
            self._m_map_reloads.inc("moved")
            return fn()

    @property
    def addr(self) -> str:
        return self.server.addr

    # -- metadata caches (reference: client/master_cache.go watch caches;
    #    TTL polling stands in for watches until the metastore is remote) ---

    def _space(self, db: str, name: str) -> Space:
        key = f"{db}/{name}"
        now = time.monotonic()
        with self._cache_lock:
            hit = self._space_cache.get(key)
            if hit and now - hit[0] < self.space_cache_ttl:
                return hit[1]
        canonical = key
        rev0 = self._watch_rev  # taken BEFORE the master fetch
        try:
            data = self._master_call("GET", f"/dbs/{db}/spaces/{name}")
        except RpcError as e:
            if e.code != 404:
                raise
            # alias resolution (reference: alias -> db/space indirection)
            alias = self._master_call("GET", f"/alias/{name}")
            data = self._master_call(
                "GET",
                f"/dbs/{alias['db_name']}/spaces/{alias['space_name']}",
            )
            canonical = f"{alias['db_name']}/{alias['space_name']}"
        space = Space.from_dict(data)
        # SLO reconcile on every metadata fetch: declared objectives
        # start (or stop) being scored within one cache TTL of the
        # space definition changing. Alias users score under the alias
        # key too, so their burn shows up under the name they query.
        self.slo.set_objective(canonical, space.slo)
        if canonical != key:
            self.slo.set_objective(key, space.slo)
        # runs whether or not the fetch is cached below: the pid-set
        # diff is what retires remapped partitions from the result
        # cache, and a watch-raced fetch still carries a valid map
        self._track_space(key, space)
        with self._cache_lock:
            # a watch event between our fetch and now may have evicted
            # this very key — caching what we fetched would write STALE
            # metadata back after its invalidation was consumed. Serve
            # the fetched value but don't cache it; the next call
            # re-fetches fresh.
            if self._watch_rev == rev0:
                self._space_cache[key] = (now, space)
                if canonical != key:
                    self._alias_backmap.setdefault(canonical,
                                                   set()).add(key)
        return space

    def _servers(self) -> dict[int, Server]:
        now = time.monotonic()
        with self._cache_lock:
            ts, cache = self._server_cache
            if now - ts < self.space_cache_ttl and cache:
                return cache
        data = self._master_call("GET", "/servers")
        servers = {
            s["node_id"]: Server.from_dict(s) for s in data["servers"]
        }
        with self._route_lock:
            # zero-fill route counters so the per-node series render
            # from the first scrape after discovery (cardinality-soak
            # contract: traffic moves values, never label sets)
            for nid in servers:
                self._route_counts.setdefault(nid, 0)
        with self._cache_lock:
            self._server_cache = (now, servers)
        return servers

    def _partition_target(
        self, space: Space, partition_id: int,
        load_balance: str = "leader",
        exclude: tuple = (),
    ) -> tuple[int, str]:
        """Pick a replica for the RPC (reference: client/ps.go:33-39
        clientType LEADER/NOTLEADER/RANDOM, plus "least_loaded" scored
        from the heartbeat load digest). Writes always go to the
        leader; reads may spread across replicas (replication is
        synchronous, so followers serve the same committed state).
        Read balancing skips nodes under a faulty penalty; the leader is
        never skipped for leader-targeted calls — correctness over
        availability there, and the failover retry handles a dead one.
        A non-empty ``exclude`` marks a hedge attempt: it must land on
        a different node than the primary, picking the least-loaded of
        what remains (503 when nothing remains — the hedge just loses)."""
        import random

        servers = self._servers()
        now = time.monotonic()
        part = next((p for p in space.partitions if p.id == partition_id),
                    None)
        if part is None:
            # the routing decision predates a map flip (split cutover /
            # migration): surface the same 404 a retired PS partition
            # returns, so _retry_moved re-routes through the fresh map
            raise RpcError(
                404, f"partition {partition_id} not in routing map")
        leader = part.leader if part.leader >= 0 else part.replicas[0]
        candidates = [r for r in part.replicas if r in servers]
        healthy = [r for r in candidates
                   if self._faulty.get(r, 0.0) <= now]
        node = leader
        if exclude:
            pool = [r for r in (healthy or candidates)
                    if r not in exclude]
            if not pool:
                raise RpcError(503, f"no alternate replica for "
                                    f"partition {partition_id}")
            node = self._pick_least_loaded(servers, pool)
        elif load_balance == "random" and candidates:
            node = random.choice(healthy or candidates)
        elif load_balance == "not_leader":
            followers = [r for r in (healthy or candidates) if r != leader]
            if followers:
                node = random.choice(followers)
        elif load_balance == "least_loaded" and candidates:
            node = self._pick_least_loaded(servers, healthy or candidates)
        srv = servers.get(node)
        if srv is None:
            raise RpcError(503, f"no server for partition {partition_id}")
        return node, srv.rpc_addr

    @staticmethod
    def _pick_least_loaded(servers: dict[int, Server],
                           pool: list[int]) -> int:
        """Score replicas by the load digest their PS heartbeats to the
        master (queue depth + inflight, weighted by the node's own q95):
        lowest wins, ties break randomly so equal nodes share traffic.
        Nodes without a digest yet (just joined, old PS) score neutral."""
        import random

        def score(n: int) -> float:
            load = servers[n].load if n in servers else {}
            depth = (float(load.get("waiting", 0))
                     + float(load.get("inflight", 0)))
            return (1.0 + depth) * (1.0 + float(load.get("q95_ms", 0.0)))

        best = min(score(n) for n in pool)
        return random.choice([n for n in pool if score(n) == best])

    def _invalidate_caches(self) -> None:
        with self._cache_lock:
            self._space_cache.clear()
            self._server_cache = (0.0, {})

    def _call_partition(self, space_key: tuple[str, str], pid: int,
                        path: str, body: dict, load_balance: str = "leader",
                        exclude: tuple = (), on_target=None):
        """RPC to a partition replica with one failover retry: an
        unreachable node triggers a metadata refresh (the master may
        have promoted a replica) and a second attempt against the leader
        (reference: client.go:433-447 replica failover retry loop)."""
        # -1: node unreachable; 421: replica is no longer the leader
        # (raft failover moved it); 503: quorum not yet re-established.
        # All mean the cluster is mid-failover: refresh metadata and
        # retry with backoff until the master finishes promoting
        # (reference: client.go:433-447 replica failover retry loop).
        # 499 (ERR_REQUEST_KILLED) deliberately falls through the
        # whitelist below: a deadline/operator kill is terminal, and a
        # retry would re-run the exact work the kill was meant to shed.
        last: RpcError | None = None
        # Thread the client deadline into the transport: each attempt's
        # HTTP timeout is the remaining budget plus a grace window. The
        # PS-side killer is the deadline ENFORCER (it answers 499, which
        # is terminal below); the transport bound is only the safety net
        # for a PS too hung to answer at all, so it must fire strictly
        # AFTER the kill would — a timeout equal to the budget races the
        # 499 and the whitelisted -1 it produces would mask the kill and
        # re-run killed work as failover. Without a deadline the
        # transport default still bounds every attempt.
        dl_ms = body.get("deadline_ms")
        deadline = (time.monotonic() + float(dl_ms) / 1e3) if dl_ms else None
        grace = 2.0
        for attempt in range(6):
            if attempt:
                self._invalidate_caches()
                delay = 0.3 * attempt
                if deadline is not None:
                    budget = deadline - time.monotonic()
                    if budget <= 0.0:
                        raise last or RpcError(
                            ERR_REQUEST_KILLED,
                            "request_killed: deadline exhausted during "
                            "failover retry")
                    delay = min(delay, budget)
                # lint: allow[serving-blocking] bounded failover backoff, clamped to the request's remaining deadline budget
                time.sleep(delay)
            node = -1
            try:
                space = self._space(*space_key)
                lb = load_balance
                if attempt and (
                    load_balance == "leader"
                    or last is None or last.code != -1
                ):
                    # 421/503 mean the leadership map moved: re-aim at
                    # the (refreshed) leader. A plain unreachable node
                    # on a READ keeps the caller's balancing — the
                    # faulty penalty steers the next pick to a healthy
                    # replica instead of forcing reads onto a possibly
                    # dead leader mid-failover
                    lb = "leader"
                node, addr = self._partition_target(space, pid, lb,
                                                    exclude=exclude)
                if on_target is not None:
                    # publish the pick before the RPC blocks: the hedge
                    # coordinator reads it to aim elsewhere / cancel
                    on_target(node)
                with self._route_lock:
                    self._route_counts[node] = (
                        self._route_counts.get(node, 0) + 1)
                timeout = 120.0
                if deadline is not None:
                    budget = deadline - time.monotonic()
                    if budget <= 0.0:
                        raise last or RpcError(
                            ERR_REQUEST_KILLED,
                            "request_killed: deadline exhausted before "
                            "partition RPC")
                    timeout = min(timeout, budget + grace)
                out = rpc.call(addr, "POST", path,
                               {**body, "partition_id": pid},
                               timeout=timeout)
                with self._cache_lock:
                    self._faulty.pop(node, None)  # proven healthy
                return out
            except RpcError as e:
                if e.code == -1 and node >= 0:
                    # unreachable: penalise so read balancing routes
                    # around it instead of rediscovering per request
                    with self._cache_lock:
                        self._faulty[node] = time.monotonic() + self.faulty_ttl
                if e.code not in (-1, 421, 503):
                    raise
                last = e
        raise last

    # -- adaptive hedged scatter (tail-latency tentpole) ---------------------

    def _hedge_note(self, event: str) -> None:
        self._m_hedges.inc(event)
        with self._hedge_lock:
            self.hedge_stats[event] += 1

    def _hedge_credit(self) -> None:
        """Every primary scatter RPC earns a fraction of a hedge token:
        sustained hedge volume can never exceed hedge_budget_pct of
        primary volume (plus the small burst the cap allows)."""
        with self._hedge_lock:
            self._hedge_tokens = min(
                self._hedge_token_cap,
                self._hedge_tokens + self.hedge_budget_pct / 100.0)

    def _hedge_debit(self) -> bool:
        with self._hedge_lock:
            if self._hedge_tokens >= 1.0:
                self._hedge_tokens -= 1.0
                return True
            return False

    def _hedge_delay_ms(self, skey: tuple[str, str],
                        pid: int) -> float | None:
        """The adaptive hedge delay for this partition, or None when
        hedging is ineligible: disabled, fewer than two live replicas
        to race, or too few samples to call anything a straggler. The
        delay is the partition's own observed tail — the configured
        quantile of its scatter sketch (node-level sketch as fallback),
        clamped to [hedge_min_delay_ms, hedge_max_delay_ms]."""
        if self.hedge_quantile <= 0.0:
            return None
        try:
            space = self._space(*skey)
            servers = self._servers()
        except RpcError:
            return None  # metadata unavailable: the plain path copes
        part = next((p for p in space.partitions if p.id == pid), None)
        if part is None or len(
                [r for r in part.replicas if r in servers]) < 2:
            return None
        from vearch_tpu.obs.quantiles import _qlabel

        snap = self.latency_quantiles.snapshot()
        lbl = _qlabel(self.hedge_quantile)
        for key in ((pid, "scatter"), ("_node", "scatter")):
            rec = snap.get(key)
            if rec and rec.get("count", 0) >= self.hedge_min_samples:
                q = float(rec["q"].get(lbl)
                          or rec["q"].get("0.95") or 0.0)
                return min(self.hedge_max_delay_ms,
                           max(self.hedge_min_delay_ms, q))
        return None

    def _scatter_call(self, skey: tuple[str, str], pid: int,
                      sub: dict, lb: str) -> dict:
        """One partition's search RPC with both tail defenses: adaptive
        hedging (second attempt on another replica once the RPC
        outlives the observed tail; first success wins, loser is
        killed) and the replica staleness guard (an answer whose
        apply_version predates a write this router already acknowledged
        is treated like a version-mismatched cache entry: discarded and
        re-fetched from the leader — read-your-writes holds under
        replica routing)."""
        with self._part_versions_lock:
            known = self._part_versions.get(pid, -1)
        delay_ms = self._hedge_delay_ms(skey, pid)
        if delay_ms is None:
            target: dict = {}
            r = self._call_partition(
                skey, pid, "/ps/doc/search", sub, lb,
                on_target=lambda n: target.update(n=n))
            r["_served_by"] = target.get("n")
            r["_hedge"] = "none"
        else:
            r = self._hedged_call(skey, pid, sub, lb, delay_ms)
        av = r.get("apply_version")
        if av is not None and int(av) < known:
            self._m_replica_refetch.inc()
            target = {}
            r2 = self._call_partition(
                skey, pid, "/ps/doc/search", sub, "leader",
                on_target=lambda n: target.update(n=n))
            r2["_served_by"] = target.get("n")
            r2["_hedge"] = r["_hedge"]
            return r2
        return r

    def _hedged_call(self, skey: tuple[str, str], pid: int, sub: dict,
                     lb: str, delay_ms: float) -> dict:
        """Race a primary attempt against a (budget-gated) hedge on a
        different replica. Both attempts share the request id (so an
        operator kill-by-rid still reaches them) but carry distinct
        _hedge_attempt markers, so cancelling the loser cannot kill
        sibling partition RPCs of the same fan-out. A kill-induced 499
        on the loser is discarded here — it never double-counts and
        never propagates once a winner exists."""
        import uuid

        rid = str(sub.get("request_id") or uuid.uuid4().hex)
        self._hedge_credit()  # primary volume feeds the budget
        done = threading.Event()
        lock = threading.Lock()
        box: dict = {"winner": None, "errors": {}, "pending": 1,
                     "nodes": {}}

        def run(slot: str, att: str, exclude: tuple) -> None:
            try:
                out = self._call_partition(
                    skey, pid, "/ps/doc/search",
                    # _hedge_extra marks the DUPLICATE attempt for the
                    # PS accountant: its device work bills honestly but
                    # the logical request meters once (the primary's)
                    {**sub, "request_id": rid, "_hedge_attempt": att,
                     **({"_hedge_extra": True} if slot == "hedge"
                        else {})},
                    lb, exclude=exclude,
                    on_target=lambda n: box["nodes"].__setitem__(slot, n),
                )
                with lock:
                    if box["winner"] is None:
                        box["winner"] = (slot, out)
            except RpcError as e:
                with lock:
                    box["errors"][slot] = e
            finally:
                with lock:
                    box["pending"] -= 1
                    finished = (box["winner"] is not None
                                or box["pending"] == 0)
                if finished:
                    done.set()

        att1, att2 = uuid.uuid4().hex, uuid.uuid4().hex
        threading.Thread(target=run, args=("primary", att1, ()),
                         name="router-scatter-primary",
                         daemon=True).start()
        fired = False
        if not done.wait(delay_ms / 1e3):
            if self._hedge_debit():
                fired = True
                self._hedge_note("fired")
                exclude = tuple(
                    n for n in (box["nodes"].get("primary"),)
                    if n is not None)
                with lock:
                    box["pending"] += 1
                threading.Thread(target=run, args=("hedge", att2, exclude),
                                 name="router-scatter-hedge",
                                 daemon=True).start()
            else:
                self._hedge_note("budget_denied")
        done.wait()
        with lock:
            winner = box["winner"]
            err = (box["errors"].get("primary")
                   or box["errors"].get("hedge"))
        if winner is None:
            raise err  # both attempts failed: the primary's error wins
        slot, out = winner
        out["_served_by"] = box["nodes"].get(slot)
        out["_hedge"] = ("hedge_won" if slot == "hedge"
                         else ("fired" if fired else "none"))
        if slot == "hedge":
            self._hedge_note("won")
        if fired:
            loser = "hedge" if slot == "primary" else "primary"
            self._cancel_attempt(rid, att2 if loser == "hedge" else att1,
                                 box["nodes"].get(loser))
        return out

    def _cancel_attempt(self, rid: str, att: str, node) -> None:
        """Fire-and-forget kill of a hedge loser, narrowed to its
        attempt id. A 404 means the loser already finished — nothing
        left to cancel, nothing to report."""
        if node is None:
            return

        def kill() -> None:
            try:
                srv = self._servers().get(node)
                if srv is None:
                    return
                # bounded best-effort: a cancel that cannot land in 5s
                # is not worth holding a thread for — the PS deadline
                # reaps the attempt anyway
                out = rpc.call(srv.rpc_addr, "POST", "/ps/kill",
                               {"request_id": rid, "attempt": att},
                               timeout=5.0)
                if out.get("killed"):
                    self._hedge_note("cancelled")
            except RpcError:
                pass

        threading.Thread(target=kill, name="router-hedge-cancel",
                         daemon=True).start()

    def _authenticate(self, headers, method, path) -> None:
        """BasicAuth via the master's /auth/check (positively cached 5s)
        plus per-endpoint privilege enforcement (reference: router
        doc_http.go:122 role.HasPermissionForResources — a 'read' user
        may search but not upsert/delete)."""
        from vearch_tpu.cluster.auth import has_permission, parse_basic_auth

        user, password = parse_basic_auth(headers)
        key = (user, password)
        now = time.monotonic()
        record = None
        with self._cache_lock:
            hit = self._auth_cache.get(key)
            if hit and hit[0] > now:
                record = hit[1]
        if record is None:
            record = rpc.call(self.master_addr, "POST", "/auth/check",
                              {"name": user, "password": password})
            with self._cache_lock:
                self._auth_cache[key] = (now + 5.0, record)
        has_permission(record.get("role", ""),
                       record.get("privileges") or {}, path, method)

    def _master_call(self, method: str, path: str, body=None,
                     timeout: float = 30.0):
        # metadata/admin calls get an explicit 30s bound: a wedged
        # master must fail serving-path metadata fetches fast enough
        # for the cached copy + failover retry to take over, not pin
        # request threads for the transport default
        return rpc.call(self.master_addr, method, path, body,
                        timeout=timeout, auth=self.master_auth)

    def _proxy_master(self, method: str, prefix: str):
        def h(body, parts):
            path = prefix + ("/" + "/".join(parts) if parts else "")
            if isinstance(body, dict) and body.get("_query"):
                # re-encode query params stripped by routing so e.g.
                # GET space?detail=true survives the proxy hop
                from urllib.parse import urlencode

                q = body.pop("_query")
                path += "?" + urlencode(q)
                body = body or None
            # proxied admin ops (space create, backup) keep the full
            # transport budget; only serving-path metadata is tight
            return self._master_call(method, path, body, timeout=120.0)

        return h

    def _h_cache_space(self, _body, parts) -> dict:
        """GET /cache/dbs/{db}/spaces/{space} — THIS router's cached
        view of the space (reference: doc_http.go:330 cacheSpaceInfo;
        ops use it to check router cache freshness vs the master)."""
        if len(parts) != 3 or parts[1] != "spaces":
            raise RpcError(404, "GET /cache/dbs/{db}/spaces/{space}")
        return self._space(parts[0], parts[2]).to_dict()

    def _h_health(self, _body, _parts) -> dict:
        return self._master_call("GET", "/")

    def _h_partition_rule(self, body, _parts) -> dict:
        out = self._master_call("POST", "/partitions/rule", body)
        # topology changed (groups added/dropped): serving from the TTL
        # cache would fan out to deleted partitions
        self._invalidate_caches()
        return out

    def _h_field_index(self, body, _parts) -> dict:
        out = self._master_call("POST", "/field_index", body)
        # schema changed (field gained/lost a scalar index): refresh so
        # filter planning sees the new index flags promptly
        self._invalidate_caches()
        return out

    # -- document routes -----------------------------------------------------

    def _partition_of_keys(self, space: Space, keys: list[str]) -> list[int]:
        """Vectorised murmur3(_id) -> slot -> partition id (reference:
        client.go:239 PartitionDocs). Hashing runs in the native module
        (numpy fallback); the slot binary search is one searchsorted."""
        import numpy as np

        from vearch_tpu import native

        slots = native.murmur3_batch(keys)
        starts = np.asarray(space.slot_starts(), dtype=np.uint64)
        idx = np.searchsorted(starts, slots.astype(np.uint64), side="right") - 1
        return [space.partitions[int(i)].id for i in idx]

    def _route_docs(
        self, space: Space, docs: list[dict]
    ) -> dict[int, list[dict]]:
        import uuid

        docs = [
            doc if "_id" in doc else {**doc, "_id": uuid.uuid4().hex}
            for doc in docs
        ]
        if space.partition_rule:
            return self._route_docs_by_rule(space, docs)
        pids = self._partition_of_keys(space, [str(d["_id"]) for d in docs])
        by_partition: dict[int, list[dict]] = {}
        if space.expanded:
            # after expansion a pre-expansion doc may live OFF its
            # re-carved slot; slot-routing its update would create a
            # second live copy (and 400 a partial update). Route each
            # existing _id to the partition that actually HOLDS it; only
            # genuinely new ids go to the slot owner.
            holders = self._find_holders(
                space, [str(d["_id"]) for d in docs])
            for doc, pid in zip(docs, pids):
                owner = holders.get(str(doc["_id"]), pid)
                by_partition.setdefault(owner, []).append(doc)
            return by_partition
        for doc, pid in zip(docs, pids):
            by_partition.setdefault(pid, []).append(doc)
        return by_partition

    def _find_holders(
        self, space: Space, keys: list[str]
    ) -> dict[str, int]:
        """{_id: partition_id} for ids that already exist somewhere in
        the space (expanded-space upsert routing). One parallel
        existence probe per PRE-expansion partition — only those can
        hold rows off their re-carved slot (rows written after the
        expansion are slot-routed correctly, so partitions created by
        the expansion never hold off-slot ids). Spaces from before this
        field existed probe every partition."""
        skey = (space.db_name, space.name)

        def probe(pid: int):
            out = self._call_partition(
                skey, pid, "/ps/doc/query",
                {"document_ids": keys, "fields": []})
            return pid, [d["_id"] for d in out["documents"]]

        probe_parts = space.partitions
        if space.pre_expand_pids:
            pre = set(space.pre_expand_pids)
            probe_parts = [p for p in space.partitions if p.id in pre]
        holders: dict[str, int] = {}
        futures = [self._pool.submit(probe, p.id)
                   for p in probe_parts]
        for f in futures:
            try:
                pid, found = f.result()
            except RpcError:
                # one unreachable partition must not take down writes
                # bound for healthy ones: ids it may hold fall back to
                # slot routing (worst case a duplicate copy that the
                # next holder-routed update or fan-out delete retires)
                continue
            for k in found:
                holders.setdefault(k, pid)
        return holders

    def _route_docs_by_rule(
        self, space: Space, docs: list[dict]
    ) -> dict[int, list[dict]]:
        """Range-rule routing: the rule field picks the range group, the
        murmur3(_id) slot picks the partition within the group
        (reference: space.go:198 PartitionIdsByRangeField + slot)."""
        import numpy as np

        from vearch_tpu import native
        from vearch_tpu.cluster.hashing import partition_for_slot

        field = space.partition_rule["field"]
        groups = space.rule_groups()
        bounds = space.rule_bounds()  # normalized once per request
        by_partition: dict[int, list[dict]] = {}
        slots = native.murmur3_batch([str(d["_id"]) for d in docs])
        for doc, slot in zip(docs, np.asarray(slots).tolist()):
            value = doc.get(field)
            if value is None:
                raise RpcError(
                    400, f"partition rule field {field!r} missing in doc "
                         f"{doc.get('_id')!r}"
                )
            try:
                gname = space.rule_group_for(value, bounds)
            except ValueError as e:
                raise RpcError(400, str(e)) from e
            parts = groups[gname]
            idx = partition_for_slot([p.slot for p in parts], int(slot))
            by_partition.setdefault(parts[idx].id, []).append(doc)
        return by_partition

    def _h_upsert(self, body: dict, _parts) -> dict:
        import uuid

        skey = (body["db_name"], body["space_name"])
        # ids are assigned BEFORE the moved-retry boundary: a retry
        # must re-route the SAME ids (minting fresh uuids on the rerun
        # would duplicate docs already written to healthy partitions)
        body["documents"] = [
            d if "_id" in d else {**d, "_id": uuid.uuid4().hex}
            for d in body["documents"]
        ]
        return self._retry_moved(skey, lambda: self._upsert_impl(body))

    def _upsert_impl(self, body: dict) -> dict:
        skey = (body["db_name"], body["space_name"])
        space = self._space(*skey)
        self._ensure_pool_capacity(len(space.partitions))
        self._validate_docs(space, body["documents"])
        by_partition = self._route_docs(space, body["documents"])

        from vearch_tpu.cluster.tracing import NULL_SPAN

        profile = bool(body.get("profile", False))
        # writes are orders of magnitude rarer than reads: a profiled
        # upsert always gets its span tree (the acceptance surface for
        # the write path), not just an explicitly traced one
        explicit_trace = bool(body.get("trace", False)) or profile
        sub = {"profile": profile}
        # write-side root span, symmetric with router.search: scatter
        # children carry _trace_ctx so each PS nests its ps.upsert (and
        # the raft propose/wal/commit/apply phases) under this tree
        root = (
            self.tracer.span(
                "router.upsert",
                tags={"db": skey[0], "space": skey[1],
                      "docs": len(body["documents"]),
                      "partitions": len(by_partition)},
            )
            if self.tracer.should_sample(explicit_trace)
            else NULL_SPAN
        )
        with root:
            def send(pid: int, docs: list[dict]):
                t0 = time.monotonic()
                if root.ctx() is not None:
                    span = self.tracer.span(
                        "router.scatter", ctx=root.ctx(),
                        tags={"partition": pid, "op": "upsert"},
                    )
                    body_p = {**sub, "documents": docs,
                              "_trace_ctx": span.ctx()}
                else:
                    span = NULL_SPAN
                    body_p = {**sub, "documents": docs}
                with span:
                    r = self._call_partition(skey, pid, "/ps/doc/upsert",
                                             body_p)
                # the write ack carries the apply version that covers
                # it — bumping the validity map HERE is what makes a
                # read-your-writes search through this router miss the
                # cache instead of serving pre-write results
                self._note_apply_version(pid, r.get("apply_version"))
                self._observe_map_version(skey, r.get("map_version"))
                r["_rpc_ms"] = round((time.monotonic() - t0) * 1e3, 3)
                return pid, r

            futures = [
                self._pool.submit(send, pid, docs)
                for pid, docs in by_partition.items()
            ]
            results = [f.result() for f in futures]
            t_merge = time.monotonic()
            keys: list[str] = []
            for _, r in results:
                keys.extend(r["keys"])
            out: dict = {"total": len(keys), "document_ids": keys}
            if root.trace_id:
                out["trace_id"] = root.trace_id
            if profile:
                # same merged shape as the search profile, so one
                # client-side renderer covers both paths
                out["profile"] = {
                    "partitions": {
                        str(pid): {"rpc_ms": r["_rpc_ms"],
                                   **(r.get("profile") or {})}
                        for pid, r in results
                    },
                    "merge_ms": round((time.monotonic() - t_merge) * 1e3, 3),
                    "partition_count": len(results),
                }
            return out

    def _h_slowlog(self, _body, _parts) -> dict:
        return {"threshold_ms": self.slowlog.threshold_ms,
                "entries": self.slowlog.entries()}

    def _validate_docs(self, space: Space, docs: list[dict]) -> None:
        """Schema validation at the router (reference: doc_parse.go —
        vector dims, unknown fields)."""
        vf = {f.name: f for f in space.schema.vector_fields()}
        known = {f.name for f in space.schema.fields} | {"_id"}
        for doc in docs:
            for name, f in vf.items():
                v = doc.get(name)
                if v is None:
                    if "_id" in doc:
                        # partial update: the engine inherits the stored
                        # vector of the doc this _id replaces (reference:
                        # upsert with has_vector=False updates scalars
                        # only) — and 400s if the _id is new
                        continue
                    raise RpcError(400, f"missing vector field {name!r}")
                if len(v) != f.wire_dim:
                    raise RpcError(
                        400,
                        f"vector field {name!r} length {len(v)} != "
                        f"expected {f.wire_dim}",
                    )
            for k in doc:
                if k not in known:
                    raise RpcError(400, f"unknown field {k!r}")

    def _parse_vectors(
        self, space: Space, body: dict
    ) -> tuple[dict[str, Any], dict[str, tuple]]:
        """reference: doc_query.go:165 parseSearch — `vectors` is a list of
        {field, feature} with feature a flattened batch. Parsed into
        [b, d] float32 arrays so the router->PS hop rides the binary
        tensor codec instead of JSON float lists. Returns (vectors,
        per-field (min_score, max_score) bounds)."""
        import numpy as np

        out: dict[str, Any] = {}
        bounds: dict[str, tuple] = {}
        nq = None
        for v in body.get("vectors", []):
            f = space.schema.field(v["field"])
            # lint: allow[host-sync] host-side wire-payload decode (JSON floats -> np), no device involved
            feat = np.asarray(v["feature"], dtype=np.float32).ravel()
            wd = max(f.wire_dim, 1)
            if feat.shape[0] % wd != 0:
                raise RpcError(
                    400,
                    f"feature length {feat.shape[0]} not divisible by "
                    f"dimension {wd}",
                )
            b = feat.shape[0] // wd
            if b == 0:
                # an empty feature would trace a 0-query batch through
                # the engine and answer [] — the reference 400s it
                # (test_document_search.py badcase "empty_vector")
                raise RpcError(400, f"empty feature for field "
                                    f"{v['field']!r}")
            if nq is None:
                nq = b
            elif nq != b:
                raise RpcError(400, "inconsistent query batch across fields")
            out[v["field"]] = feat.reshape(b, wd)
            if v.get("min_score") is not None or v.get("max_score") is not None:
                # score window per vector query (reference: min_score/
                # max_score in doc_query.go vector entries)
                bounds[v["field"]] = (v.get("min_score"), v.get("max_score"))
        if not out:
            raise RpcError(400, "search requires `vectors`")
        return out, bounds

    def _parse_sort_body(self, space: Space, body: dict,
                         allow_score: bool = True) -> list[dict]:
        """Normalize + validate a request's `sort` against the space
        schema (reference: doc_query.go:1329-1343 — unknown/vector sort
        fields are PARAM_ERRORs; sort fields are auto-added to the
        requested fields so their values come back)."""
        from vearch_tpu.engine.sort import (ID_FIELD, SCORE_FIELD,
                                            parse_sort, validate_sort)

        try:
            specs = parse_sort(body.get("sort"))
            validate_sort(
                specs,
                {f.name: f.data_type.value for f in space.schema.fields},
                allow_score=allow_score,
            )
        except ValueError as e:
            raise RpcError(400, str(e)) from e
        if specs and isinstance(body.get("fields"), list) and body["fields"]:
            # non-empty explicit projection: append missing sort fields
            # (reference: doc_query.go:1337-1339 queryReq.Fields append)
            have = set(body["fields"])
            for s in specs:
                f = s["field"]
                if f not in (ID_FIELD, SCORE_FIELD) and f not in have:
                    body["fields"] = body["fields"] + [f]
                    have.add(f)
        return specs

    @staticmethod
    def _page_window(body: dict, k: int) -> tuple[int, int]:
        """(start, size) of the global result window (reference:
        client.go:887-900 page_size/page_num slicing after the merge)."""
        size = int(body.get("page_size", 0) or 0)
        if size > 0:
            num = max(int(body.get("page_num", 1) or 1), 1)
            return size * (num - 1), size
        return 0, k

    def _h_search(self, body: dict, _parts) -> dict:
        t0 = time.monotonic()
        out: dict | None = None
        killed = False
        slo_bad = False
        try:
            out = self._retry_moved(
                (body["db_name"], body["space_name"]),
                lambda: self._search_impl(body))
            return out
        except RpcError as e:
            # a killed request (deadline/slow/operator) is terminal —
            # it still must leave a slowlog record at this role
            killed = e.code == ERR_REQUEST_KILLED
            # availability scoring: sheds, kills, and server faults
            # spend the error budget; client errors (bad names, parse
            # failures) do not
            slo_bad = e.code in (429, ERR_REQUEST_KILLED) or e.code >= 500
            raise
        finally:
            ms = (time.monotonic() - t0) * 1e3
            # one observation per logical request — the hedged second
            # attempt lives below this layer, so a won hedge scores
            # (and bills) exactly once
            self.slo.observe(
                f"{body.get('db_name')}/{body.get('space_name')}",
                ms, ok=not slo_bad)
            if self.slowlog.should_log(ms, killed=killed):
                entry = {
                    "op": "search",
                    "db_name": body.get("db_name"),
                    "space_name": body.get("space_name"),
                    "request_id": body.get("request_id"),
                    "elapsed_ms": round(ms, 3),
                    "killed": killed,
                }
                if out is not None:
                    if out.get("trace_id"):
                        entry["trace_id"] = out["trace_id"]
                    prof = out.get("profile")
                    if prof:
                        entry["partitions"] = prof.get("partitions")
                        entry["merge_ms"] = prof.get("merge_ms")
                self.slowlog.add(entry)

    def _search_impl(self, body: dict) -> dict:
        skey = (body["db_name"], body["space_name"])
        space = self._space(*skey)
        self._ensure_pool_capacity(len(space.partitions))
        vectors, score_bounds = self._parse_vectors(space, body)
        k = int(body.get("limit", body.get("topn", 10)))
        sort_specs = self._parse_sort_body(space, body)
        # pagination windows into the global top-k candidate set
        # (reference: AddMergeSort caps the merge at TopN, then
        # page_size/page_num slice within it — a window past k is empty)
        start, size = self._page_window(body, k)
        sub = {
            "vectors": vectors,
            "k": k,
            "score_bounds": score_bounds or None,
            # forwarded so /ps/kill can target queries by the id the
            # client supplied (reference: Rqueue kill by request id)
            "request_id": body.get("request_id"),
            # consistent reads bounce off lagging replicas (reference:
            # raft_consistent, client/client.go:1316-1360)
            "raft_consistent": bool(body.get("raft_consistent", False)),
            "filters": body.get("filters"),
            "include_fields": body.get("fields"),
            # explicit opt-in to the internal columnar result shape: a
            # version-skewed PS that ignores it just answers rows, and
            # an old router never sends it (the merge handles both).
            # Sorted requests need per-hit sort values -> row shape.
            "columnar_wire": body.get("fields") == [] and not sort_specs,
            "sort": sort_specs or None,
            "index_params": body.get("index_params") or {},
            "trace": bool(body.get("trace", False)),
            # profile=true: the PS returns its structured per-phase,
            # per-dispatch breakdown, merged below (the Elasticsearch-
            # profile / EXPLAIN analogue)
            "profile": bool(body.get("profile", False)),
            # per-request deadline: each PS arms RequestContext.kill
            # between dispatches; an expired request comes back as a
            # terminal request_killed error (never retried)
            "deadline_ms": body.get("deadline_ms"),
            "field_weights": {
                r["field"]: r["weight"]
                for r in body.get("ranker", {}).get("params", [])
            } if isinstance(body.get("ranker"), dict) else {},
            # per-request cache bypass (SDK `cache=False`): forwarded
            # so the PS-tier caches honor it too
            "cache": body.get("cache", True) is not False,
        }

        # replica_read flips the default read routing to the least-
        # loaded live replica; an explicit load_balance always wins
        lb = body.get("load_balance") or (
            "least_loaded" if self.replica_read else "leader")

        from vearch_tpu.cluster.tracing import NULL_SPAN

        explicit_trace = bool(body.get("trace", False))
        want_profile = bool(body.get("profile", False))
        root = (
            self.tracer.span(
                "router.search",
                tags={"db": skey[0], "space": skey[1], "k": k,
                      "batch": int(next(iter(vectors.values())).shape[0])},
            )
            if self.tracer.should_sample(explicit_trace)
            else NULL_SPAN
        )
        with root:
            if root.ctx() is not None:
                sub["trace"] = True  # sampled spans imply phase timings

            # merged-result cache: consistent reads must see the log
            # (raft_consistent), trace:true promises per-partition
            # timing that a hit cannot produce, and profile:true is a
            # measurement of the live fan-out path — serving any of
            # them a memoized envelope would be lying. All three fall
            # through to the scatter path. The entry validates against
            # the per-partition apply versions recorded when it was
            # computed.
            cacheable = (
                self.result_cache.max_entries > 0
                and sub["cache"]
                and not sub["raft_consistent"]
                and not explicit_trace
                and not want_profile
            )
            pids = [p.id for p in space.partitions]
            ckey = None
            if cacheable:
                from vearch_tpu.cluster.querycache import (
                    canonical_query_key,
                )

                ckey = canonical_query_key(
                    "/".join(skey), vectors, k, {
                        "filters": sub["filters"],
                        "include_fields": sub["include_fields"],
                        "columnar_wire": sub["columnar_wire"],
                        "columnar": bool(body.get("columnar")),
                        "sort": sub["sort"],
                        "index_params": sub["index_params"],
                        "score_bounds": sub["score_bounds"],
                        "field_weights": sub["field_weights"],
                        "page": [start, size],
                        "load_balance": lb,
                    },
                )
                with self._part_versions_lock:
                    cur = {
                        pid: self._part_versions.get(pid, -1)
                        for pid in pids
                    }
                ent = self.result_cache.get(ckey, cur)
                if ent is not None:
                    return self._cache_response(
                        ent, "hit", root, want_profile)
            elif not sub["cache"]:
                self.result_cache.note("bypass")

            def compute():
                out_core, results, merge_ms = self._search_scatter(
                    skey, space, body, sub, sort_specs, k, start,
                    size, lb, root,
                )
                if cacheable:
                    # the entry's validity map comes from the partial
                    # responses themselves — each PS stamped the apply
                    # version it answered AT (captured before its
                    # search ran, so a racing write labels the entry
                    # older, never fresher)
                    versions = {
                        pid: r.get("apply_version") for pid, r in results
                    }
                    if (set(versions) == set(pids)
                            and all(v is not None
                                    for v in versions.values())):
                        self.result_cache.put(
                            ckey,
                            {"out": out_core, "n": len(results)},
                            {p: int(v) for p, v in versions.items()},
                        )
                return out_core, results, merge_ms

            if cacheable:
                (out_core, results, merge_ms), coalesced = (
                    self._search_flight.do(ckey, compute)
                )
                if coalesced:
                    self.result_cache.note("coalesced")
                    return self._cache_response(
                        {"out": out_core, "n": len(results)},
                        "coalesced", root, want_profile)
                cache_status = "miss"
            else:
                out_core, results, merge_ms = compute()
                cache_status = (
                    "bypass" if not sub["cache"] else "uncacheable")

            out = dict(out_core)
            root.set_tag("cache", cache_status)
            if root.trace_id:
                # lets clients pull the span tree from /debug/traces on
                # each role (reference: Jaeger trace id in responses)
                out["trace_id"] = root.trace_id
            if explicit_trace:
                # per-partition timing breakdown (reference: trace:true
                # response params, client/client.go:521-565)
                out["params"] = {
                    str(pid): {"rpc_ms": r["_rpc_ms"], **r.get("timing", {})}
                    for pid, r in results
                }
            if want_profile:
                # router-merged explain surface: each partition's
                # structured phase/dispatch breakdown plus the router's
                # own scatter RTT and merge cost
                out["profile"] = {
                    "partitions": {
                        str(pid): {"rpc_ms": r["_rpc_ms"],
                                   "hedge": r.get("_hedge", "none"),
                                   "served_by": r.get("_served_by"),
                                   **(r.get("profile") or {})}
                        for pid, r in results
                    },
                    "merge_ms": merge_ms,
                    "partition_count": len(results),
                    "cache": cache_status,
                }
            return out

    def _cache_response(self, ent: dict, status: str, root,
                        want_profile: bool) -> dict:
        """Shape a served-from-cache (or coalesced) response: the core
        payload is shared with the entry, the envelope is fresh per
        caller. The profile says explicitly that no partition work
        happened for THIS response."""
        out = dict(ent["out"])
        root.set_tag("cache", status)
        if root.trace_id:
            out["trace_id"] = root.trace_id
        if want_profile:
            out["profile"] = {
                "cache": status,
                "partitions": {},
                "partition_count": ent["n"],
                "merge_ms": 0.0,
            }
        return out

    def _search_scatter(self, skey, space, body, sub, sort_specs, k,
                        start, size, lb, root):
        """One real fan-out + merge pass: every partition is queried
        and the partials merged. Returns (core response without the
        per-request envelope, [(pid, partial)], merge_ms) — the caller
        attaches trace_id/params/profile, and the cache stores only
        the core. Kept as a seam: the coalescing tests stall exactly
        this method to prove N callers share one scatter."""
        import time as _time

        from vearch_tpu.cluster.tracing import NULL_SPAN

        def timed(pid):
            t0 = _time.monotonic()
            if root.ctx() is not None:
                span = self.tracer.span(
                    "router.scatter", ctx=root.ctx(),
                    tags={"partition": pid},
                )
                body_p = {**sub, "_trace_ctx": span.ctx()}
            else:
                span, body_p = NULL_SPAN, sub
            with span:
                r = self._scatter_call(skey, pid, body_p, lb)
                span.set_tag("hedge", r.get("_hedge", "none"))
                if r.get("_served_by") is not None:
                    span.set_tag("served_by", r["_served_by"])
            # every partial carries the partition's apply version —
            # feed the router's validity map even on plain searches
            self._note_apply_version(pid, r.get("apply_version"))
            self._observe_map_version(skey, r.get("map_version"))
            r["_rpc_ms"] = round((_time.monotonic() - t0) * 1e3, 3)
            # tail-quantile sketches: per-partition for /router/stats,
            # node-level for the vearch_router_latency_quantile gauge
            self.latency_quantiles.observe((pid, "scatter"),
                                           r["_rpc_ms"])
            self.latency_quantiles.observe(("_node", "scatter"),
                                           r["_rpc_ms"])
            return pid, r

        futures = [
            self._pool.submit(timed, p.id) for p in space.partitions
        ]
        results = [f.result() for f in futures]
        partials = [r for _, r in results]
        t_merge = _time.monotonic()
        if sort_specs:
            merged = self._merge_search_sorted(
                partials, sort_specs, k, start, size)
        else:
            merged = self._merge_search(partials, k)
            # window slice within top-k (no-op without paging:
            # start=0, size=k)
            merged = [rows[start:start + size] for rows in merged]
        if body.get("columnar") and body.get("fields") == []:
            # opt-in columnar response: the client gets key lists +
            # ONE flat f32 score buffer over the binary codec
            # instead of b*k JSON dicts (the SDK reshapes, so its
            # return type is unchanged)
            import numpy as np

            out = {
                "columnar": True,
                "keys": [[r["_id"] for r in rows] for rows in merged],
                # lint: allow[host-sync] packs merged host floats for the columnar wire codec, no device involved
                "scores": np.asarray(
                    [r["_score"] for rows in merged for r in rows],
                    dtype=np.float32,
                ),
            }
        else:
            out = {"documents": merged}
        return out, results, round((_time.monotonic() - t_merge) * 1e3, 3)

    def _merge_search(
        self, partials: list[dict], k: int
    ) -> list[list[dict]]:
        """Top-k merge across partitions (reference: client.go:779 sorted
        merge). Scores are metric-oriented: L2 ascending, IP/cosine
        descending."""
        if not partials:
            return []
        metric = partials[0]["metric"]
        reverse = metric != "L2"
        n_columnar = sum(1 for p in partials if p.get("columnar"))
        if 0 < n_columnar < len(partials):
            # version-skewed mix (one PS answered columnar, another
            # rows): normalize columnar partials down to row form so
            # the merge below sees one shape
            partials = [
                self._rows_from_columnar(p) if p.get("columnar") else p
                for p in partials
            ]
        if n_columnar == len(partials):
            # fields-free fast path: merge on raw key/score arrays and
            # build ONLY the final top-k dicts for the client response
            import numpy as np

            nq = len(partials[0]["keys"])
            # scores arrive as one flat buffer per partition; per-query
            # slices are recovered from the key-list lengths and stay
            # numpy until only the final top-k becomes Python objects
            sliced = []
            for p in partials:
                # lint: allow[host-sync] wraps the wire-decoded score buffer (already host memory), no device involved
                flat = np.asarray(p["scores"])
                offs = np.cumsum([0] + [len(ks) for ks in p["keys"]])
                sliced.append([
                    flat[offs[i]:offs[i + 1]] for i in range(nq)
                ])
            out = []
            for qi in range(nq):
                keys: list[str] = []
                for p in partials:
                    keys.extend(p["keys"][qi])
                scores = np.concatenate([sc[qi] for sc in sliced])
                # stable on the NEGATED array for descending order:
                # reversing an ascending stable sort would invert tie
                # order vs the legacy dict-row merge
                order = np.argsort(-scores if reverse else scores,
                                   kind="stable")[:k]
                top = scores[order].tolist()
                out.append([
                    {"_id": keys[i], "_score": s}
                    for i, s in zip(order.tolist(), top)
                ])
            return out
        nq = len(partials[0]["results"])
        out = []
        for qi in range(nq):
            rows: list[dict] = []
            for p in partials:
                rows.extend(p["results"][qi])
            rows.sort(key=lambda r: r["_score"], reverse=reverse)
            out.append(rows[:k])
        return out

    def _merge_search_sorted(
        self, partials: list[dict], specs: list[dict],
        k: int, start: int, size: int,
    ) -> list[list[dict]]:
        """Cross-partition merge for sorted searches (reference:
        SearchFieldSortExecute client.go:779 + sortorder compare).
        Candidate selection stays SCORE-based — the global top-k by
        score, identical to an unsorted search — and the sort spec then
        reorders that set (the reference's AddMergeSort caps at topN by
        score before the final sort). Rows carry "_sort" values from the
        engine; ties break on metric-oriented score then _id, so the
        order is deterministic and independent of partition count."""
        if not partials:
            return []
        from vearch_tpu.engine.sort import row_sort_key

        metric = partials[0]["metric"]
        l2 = metric == "L2"
        partials = [
            self._rows_from_columnar(p) if p.get("columnar") else p
            for p in partials
        ]

        def values_of(row: dict):
            sv = row.get("_sort")
            if sv is not None:
                return sv
            # version-skewed PS without sort support: derive what we
            # can from the projected fields (score/_id always known)
            out = []
            for s in specs:
                f = s["field"]
                if f == "_score":
                    out.append(row.get("_score"))
                elif f == "_id":
                    out.append(row.get("_id"))
                else:
                    out.append(row.get(f))
            return out

        key = row_sort_key(
            specs, values_of,
            tie_key=lambda r: ((r["_score"] if l2 else -r["_score"]),
                               str(r.get("_id", ""))),
        )
        nq = len(partials[0]["results"])
        out = []
        for qi in range(nq):
            rows: list[dict] = []
            for p in partials:
                rows.extend(p["results"][qi])
            # 1) candidate set = global top-k by score (identical to an
            #    unsorted search)
            rows.sort(key=lambda r: r["_score"], reverse=not l2)
            rows = rows[:k]
            # 2) reorder candidates by the sort spec, 3) window slice
            rows.sort(key=key)
            out.append(rows[start:start + size])
        return out

    @staticmethod
    def _rows_from_columnar(p: dict) -> dict:
        """Expand a columnar search partial ({keys, scores} arrays) to
        the row form ({results: [[{_id,_score}]]}) the slow merge path
        consumes."""
        import numpy as np

        # lint: allow[host-sync] wraps the wire-decoded score buffer (already host memory), no device involved
        flat = np.asarray(p["scores"])
        offs = np.cumsum([0] + [len(ks) for ks in p["keys"]])
        results = [
            [{"_id": kk, "_score": ss}
             for kk, ss in zip(ks, flat[offs[i]:offs[i + 1]].tolist())]
            for i, ks in enumerate(p["keys"])
        ]
        out = {k_: v for k_, v in p.items()
               if k_ not in ("columnar", "keys", "scores")}
        out["results"] = results
        return out

    def _h_query(self, body: dict, _parts) -> dict:
        skey = (body["db_name"], body["space_name"])
        return self._retry_moved(skey, lambda: self._query_impl(body))

    def _query_impl(self, body: dict) -> dict:
        skey = (body["db_name"], body["space_name"])
        space = self._space(*skey)
        # parse/validate BEFORE branching so an invalid sort 400s on the
        # document_ids path too instead of being silently ignored
        sort_specs = self._parse_sort_body(space, body, allow_score=False)
        if body.get("document_ids"):
            keys_in = [str(k) for k in body["document_ids"]]
            # routing choices (reference: test_module_space.py
            # test_document_operation — partition_id targets one
            # partition, get_by_hash forces slot routing):
            # - explicit partition_id: only that partition
            # - rule spaces: owner depends on the rule field -> fan out
            # - expanded spaces: pre-expansion rows may live off their
            #   re-carved slot -> fan out (unless get_by_hash)
            if body.get("partition_id") is not None:
                pid = int(body["partition_id"])
                if pid not in {p.id for p in space.partitions}:
                    raise RpcError(404, f"partition {pid} not in space")
                by_partition = {pid: keys_in}
            elif space.partition_rule or (
                space.expanded and not body.get("get_by_hash")
            ):
                by_partition = {p.id: keys_in for p in space.partitions}
            else:
                by_partition: dict[int, list[str]] = {}
                for key, pid in zip(keys_in,
                                    self._partition_of_keys(space, keys_in)):
                    by_partition.setdefault(pid, []).append(key)

            lb = body.get("load_balance") or (
                "least_loaded" if self.replica_read else "leader")

            def send(pid: int, keys: list[str]):
                return self._call_partition(
                    skey, pid, "/ps/doc/query",
                    {"document_ids": keys, "fields": body.get("fields"),
                     "raft_consistent":
                         bool(body.get("raft_consistent", False)),
                     "vector_value": body.get("vector_value", False)}, lb)

            futures = [
                self._pool.submit(send, pid, keys)
                for pid, keys in by_partition.items()
            ]
            docs: list[dict] = []
            seen: set[str] = set()
            for f in futures:
                for d in f.result()["documents"]:
                    if d["_id"] not in seen:
                        seen.add(d["_id"])
                        docs.append(d)
            if sort_specs:
                # sort overrides the default request order (fetched
                # docs carry all fields unless projected, and sort
                # fields were auto-added to any non-empty projection)
                self._sort_docs(docs, sort_specs)
            return {"total": len(docs), "documents": docs}

        limit = int(body.get("limit", 50))
        offset = int(body.get("offset", 0))
        # page_size/page_num are sugar over offset/limit (reference:
        # QueryFieldSortExecute pagination, client.go:1135-1152)
        if int(body.get("page_size", 0) or 0) > 0:
            limit = int(body["page_size"])
            offset = limit * (max(int(body.get("page_num", 1) or 1), 1) - 1)

        # global pagination: every shard returns its first offset+limit
        # matches (offset 0), the union is ordered deterministically by
        # _id (or the sort spec), and the global [offset : offset+limit]
        # window is sliced here. Passing the client offset through to
        # each shard would skip `offset` docs *per shard* and return
        # partition-ordered pages (r1 VERDICT weak-7).
        def send_filter(pid: int):
            return self._call_partition(
                skey, pid, "/ps/doc/query",
                {"filters": body.get("filters"), "limit": offset + limit,
                 "offset": 0,
                 "fields": body.get("fields"),
                 "sort": sort_specs or None,
                 "raft_consistent": bool(body.get("raft_consistent", False)),
                 "vector_value": body.get("vector_value", False)},
                body.get("load_balance") or (
                    "least_loaded" if self.replica_read else "leader"))

        # explicit partition_id = a sampling read of ONE partition
        # (reference: doc_query.go query-by-partition — inspect a
        # shard's contents without ids)
        targets = space.partitions
        if body.get("partition_id") is not None:
            pid = int(body["partition_id"])
            by_id = {p.id: p for p in space.partitions}
            if pid not in by_id:
                raise RpcError(404, f"partition {pid} not in space")
            targets = [by_id[pid]]

        futures = [self._pool.submit(send_filter, p.id) for p in targets]
        docs = []
        for f in futures:
            docs.extend(f.result()["documents"])
        if sort_specs:
            self._sort_docs(docs, sort_specs)
        else:
            docs.sort(key=lambda d: str(d.get("_id", "")))
        page = docs[offset:offset + limit]
        return {"total": len(page), "documents": page}

    @staticmethod
    def _sort_docs(docs: list[dict], specs: list[dict]) -> None:
        """In-place doc order by the sort spec: engine-attached "_sort"
        values when present, field values otherwise; _id tie-break."""
        from vearch_tpu.engine.sort import row_sort_key

        def values_of(d: dict):
            sv = d.get("_sort")
            if sv is not None:
                return sv
            return [d.get("_id") if s["field"] == "_id"
                    else d.get(s["field"]) for s in specs]

        docs.sort(key=row_sort_key(
            specs, values_of,
            tie_key=lambda d: str(d.get("_id", ""))))

    def _h_delete(self, body: dict, _parts) -> dict:
        skey = (body["db_name"], body["space_name"])
        return self._retry_moved(skey, lambda: self._delete_impl(body))

    def _delete_impl(self, body: dict) -> dict:
        skey = (body["db_name"], body["space_name"])
        space = self._space(*skey)
        if body.get("document_ids"):
            keys_in = [str(k) for k in body["document_ids"]]
            # expanded spaces: stale copies may live off-slot — a delete
            # must reach every partition or resurrect via search results
            if space.partition_rule or space.expanded:
                by_partition = {p.id: keys_in for p in space.partitions}
            else:
                by_partition: dict[int, list[str]] = {}
                for key, pid in zip(keys_in,
                                    self._partition_of_keys(space, keys_in)):
                    by_partition.setdefault(pid, []).append(key)

            def send(pid: int, keys: list[str]):
                r = self._call_partition(skey, pid, "/ps/doc/delete",
                                         {"keys": keys})
                self._note_apply_version(pid, r.get("apply_version"))
                self._observe_map_version(skey, r.get("map_version"))
                return r

            futures = [
                self._pool.submit(send, pid, keys)
                for pid, keys in by_partition.items()
            ]
            return {"total": sum(f.result()["deleted"] for f in futures)}

        if body.get("limit") is not None:
            # explicit limit is a GLOBAL budget: walk partitions
            # sequentially, decrementing what remains (a parallel fan-out
            # would delete up to `limit` per shard). limit=0 deletes
            # nothing, by design — it is not "unbounded".
            remaining = int(body["limit"])
            total = 0
            for p in space.partitions:
                if remaining <= 0:
                    break
                out = self._call_partition(
                    skey, p.id, "/ps/doc/delete",
                    {"filters": body.get("filters"), "limit": remaining})
                self._note_apply_version(p.id, out.get("apply_version"))
                self._observe_map_version(skey, out.get("map_version"))
                total += out["deleted"]
                remaining -= out["deleted"]
            return {"total": total}

        def send_filter(pid: int):
            # no cap: the PS drains all matches
            r = self._call_partition(skey, pid, "/ps/doc/delete",
                                     {"filters": body.get("filters")})
            self._note_apply_version(pid, r.get("apply_version"))
            self._observe_map_version(skey, r.get("map_version"))
            return r

        futures = [self._pool.submit(send_filter, p.id) for p in space.partitions]
        return {"total": sum(f.result()["deleted"] for f in futures)}

    # -- index ops (reference: doc_http.go /index/{flush,forcemerge,rebuild})

    def _index_op(self, body: dict, ps_path: str) -> dict:
        skey = (body["db_name"], body["space_name"])
        space = self._space(*skey)

        def send(pid: int):
            return self._call_partition(skey, pid, ps_path, {})

        futures = [self._pool.submit(send, p.id) for p in space.partitions]
        return {"partitions": [f.result() for f in futures]}

    def _h_flush(self, body: dict, _parts) -> dict:
        return self._index_op(body, "/ps/flush")

    def _h_forcemerge(self, body: dict, _parts) -> dict:
        return self._index_op(body, "/ps/index/build")

    def _h_rebuild(self, body: dict, _parts) -> dict:
        return self._index_op(body, "/ps/index/rebuild")

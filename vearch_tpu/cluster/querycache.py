"""Query-result caching primitives: canonical keys, a version-exact
LRU, and single-flight coalescing.

These back the multi-tier serving cache (see docs/PERF.md "Tier 4"):

- the router keeps a :class:`VersionedLRUCache` of merged search
  responses, where each entry records the per-partition **apply
  version** (raft apply index) it was computed against — a write to
  any touched partition bumps that partition's version and the entry
  stops validating, so invalidation is exact (no TTL guessing, no
  blanket flush, read-your-writes holds);
- each PS keeps a per-partition result cache whose keys *embed* the
  apply version, so stale entries simply age out of the LRU;
- :class:`SingleFlight` coalesces N concurrent identical requests
  into one computation at both tiers (one dispatch set, N responses).

Everything here is pure data-structure code — no HTTP, no engine
imports — so bench.py and unit tests can exercise it standalone.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from typing import Any, Callable, Mapping

import numpy as np

from vearch_tpu.tools import lockcheck

__all__ = [
    "canonical_query_key",
    "VersionedLRUCache",
    "SingleFlight",
]


def canonical_query_key(
    space: str,
    vectors: Mapping[str, Any],
    k: int,
    options: Mapping[str, Any] | None = None,
) -> str:
    """Canonical cache key for a search request.

    Hashes the exact query-vector bytes (float32, [b, d] layout) per
    field plus a sorted-JSON rendering of every result-shaping option
    (k, filters, sort, include_fields, ...). Two requests share a key
    iff the engine would compute byte-identical results for them, so
    numeric jitter in a "similar" vector never aliases.
    """
    h = hashlib.sha256()
    h.update(space.encode())
    for name in sorted(vectors):
        # lint: allow[host-sync] canonicalises the (host) query payload for byte-exact hashing, no device involved
        arr = np.ascontiguousarray(np.asarray(vectors[name], np.float32))
        h.update(name.encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    h.update(b"|k=%d|" % int(k))
    if options:
        # default=str keeps unhashable leaves (e.g. np scalars in
        # score_bounds) from poisoning the key derivation
        h.update(
            json.dumps(options, sort_keys=True, default=str).encode()
        )
    return h.hexdigest()


@lockcheck.guarded
class VersionedLRUCache:
    """Thread-safe LRU whose entries validate against data versions.

    ``put(key, value, versions)`` records the version map the value was
    computed against (for the router: ``{partition_id: apply_version}``).
    ``get(key, current_versions)`` returns the value only when the
    recorded map **exactly equals** the current one — any partition
    that was written to since (version advanced), or any partition
    added/removed from the space (key-set mismatch), invalidates the
    entry. Invalidation is lazy: stale entries are dropped at lookup
    (counted under ``invalidated``) or evicted by LRU pressure.

    An optional ``ttl_s`` bounds entry age as a safety net for version
    signals the router might miss (e.g. a partition served by a replica
    it never heard from); version matching remains the primary gate.

    ``stats`` pre-initializes every event key so metrics callbacks can
    render the full label set from the first scrape (the cardinality
    soak asserts zero series growth after warmup).
    """

    EVENTS = ("hit", "miss", "invalidated", "eviction", "bypass",
              "coalesced")

    # stats is mutated in place under _lock too; declaring it catches a
    # future rebind (e.g. a reset that swaps the dict) done lock-free
    _guarded_by = {"_data": "_lock", "stats": "_lock"}

    def __init__(self, max_entries: int = 512, ttl_s: float = 0.0):
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self._lock = lockcheck.make_lock("querycache.lru")
        self._data: OrderedDict[str, tuple[Any, dict, float]] = (
            OrderedDict()
        )
        self.stats: dict[str, int] = {e: 0 for e in self.EVENTS}

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def note(self, event: str, by: int = 1) -> None:
        """Count an event that happens outside get/put (e.g. bypass,
        coalesced) so one stats dict carries the whole story."""
        with self._lock:
            self.stats[event] = self.stats.get(event, 0) + by

    def get(
        self,
        key: str,
        current_versions: Mapping[Any, int] | None = None,
        now: float | None = None,
    ) -> Any | None:
        import time as _time

        t = _time.monotonic() if now is None else now
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                self.stats["miss"] += 1
                return None
            value, versions, stamp = ent
            if self.ttl_s > 0 and (t - stamp) > self.ttl_s:
                del self._data[key]
                self.stats["invalidated"] += 1
                self.stats["miss"] += 1
                return None
            if (current_versions is not None
                    and dict(current_versions) != versions):
                # exact invalidation: some touched partition applied a
                # write (or the partition set changed) since this entry
                # was computed
                del self._data[key]
                self.stats["invalidated"] += 1
                self.stats["miss"] += 1
                return None
            self._data.move_to_end(key)
            self.stats["hit"] += 1
            return value

    def put(
        self,
        key: str,
        value: Any,
        versions: Mapping[Any, int] | None = None,
        now: float | None = None,
    ) -> None:
        import time as _time

        if self.max_entries <= 0:
            return
        t = _time.monotonic() if now is None else now
        with self._lock:
            self._data[key] = (value, dict(versions or {}), t)
            self._data.move_to_end(key)
            while len(self._data) > self.max_entries:
                self._data.popitem(last=False)
                self.stats["eviction"] += 1

    def evict_pids(self, pids) -> int:
        """Drop every entry whose recorded version map touches any of
        `pids` — the targeted invalidation for a partition-map remap
        (split cutover / migration): only results computed against a
        remapped partition are lost, the rest of the cache survives.
        Counted under ``invalidated``; returns how many were dropped."""
        doomed = set(pids)
        if not doomed:
            return 0
        with self._lock:
            stale = [k for k, (_, versions, _) in self._data.items()
                     if doomed & set(versions)]
            for k in stale:
                del self._data[k]
            self.stats["invalidated"] += len(stale)
            return len(stale)

    def clear(self) -> int:
        with self._lock:
            n = len(self._data)
            self._data.clear()
            return n


class _Flight:
    __slots__ = ("done", "value", "error", "waiters")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.value: Any = None
        self.error: BaseException | None = None
        self.waiters = 0


@lockcheck.guarded
class SingleFlight:
    """Coalesce concurrent calls with the same key into one execution.

    ``do(key, fn)`` returns ``(value, coalesced)`` — the first caller
    runs ``fn`` (the *leader*); callers arriving while it runs block
    and share the result with ``coalesced=True``. Errors propagate to
    every waiter and nothing is memoized: the flight is forgotten the
    moment the leader finishes, so a later call recomputes.
    """

    _guarded_by = {"_flights": "_lock"}

    def __init__(self, timeout_s: float = 30.0):
        self.timeout_s = float(timeout_s)
        self._lock = lockcheck.make_lock("querycache.singleflight")
        self._flights: dict[Any, _Flight] = {}

    def waiters(self, key: Any) -> int:
        """Blocked-follower count for `key` (tests use this to release
        a stalled leader only once all N callers have coalesced)."""
        with self._lock:
            f = self._flights.get(key)
            return f.waiters if f is not None else 0

    def do(
        self, key: Any, fn: Callable[[], Any]
    ) -> tuple[Any, bool]:
        with self._lock:
            flight = self._flights.get(key)
            if flight is not None:
                flight.waiters += 1
                leader = False
            else:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
        if not leader:
            ok = flight.done.wait(self.timeout_s)
            if not ok:
                raise TimeoutError(
                    f"single-flight wait for {key!r} exceeded "
                    f"{self.timeout_s}s"
                )
            if flight.error is not None:
                raise flight.error
            return flight.value, True
        try:
            flight.value = fn()
        except BaseException as e:
            flight.error = e
            raise
        finally:
            with self._lock:
                self._flights.pop(key, None)
            flight.done.set()
        return flight.value, False

"""Per-partition replicated log (leader/follower state machine).

TPU-native analogue of the reference's raftstore (reference:
internal/ps/storage/raftstore/store.go:70 CreateStore,
store_writer.go:77 quorum write proposals, raft_state_machine.go:92
Apply on every replica, gammacb/snapshot.go:26 snapshot-as-file-stream).

Design differences from textbook raft, on purpose:
- **Leadership is master-arbitrated, not voted.** The metadata plane
  (master) is the single config authority, like the reference's etcd.
  Promotion is fencing-based: the master bumps the partition term on
  every alive replica FIRST (after which stale-term appends are
  rejected, so a deposed leader can no longer commit), then appoints
  the replica with the max (last_term, last_index) log. This trades
  raft's partition-tolerant election for a simpler protocol with the
  same no-acked-write-lost guarantee under fail-stop failures.
- **Commit is count-based across terms.** Safe here because fencing
  guarantees no older-term leader can assemble a quorum after a
  promotion.
- Membership changes are master-decreed (reference: ChangeMember RPC,
  ps/handler_admin.go:329) and fence through a term bump.

Everything else is the classic algorithm: append-only WAL, quorum ack
before the client ack, follower conflict truncation, next_index backoff
catch-up, log-compaction behind flush with snapshot install for
followers that fell behind the truncation horizon.
"""

from __future__ import annotations

import base64
import threading
import time
from typing import Any, Callable

import numpy as np

from vearch_tpu.cluster.metrics import internal_error
from vearch_tpu.cluster.rpc import RpcError
from vearch_tpu.cluster.wal import Wal
from vearch_tpu.tools import lockcheck

SNAP_CHUNK = 4 << 20  # 4 MB per snapshot chunk (reference streams 10MB)


@lockcheck.guarded
class RaftNode:
    """One replica of one partition's replicated log."""

    # lock discipline (lint VL201 + runtime lockcheck): every
    # term/commit/membership decision and all leader-side replication
    # state mutates only under _lock. Methods whose callers all hold it
    # carry a `# lint: holds[_lock]` claim, verified at runtime when
    # VEARCH_LOCKCHECK=1.
    _guarded_by = {
        "_match": "_lock",
        "_next": "_lock",
        "_peer_commit": "_lock",
        "_last_peer_ack": "_lock",
        "_snap_in": "_lock",
        "_resync_pending": "_lock",
        "_peer_locks": "_lock",
        "_apply_results": "_lock",
        "applied": "_lock",
        "is_leader": "_lock",
        "members": "_lock",
        "learners": "_lock",
        "leader_hint": "_lock",
        "_last_leader_contact": "_lock",
        "_election_jitter": "_lock",
        "_stopped": "_lock",
        "snapshots_sent": "_lock",
        "snapshots_installed": "_lock",
        "elections_started": "_lock",
        "elections_won": "_lock",
        "heartbeats_acked": "_lock",
    }

    def __init__(
        self,
        pid: int,
        node_id: int,
        wal_dir: str,
        apply_fn: Callable[[dict], Any],
        send_fn: Callable[[int, str, dict], dict],
        members: list[int],
        is_leader: bool,
        snapshot_fn: Callable[[], tuple[bytes, int]] | None = None,
        install_fn: Callable[[bytes, int], None] | None = None,
        quorum_timeout: float = 10.0,
        election_timeout: float | None = None,
        route_prefix: str = "/ps/raft",
        observer: Callable[[str, dict], None] | None = None,
        learners: list[int] | None = None,
    ):
        self.pid = pid
        self.node_id = node_id
        self.wal = Wal(wal_dir)
        self.apply_fn = apply_fn
        self.send_fn = send_fn
        self.snapshot_fn = snapshot_fn
        self.install_fn = install_fn
        self.quorum_timeout = quorum_timeout
        self.route_prefix = route_prefix

        self.members = list(members) if members else [node_id]
        # non-voting replication targets (replica migration catch-up):
        # they receive appends/snapshots and report lag in state(), but
        # never count toward quorum() / _advance_commit and never
        # campaign (election_tick's membership guard covers them)
        self.learners = list(learners or [])
        self.is_leader = bool(is_leader)
        self.applied = 0  # set by recovery before serving
        self._apply_results: dict[int, Any] = {}

        # protects term/commit/log decisions
        self._lock = lockcheck.make_lock("raft._lock", reentrant=True)
        # serialises state-machine applies
        self._apply_lock = lockcheck.make_lock("raft._apply_lock")
        # one in-flight proposal batch
        self._propose_lock = lockcheck.make_lock("raft._propose_lock")
        self._peer_locks: dict[int, Any] = {}
        self._match: dict[int, int] = {}  # peer -> highest replicated index
        self._next: dict[int, int] = {}  # peer -> next index to send
        self._commit_cv = threading.Condition(self._lock)
        self._stopped = False

        # incoming snapshot staging: sid -> {chunks, snap_index, term}
        self._snap_in: dict[str, dict] = {}
        # observability (parity checks + tests assert the catch-up path)
        self.snapshots_sent = 0
        self.snapshots_installed = 0
        self.elections_started = 0
        self.elections_won = 0
        self.heartbeats_acked = 0  # successful append responses sent out
        # event sink for the hosting PS (metrics histograms + trace
        # spans). Called OUTSIDE the propose path's critical section for
        # latency events, but may fire under self._lock for rare state
        # transitions — the observer must be cheap, non-blocking, and
        # must never call back into this node.
        self._observer = observer
        # leader-side per-peer liveness: last successful append/snapshot
        # ack, and the highest commit index the peer has been TOLD about
        # (a follower that has every entry but a stale commit index is
        # still lagging — it hasn't applied)
        self._last_peer_ack: dict[int, float] = {}
        self._peer_commit: dict[int, int] = {}
        # missed-wakeup guard (VERDICT weak #2): a sync requested while
        # another sync to the same peer is in flight must not be lost —
        # the in-flight holder re-probes before releasing the peer lock
        self._resync_pending: set[int] = set()

        # -- voted election mode (metadata groups; data partitions keep
        # master-arbitrated fencing). Standard raft: randomized timeout,
        # vote restriction (candidate log must be >= voter's), commit
        # only entries of the current term by counting (a no-op entry
        # appended on election carries prior-term entries).
        self.election_timeout = election_timeout
        # monotonic clock: ack ages and election quiet-times are
        # durations, which an NTP step must not bend
        self._born = time.monotonic()  # baseline for ack ages
        self._last_leader_contact = time.monotonic()
        self.leader_hint: int | None = node_id if is_leader else None
        import random

        self._election_jitter = random.uniform(0.8, 1.6)

    # -- properties ----------------------------------------------------------

    @property
    def term(self) -> int:
        return self.wal.term

    @property
    def commit(self) -> int:
        return self.wal.commit_index

    def quorum(self) -> int:
        # voters only: learners never change the commit arithmetic
        return len(self.members) // 2 + 1

    def _peers(self) -> list[int]:
        """Replication targets: voters + learners, minus self (commit
        counting stays voters-only — see _advance_commit)."""
        out = [m for m in self.members if m != self.node_id]
        out += [l for l in self.learners
                if l != self.node_id and l not in self.members]
        return out

    def _observe(self, event: str, info: dict) -> None:
        if self._observer is None:
            return
        try:
            self._observer(event, info)
        except Exception as e:
            # observability must never fail the protocol — but a broken
            # observer must not fail silently either
            internal_error("raft.observer", e)

    def replication_lag(self) -> dict[int, int]:
        """Per-peer entries behind the leader's log end (leader view).
        A peer at lag 0 holds every entry; whether it has APPLIED them
        rides the commit index, tracked separately in state()."""
        with self._lock:
            last = self.wal.last_index
            return {
                p: max(0, last - self._match.get(p, 0))
                for p in self._peers()
            }

    def heartbeat_age(self) -> float:
        """Seconds since this node last saw proof of a live replication
        channel: for a leader, the OLDEST peer ack (worst case across
        followers); for a follower, the last leader contact."""
        now = time.monotonic()
        with self._lock:
            if self.is_leader:
                peers = [m for m in self.members if m != self.node_id]
                if not peers:
                    return 0.0
                return max(
                    now - self._last_peer_ack.get(p, self._born) for p in peers
                )
            return now - self._last_leader_contact

    def state(self) -> dict:
        with self._lock:
            now = time.monotonic()
            last = self.wal.last_index
            peers = {
                str(p): {
                    "next": self._next.get(p, last + 1),
                    "match": self._match.get(p, 0),
                    "lag": max(0, last - self._match.get(p, 0)),
                    "ack_age": round(
                        now - self._last_peer_ack.get(p, self._born), 3
                    ),
                }
                for p in self._peers()
            } if self.is_leader else {}
            return {
                "pid": self.pid,
                "node_id": self.node_id,
                "term": self.term,
                "last_index": last,
                "last_term": self.wal.last_term,
                "commit": self.commit,
                "applied": self.applied,
                "is_leader": self.is_leader,
                "leader_hint": self.node_id if self.is_leader
                else self.leader_hint,
                "members": list(self.members),
                "learners": list(self.learners),
                "snapshots_sent": self.snapshots_sent,
                "snapshots_installed": self.snapshots_installed,
                "elections_started": self.elections_started,
                "elections_won": self.elections_won,
                "peers": peers,
            }

    # -- leader: propose + replicate -----------------------------------------

    def propose(self, ops: list[dict],
                timing: dict | None = None) -> list[Any]:
        """Append ops, replicate to a quorum, commit, apply. Returns the
        apply results in op order. Raises 421 when not leader, 503 when
        a quorum cannot be assembled in time (the entries stay in the
        log and may commit later — at-least-once, ops are idempotent).

        When `timing` is a dict the per-phase wall windows land in it
        (`propose_wait_ms` / `wal_append_ms` / `commit_wait_ms` /
        `apply_ms` / `total_ms` + `_phase_spans` rows) — the write-side
        analogue of the engine's trace dict, replayed by the PS as child
        spans under ps.upsert / ps.delete."""
        t_enter = time.monotonic()
        # one wall reading anchors the span epochs; every phase window
        # is measured monotonically and offset from it (an NTP step
        # mid-proposal must not corrupt the durations)
        wall0 = time.time() - t_enter  # lint: allow[wall-clock] span epoch anchor, correlates with collector time
        with self._propose_lock:
            # serialized proposals queue on _propose_lock: the wait here
            # is the write-side analogue of the search gate wait
            t_lock = time.monotonic()
            with self._lock:
                if self._stopped:
                    raise RpcError(503, f"partition {self.pid}: stopped")
                if not self.is_leader:
                    raise RpcError(421, f"partition {self.pid}: not leader")
                term = self.term
                start = self.wal.last_index + 1
                entries = [
                    {"index": start + i, "term": term, "op": op}
                    for i, op in enumerate(ops)
                ]
                t_wal = time.monotonic()
                self.wal.append(entries, fsync=True)
                t_append = time.monotonic()
                target = entries[-1]["index"]
            self._replicate_and_wait(target)
            with self._lock:
                if self.commit < target:
                    raise RpcError(
                        503,
                        f"partition {self.pid}: no quorum for index "
                        f"{target} within {self.quorum_timeout}s",
                    )
            t_commit = time.monotonic()
            # append -> quorum-commit wall time (the replication RTT the
            # client write waited for)
            self._observe("commit", {
                "seconds": t_commit - t_append, "index": target,
                "entries": len(entries),
            })
            self._apply_to_commit()
            t_apply = time.monotonic()
            # push the advanced commit index to followers synchronously
            # so they apply before the client sees the ack — follower
            # reads (load_balance random/not_leader) then serve the
            # write immediately, matching the reference's synchronous
            # replica visibility expectations. Best-effort: a straggler
            # catches up on the next tick.
            self._notify_commit()
            if timing is not None:
                spans = []
                spans.append(["raft.propose_wait",
                              int((wall0 + t_enter) * 1e6),
                              int((t_lock - t_enter) * 1e6)])
                spans.append(["wal.append", int((wall0 + t_wal) * 1e6),
                              int((t_append - t_wal) * 1e6)])
                spans.append(["raft.commit_wait",
                              int((wall0 + t_append) * 1e6),
                              int((t_commit - t_append) * 1e6)])
                spans.append(["engine.apply",
                              int((wall0 + t_commit) * 1e6),
                              int((t_apply - t_commit) * 1e6)])
                timing["propose_wait_ms"] = round(
                    (t_lock - t_enter) * 1e3, 3)
                timing["wal_append_ms"] = round(
                    (t_append - t_wal) * 1e3, 3)
                timing["commit_wait_ms"] = round(
                    (t_commit - t_append) * 1e3, 3)
                timing["apply_ms"] = round((t_apply - t_commit) * 1e3, 3)
                timing["total_ms"] = round(
                    (time.monotonic() - t_enter) * 1e3, 3)
                timing["entries"] = len(entries)
                timing["_phase_spans"] = spans
            with self._lock:
                return [self._apply_results[e["index"]] for e in entries]

    def _replicate_and_wait(self, target: int) -> None:
        peers = self._peers()
        if not peers:  # single-replica group: commit == append
            self._advance_commit()
            return
        if all(p not in self.members for p in peers):
            # learners only (single-voter group mid-migration): the
            # voter quorum is already satisfied by the local append
            self._advance_commit()
        for p in peers:
            t = threading.Thread(
                target=self._sync_peer, args=(p,), daemon=True,
                name=f"raft-repl-p{self.pid}-{p}",
            )
            t.start()
        # monotonic deadline: an NTP step mid-wait must not stretch or
        # collapse the quorum window (lock-fix note: was wall-clock)
        deadline = time.monotonic() + self.quorum_timeout
        with self._commit_cv:
            while self.commit < target and time.monotonic() < deadline:
                self._commit_cv.wait(timeout=0.05)

    def _sync_peer(self, peer: int, blocking: bool = False) -> None:
        """Bring one follower up to date (serialised per peer: append
        order to a given follower must be monotonic).

        Missed-wakeup fix (VERDICT weak #2): the old non-blocking path
        silently DROPPED a sync request when another sync to the same
        peer held the lock. Under CPU contention the holder could be
        descheduled for seconds while every heartbeat tick's retry was
        discarded at this early-return — a follower one entry (or one
        commit-index update) behind then stayed behind until the next
        proposal. Now a contended request parks in _resync_pending and
        the holder re-probes before releasing, so a requested sync is
        never lost."""
        # lock-fix note: _peer_locks was populated via bare setdefault
        # from concurrent sync threads — now created under _lock (and
        # through make_lock so lockcheck sees the per-peer ordering)
        with self._lock:
            lock = self._peer_locks.setdefault(
                peer, lockcheck.make_lock(f"raft.peer{peer}"))
        if not lock.acquire(blocking=blocking):
            # lock-fix note: _resync_pending is a plain set; its
            # add/discard/probe now all run under _lock (peer_lock ->
            # _lock is the established order, so no inversion)
            with self._lock:
                self._resync_pending.add(peer)
            # the holder may have checked the flag just before we set
            # it; retry the handoff if the lock is now free
            if not lock.acquire(blocking=False):
                return
        try:
            while True:
                with self._lock:
                    self._resync_pending.discard(peer)
                self._sync_peer_locked(peer)
                with self._lock:
                    if peer not in self._resync_pending or self._stopped:
                        return
        finally:
            lock.release()

    def _notify_commit(self) -> None:
        peers = self._peers()
        threads = [
            threading.Thread(target=self._sync_peer, args=(p, True),
                             daemon=True,
                             name=f"raft-commit-p{self.pid}-{p}")
            for p in peers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)

    def _sync_peer_locked(self, peer: int) -> None:
        backoff_probes = 0
        snap_sends = 0
        while not self._stopped:
            with self._lock:
                if not self.is_leader:
                    return
                term = self.term
                ni = self._next.get(peer, self.wal.last_index + 1)
                prev = ni - 1
                # term_at answers at the compaction horizon too (the
                # WAL persists horizon_term), so appends starting
                # exactly at our snapshot horizon carry a REAL
                # prev_term the follower can verify — index-only
                # matching there would let a follower keep a divergent
                # uncommitted entry at that index (Log Matching
                # violation)
                prev_term = self.wal.term_at(prev)
                if prev_term is None and prev == self.wal.first_index - 1 \
                        and prev <= self.applied:
                    # prev is OUR horizon but its term is unknown
                    # (legacy meta / restored state). Snapshotting here
                    # would loop forever — each install resets the
                    # follower to this same unknowable horizon — so send
                    # the sentinel. The FOLLOWER side is what makes this
                    # safe: it index-matches -1 only when its own prev
                    # is absent or committed, and nacks (never
                    # truncates) an uncommitted local entry there, which
                    # walks prev back until a real term or a genuine
                    # behind-horizon snapshot resolves it.
                    prev_term = -1
                commit = self.commit
                entries = self.wal.entries_from(ni) if prev_term is not None \
                    else []
            if prev_term is None:
                # the entry before next_index was compacted away: the
                # follower is genuinely behind the log horizon -> full
                # snapshot (reference: gammacb/snapshot.go file stream).
                # Safety valve: a snapshot must advance the follower; if
                # repeated installs don't, stop this round rather than
                # livelock re-streaming (the next tick retries).
                snap_sends += 1
                if snap_sends > 3 or not self._send_snapshot(peer, term):
                    return
                continue
            try:
                resp = self.send_fn(peer, f"{self.route_prefix}/append", {
                    "pid": self.pid, "term": term, "leader": self.node_id,
                    "prev_index": prev, "prev_term": prev_term,
                    "entries": entries, "commit": commit,
                })
            except RpcError:
                return  # peer unreachable; next tick retries
            with self._lock:
                if resp.get("term", 0) > self.term:
                    self._step_down(resp["term"])
                    return
                if resp.get("success"):
                    sent_last = entries[-1]["index"] if entries else prev
                    self._match[peer] = max(
                        self._match.get(peer, 0), sent_last
                    )
                    self._next[peer] = sent_last + 1
                    self._last_peer_ack[peer] = time.monotonic()
                    self.heartbeats_acked += 1
                    # the follower adopted min(commit we sent, its log
                    # end) — remember it so the heartbeat keeps probing
                    # until the peer has both every ENTRY and the
                    # current COMMIT index (a peer with a stale commit
                    # hasn't applied: it is still lagging even at
                    # match == last_index)
                    self._peer_commit[peer] = max(
                        self._peer_commit.get(peer, 0),
                        min(commit, sent_last),
                    )
                    self._advance_commit()
                    if (self._next[peer] > self.wal.last_index
                            and self._peer_commit[peer] >= self.commit):
                        return
                else:
                    # follower nack: jump next_index to its log end + 1
                    hint = int(resp.get("last_index", prev - 1))
                    self._next[peer] = min(max(hint + 1, 1), prev)
                    backoff_probes += 1
                    if backoff_probes > 10_000:
                        return

    def _advance_commit(self) -> None:
        with self._lock:
            if not self.is_leader:
                return
            indices = sorted(
                [self.wal.last_index]
                + [self._match.get(p, 0)
                   for p in self.members if p != self.node_id],
                reverse=True,
            )
            candidate = indices[self.quorum() - 1]
            if candidate <= self.commit:
                return
            if self.election_timeout is not None:
                # voted mode: only count-commit entries of the current
                # term (raft §5.4.2); the post-election no-op makes
                # earlier entries commit transitively
                t = self.wal.term_at(candidate)
                if t is not None and t != self.term:
                    return
            self.wal.commit_index = candidate
            self.wal.save_meta()
            self._commit_cv.notify_all()

    def _send_snapshot(self, peer: int, term: int) -> bool:
        if self.snapshot_fn is None:
            return False
        data, snap_index = self.snapshot_fn()
        # term of the snapshot's last included entry — becomes the
        # follower's horizon term so its subsequent appends at the
        # horizon are term-verifiable
        snap_term = self.wal.term_at(snap_index)
        sid = f"{self.node_id}-{time.time_ns()}"
        try:
            for off in range(0, max(len(data), 1), SNAP_CHUNK):
                chunk = data[off : off + SNAP_CHUNK]
                resp = self.send_fn(peer, f"{self.route_prefix}/snapshot", {
                    "pid": self.pid, "term": term, "sid": sid,
                    "snap_index": snap_index, "snap_term": snap_term,
                    "off": off, "total": len(data),
                    # raw bytes over the binary tensor codec (the
                    # reference streams raw 10MB chunks too)
                    "data": np.frombuffer(chunk, dtype=np.uint8),
                    "done": off + SNAP_CHUNK >= len(data),
                })
                if not resp.get("success"):
                    return False
        except RpcError:
            return False
        # a stale:true final chunk means the follower already advanced
        # past snap_index via appends — rewinding next_index to
        # snap_index+1 would re-send entries it already has (and its
        # reported last_index is the real resync point)
        peer_last = snap_index
        if resp.get("stale"):
            peer_last = max(snap_index, int(resp.get("last_index",
                                                     snap_index)))
        with self._lock:
            self._match[peer] = max(self._match.get(peer, 0), peer_last)
            self._next[peer] = peer_last + 1
            self._last_peer_ack[peer] = time.monotonic()
            self.snapshots_sent += 1
            self._advance_commit()
        self._observe("snapshot_sent", {
            "peer": peer, "snap_index": snap_index, "bytes": len(data),
        })
        return True

    def tick(self) -> None:
        """Leader heartbeat: push commit index and catch up any lagging
        follower (reference: raft heartbeat + replicate transport)."""
        with self._lock:
            if not self.is_leader or self._stopped:
                return
            peers = self._peers()
        for p in peers:
            threading.Thread(
                target=self._sync_peer, args=(p,), daemon=True,
                name=f"raft-tick-p{self.pid}-{p}",
            ).start()

    # -- apply ---------------------------------------------------------------

    def _apply_to_commit(self) -> dict[int, Any]:
        """Apply committed-but-unapplied entries in index order. Returns
        {index: result} for entries applied by this call."""
        out: dict[int, Any] = {}
        with self._apply_lock:
            while True:
                with self._lock:
                    nxt = self.applied + 1
                    if nxt > self.commit:
                        break
                    e = self.wal.get(nxt)
                if e is None:
                    break  # compacted (snapshot already covers it)
                t_apply = time.monotonic()
                result = self.apply_fn(e["op"])
                self._observe("apply", {
                    "seconds": time.monotonic() - t_apply, "index": nxt,
                })
                out[nxt] = result
                with self._lock:
                    self.applied = nxt
                    # keep a bounded window of recent results: a propose
                    # whose entries were applied by a concurrent path
                    # (master decree, follower append) still needs them
                    self._apply_results[nxt] = result
                    stale = nxt - 4096
                    if stale in self._apply_results:
                        self._apply_results.pop(stale, None)
        return out

    # -- follower: append / fence / snapshot ---------------------------------

    def handle_append(self, body: dict) -> dict:
        with self._lock:
            term = int(body["term"])
            if term < self.term:
                return {"success": False, "term": self.term,
                        "last_index": self.wal.last_index}
            if term == self.term and self.is_leader:
                # two leaders in one term cannot happen under master
                # arbitration; refuse rather than silently abdicating
                # (the master's next term bump resolves the conflict)
                return {"success": False, "term": self.term,
                        "last_index": self.wal.last_index}
            if term > self.term:
                self._step_down(term)
            self._last_leader_contact = time.monotonic()
            self.leader_hint = int(body.get("leader", -1))
            prev_i = int(body["prev_index"])
            prev_t = int(body["prev_term"])
            local_t = self.wal.term_at(prev_i)
            if local_t is None:
                if prev_i <= self.applied:
                    # prev entry was compacted behind our snapshot: it is
                    # covered, treat as matching
                    pass
                else:
                    return {"success": False, "term": self.term,
                            "last_index": self.wal.last_index}
            elif prev_t == -1:
                # leader horizon sentinel (its prev term is unknowable).
                # Index-match ONLY what is safe:
                # - our entry at prev is committed -> identical to the
                #   leader's committed history by raft safety, pass;
                # - our entry is UNCOMMITTED -> it may diverge (advisor
                #   r4: index-matching here is a Log Matching
                #   violation). Nack with our commit index as the hint
                #   so the leader walks prev back to term-verifiable
                #   ground (or a real snapshot) — and never truncate
                #   here: the entry might equally be a valid tail.
                if prev_i > self.commit:
                    return {"success": False, "term": self.term,
                            "last_index": self.commit}
            elif local_t != prev_t:
                self.wal.truncate_suffix(prev_i)
                return {"success": False, "term": self.term,
                        "last_index": self.wal.last_index}
            new = []
            for e in body.get("entries", []):
                have = self.wal.term_at(e["index"])
                if have is None and e["index"] > self.wal.last_index:
                    new.append(e)
                elif have is not None and have != e["term"]:
                    self.wal.truncate_suffix(e["index"])
                    new.append(e)
                # else: already have it (duplicate delivery)
            # drop entries that precede our snapshot horizon entirely
            new = [e for e in new if e["index"] > self.applied]
            if new:
                start = new[0]["index"]
                if start <= self.wal.last_index:
                    self.wal.truncate_suffix(start)
                self.wal.append(new, fsync=True)
            commit = min(int(body["commit"]), self.wal.last_index)
            if commit > self.commit:
                self.wal.commit_index = commit
                self.wal.save_meta()
        self._apply_to_commit()
        with self._lock:
            return {"success": True, "term": self.term,
                    "last_index": self.wal.last_index}

    # -- voted elections (metadata groups) -----------------------------------

    def election_tick(self) -> None:
        """Owner calls this periodically (~timeout/3). Follower whose
        leader went quiet past the (jittered) timeout campaigns."""
        if self.election_timeout is None:
            return
        with self._lock:
            if self.is_leader or self._stopped:
                return
            if self.node_id not in self.members:
                # removed from the group (dynamic membership): a pruned
                # node no longer receives heartbeats, so without this
                # guard its timer would fire forever, deposing the real
                # leader by term inflation every timeout
                return
            quiet = time.monotonic() - self._last_leader_contact
            if quiet < self.election_timeout * self._election_jitter:
                return
            # campaign: bump term, vote for self, reset the clock with a
            # FRESH jitter draw (raft re-randomizes per round, or two
            # near-synchronized candidates split votes forever)
            import random

            self.wal.term += 1
            term = self.wal.term
            self.wal.voted_for = self.node_id
            self.wal.save_meta(fsync=True)
            self._last_leader_contact = time.monotonic()
            self._election_jitter = random.uniform(0.8, 1.6)
            last_index, last_term = self.wal.last_index, self.wal.last_term
            peers = [m for m in self.members if m != self.node_id]
            self.elections_started += 1
        self._observe("election_started", {"term": term})
        votes = 1
        for p in peers:
            try:
                resp = self.send_fn(p, f"{self.route_prefix}/vote", {
                    "pid": self.pid, "term": term,
                    "candidate": self.node_id,
                    "last_index": last_index, "last_term": last_term,
                })
            except RpcError:
                continue
            with self._lock:
                if resp.get("term", 0) > self.term:
                    self._step_down(resp["term"])
                    return
            if resp.get("granted"):
                votes += 1
        with self._lock:
            if self.term != term or self.is_leader:
                return  # a newer term appeared while counting
            if votes < self.quorum():
                return
            self.is_leader = True
            self.leader_hint = self.node_id
            self.elections_won += 1
            self._observe("election_won", {"term": term, "votes": votes})
            self._match = {}
            self._peer_commit = {}
            self._next = {
                p: self.wal.last_index + 1 for p in peers
            }
            # no-op of the new term: commits everything before it once
            # replicated (the standard prior-term commit carrier)
            self.wal.append([{
                "index": self.wal.last_index + 1, "term": term,
                "op": {"type": "noop"},
            }], fsync=True)
            self._advance_commit()
        self._apply_to_commit()
        self.tick()

    def handle_vote(self, body: dict) -> dict:
        """RequestVote (raft §5.2 + §5.4.1 up-to-date restriction)."""
        with self._lock:
            term = int(body["term"])
            if int(body["candidate"]) not in self.members:
                # a node removed from the group must not win (or even
                # disrupt) elections of the group it was removed from
                return {"granted": False, "term": self.term}
            if term < self.term:
                return {"granted": False, "term": self.term}
            if term > self.term:
                self._step_down(term)
                self.wal.voted_for = None
            up_to_date = (
                (int(body["last_term"]), int(body["last_index"]))
                >= (self.wal.last_term, self.wal.last_index)
            )
            candidate = int(body["candidate"])
            if up_to_date and self.wal.voted_for in (None, candidate):
                self.wal.voted_for = candidate
                self.wal.save_meta(fsync=True)
                # granting a vote resets our own election clock
                self._last_leader_contact = time.monotonic()
                return {"granted": True, "term": self.term}
            return {"granted": False, "term": self.term}

    def handle_fence(self, term: int) -> dict:
        """Master-driven fencing before promotion: adopt the new term
        (rejecting any older leader's appends from now on) and report
        log position so the master can pick the best candidate."""
        with self._lock:
            if term > self.term:
                self._step_down(term)
            return self.state()

    def _step_down(self, term: int) -> None:  # lint: holds[_lock]
        if self.is_leader:
            self._observe("step_down", {"term": term})
        self.is_leader = False
        if term > self.wal.term:
            self.wal.term = term
            self.wal.voted_for = None  # fresh term, fresh vote
            self.wal.save_meta(fsync=True)

    def become_leader(self, term: int, members: list[int],
                      learners: list[int] | None = None) -> dict:
        with self._lock:
            if term < self.term:
                raise RpcError(409, f"stale term {term} < {self.term}")
            self.wal.term = term
            self.members = list(members)
            if learners is not None:
                self.learners = [l for l in learners if l not in members]
            if not self.is_leader:
                self._observe("become_leader", {"term": term})
            self.is_leader = True
            self._match = {}
            self._peer_commit = {}
            self._next = {
                p: self.wal.last_index + 1 for p in self._peers()
            }
            self.wal.save_meta(fsync=True)
            # single-member group: everything in the log is committed
            self._advance_commit()
        self._apply_to_commit()
        self.tick()
        return self.state()

    def set_members(self, term: int, members: list[int],
                    learners: list[int] | None = None) -> dict:
        """Master-decreed membership change (reference: ChangeMember).
        `learners` replaces the learner set when given (None keeps it) —
        a learner promoted to voter keeps its _match/_next, so the
        promotion itself re-replicates nothing."""
        with self._lock:
            if term < self.term:
                raise RpcError(409, f"stale term {term} < {self.term}")
            self.wal.term = term
            self.members = list(members)
            if learners is not None:
                self.learners = [l for l in learners if l not in members]
            keep = set(self._peers())
            for p in keep:
                if p not in self._next:
                    self._next[p] = self.wal.last_index + 1
            self._match = {
                p: v for p, v in self._match.items() if p in keep
            }
            self._peer_commit = {
                p: v for p, v in self._peer_commit.items() if p in keep
            }
            self.wal.save_meta(fsync=True)
            if self.is_leader:
                self._advance_commit()
        self._apply_to_commit()
        self.tick()
        return self.state()

    def handle_install_snapshot(self, body: dict) -> dict:
        """Receive one chunk of a leader snapshot; install when done
        (reference: snapshot.go 10MB chunk stream)."""
        term = int(body["term"])
        with self._lock:
            if term < self.term or (term == self.term and self.is_leader):
                return {"success": False, "term": self.term}
            if term > self.term:
                self._step_down(term)
        sid = body["sid"]
        with self._lock:
            # drop abandoned streams (leader died mid-transfer): the
            # staging buffers are snapshot-sized, they must not pile up.
            # monotonic: a clock step must not mass-expire live streams
            now = time.monotonic()
            for old_sid in [
                s for s, st in self._snap_in.items()
                if now - st["ts"] > 120.0
            ]:
                del self._snap_in[old_sid]
            st = self._snap_in.setdefault(
                sid, {"buf": bytearray(), "ts": now}
            )
            st["ts"] = now
            buf: bytearray = st["buf"]
            if int(body["off"]) != len(buf):
                # duplicated/reordered chunk: nack so the leader restarts
                # the stream instead of installing a corrupt archive
                self._snap_in.pop(sid, None)
                return {"success": False, "term": self.term,
                        "error": "chunk out of order"}
            data = body["data"]
            if isinstance(data, str):  # legacy base64 framing
                buf += base64.b64decode(data)
            else:
                buf += bytes(memoryview(np.asarray(data, dtype=np.uint8)))
            if not body.get("done"):
                return {"success": True, "term": self.term}
            del self._snap_in[sid]
        snap_index = int(body["snap_index"])
        snap_term = body.get("snap_term")
        with self._apply_lock:
            with self._lock:
                if snap_index <= self.wal.commit_index:
                    # stale stream (raft: ignore InstallSnapshot at or
                    # below our COMMIT index, not just applied): a
                    # delayed/duplicated snapshot must not rewind a
                    # follower that already advanced past it via
                    # appends. Guarding only `applied` leaves a window
                    # when the apply loop lags (applied < snap_index <=
                    # commit): the wal.reset below would then DISCARD
                    # committed — possibly acked — entries above
                    # snap_index and rewind commit_index past them
                    # (caught by the adversarial suite as a vanished
                    # acked op). Committed prefixes never diverge, so
                    # the snapshot's content is already a prefix of our
                    # committed log — the apply loop catches up on its
                    # own. success=True so the leader stops
                    # re-streaming; the last_index we return (and its
                    # next append probe) resynchronizes next_index.
                    return {"success": True, "term": self.term,
                            "last_index": self.wal.last_index,
                            "stale": True}
            if self.install_fn is not None:
                self.install_fn(bytes(buf), snap_index)
            with self._lock:
                self.wal.reset(
                    snap_index + 1,
                    horizon_term=None if snap_term is None
                    else int(snap_term))
                self.wal.commit_index = snap_index
                self.applied = snap_index
                self.snapshots_installed += 1
                self.wal.save_meta(fsync=True)
        self._observe("snapshot_installed", {"snap_index": snap_index})
        return {"success": True, "term": self.term,
                "last_index": self.wal.last_index}

    # -- lifecycle -----------------------------------------------------------

    def recover_singleton_commit(self) -> None:
        """For single-member groups every fsync'd entry is committed:
        recovery replays the whole log (the durability contract —
        reference: WAL replay on restart)."""
        with self._lock:
            if len(self.members) <= 1:
                self.wal.commit_index = max(
                    self.wal.commit_index, self.wal.last_index
                )
        self._apply_to_commit()

    def close(self) -> None:
        # lock-fix note: _stopped was flipped without _lock; sync
        # threads read it under _lock to decide whether to keep looping
        with self._lock:
            self._stopped = True
        self.wal.close()

import os


def apply_jax_platform_env() -> None:
    """Propagate JAX_PLATFORMS into jax.config before backend init.

    Plugin discovery for an unavailable accelerator platform can block
    inside jax initialisation even when the env var selects cpu
    (observed with a dead TPU tunnel); the config route skips the
    unavailable plugin entirely. No-op when jax already initialised a
    backend or the env var is unset.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax  # a broken jax install must fail loudly, not hang later

    try:
        jax.config.update("jax_platforms", plat)
    except RuntimeError:
        # backend already initialised: the config is frozen, which also
        # means plugin discovery already happened — nothing to prevent
        pass


def prune_job_registry(jobs: dict, keep: int = 64) -> None:
    """Age out completed job records oldest-first, keeping `keep`
    finished entries (shared by the master and PS async-backup
    registries; caller holds the registry lock)."""
    done = [k for k in sorted(jobs, key=lambda k: jobs[k]["updated"])
            if jobs[k]["status"] in ("done", "error")]
    for old in done[:-keep]:
        del jobs[old]

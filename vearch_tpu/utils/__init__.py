import os
import time

# Span epochs are derived from monotonic measurements plus this
# process-constant anchor: durations must survive wall-clock steps
# (lint VL203), and a later NTP step merely shifts where spans sit on
# the collector's absolute timeline. Shared by every module whose
# timestamps cross function boundaries before span emission (engine
# phases, ivf dispatch capture, microbatch queue waits).
MONO_EPOCH_OFFSET = time.time() - time.monotonic()  # lint: allow[wall-clock] span epoch anchor, captured once at import


def mono_us(t_monotonic: float) -> int:
    """Monotonic seconds -> wall-anchored epoch microseconds, the
    `start_us` convention of the tracing layer."""
    return int((MONO_EPOCH_OFFSET + t_monotonic) * 1e6)


def apply_jax_platform_env() -> None:
    """Propagate JAX_PLATFORMS into jax.config before backend init.

    Plugin discovery for an unavailable accelerator platform can block
    inside jax initialisation even when the env var selects cpu
    (observed with a dead TPU tunnel); the config route skips the
    unavailable plugin entirely. No-op when jax already initialised a
    backend or the env var is unset.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax  # a broken jax install must fail loudly, not hang later

    try:
        jax.config.update("jax_platforms", plat)
    except RuntimeError:
        # backend already initialised: the config is frozen, which also
        # means plugin discovery already happened — nothing to prevent
        pass


_COMPILE_CACHE_DIR: str | None = None


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at `path` (default: the
    VEARCH_COMPILE_CACHE env var; no-op when neither is set).

    Compiled XLA programs survive process restarts, so a server restart
    or a bench rerun skips the multi-second compile stall that engine
    warmup otherwise pays once per process. Idempotent; returns the
    active cache dir (or None when disabled).
    """
    global _COMPILE_CACHE_DIR
    path = path or os.environ.get("VEARCH_COMPILE_CACHE")
    if not path:
        return _COMPILE_CACHE_DIR
    if _COMPILE_CACHE_DIR == path:
        return path
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", os.fspath(path))
        # cache every program: warmup pre-traces small search programs
        # whose compile time sits below the 1s default threshold
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        return None  # older jax without the persistent cache knobs
    _COMPILE_CACHE_DIR = path
    return path


def prune_job_registry(jobs: dict, keep: int = 64) -> None:
    """Age out completed job records oldest-first, keeping `keep`
    finished entries (shared by the master and PS async-backup
    registries; caller holds the registry lock)."""
    done = [k for k in sorted(jobs, key=lambda k: jobs[k]["updated"])
            if jobs[k]["status"] in ("done", "error")]
    for old in done[:-keep]:
        del jobs[old]

"""Leveled, rotating, per-role logging (reference: internal/pkg/log —
the Log interface with Trace/Debug/Info/Warn/Error/Fatal levels and
IsDebugEnabled guards, backed by a rotating file writer, configured from
the TOML `[global]` block and adjustable at runtime).

Built on stdlib `logging` (thread-safe, zero deps) with:
- a TRACE level below DEBUG (the reference's finest level);
- one process-wide root logger `vearch` — `init()` attaches a
  size-rotating file handler (`{log_dir}/{role}.log`) plus stderr;
  without `init()` a stderr-only handler at $VEARCH_LOG_LEVEL (default
  info) self-installs on first use, so library users get sane logs
  with no setup;
- `set_level()` for runtime changes (wired to the master's /config
  fan-out so operators can flip a cluster to debug live);
- module-level `trace/debug/info/warn/error` + `is_debug_enabled()`
  mirroring the reference's package-level API.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import threading

TRACE = 5
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_root = logging.getLogger("vearch")
_root.propagate = False
_lock = threading.Lock()
_initialized = False

_FMT = logging.Formatter(
    "%(asctime)s.%(msecs)03d %(levelname)s %(name)s: %(message)s",
    datefmt="%Y-%m-%d %H:%M:%S",
)


def parse_level(name: str) -> int:
    try:
        return _LEVELS[str(name).lower()]
    except KeyError:
        raise ValueError(
            f"unknown log level {name!r} (one of {sorted(_LEVELS)})"
        ) from None


def _ensure_default() -> None:
    global _initialized
    if _initialized:
        return
    with _lock:
        if _initialized:
            return
        h = logging.StreamHandler()
        h.setFormatter(_FMT)
        _root.addHandler(h)
        _root.setLevel(
            parse_level(os.environ.get("VEARCH_LOG_LEVEL", "info"))
        )
        _initialized = True


def init(
    role: str,
    log_dir: str | None = None,
    level: str = "info",
    max_bytes: int = 64 * 1024 * 1024,
    backups: int = 5,
    stderr: bool = True,
) -> None:
    """Configure process logging for a server role. Replaces any prior
    handlers (idempotent across restarts-in-process, as tests do)."""
    global _initialized
    with _lock:
        for h in list(_root.handlers):
            _root.removeHandler(h)
            h.close()
        if stderr:
            h = logging.StreamHandler()
            h.setFormatter(_FMT)
            _root.addHandler(h)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            fh = logging.handlers.RotatingFileHandler(
                os.path.join(log_dir, f"{role}.log"),
                maxBytes=max_bytes, backupCount=backups,
            )
            fh.setFormatter(_FMT)
            _root.addHandler(fh)
        _root.setLevel(parse_level(level))
        _initialized = True


def set_level(level: str) -> None:
    """Runtime level change (reference: log-level runtime config)."""
    _ensure_default()
    _root.setLevel(parse_level(level))


def get(name: str) -> logging.Logger:
    """Component logger, e.g. get('ps.raft') -> 'vearch.ps.raft'."""
    _ensure_default()
    return _root.getChild(name)


def is_debug_enabled() -> bool:
    _ensure_default()
    return _root.isEnabledFor(logging.DEBUG)


def is_trace_enabled() -> bool:
    _ensure_default()
    return _root.isEnabledFor(TRACE)


def trace(msg: str, *args) -> None:
    _ensure_default()
    _root.log(TRACE, msg, *args)


def debug(msg: str, *args) -> None:
    _ensure_default()
    _root.debug(msg, *args)


def info(msg: str, *args) -> None:
    _ensure_default()
    _root.info(msg, *args)


def warn(msg: str, *args) -> None:
    _ensure_default()
    _root.warning(msg, *args)


def error(msg: str, *args) -> None:
    _ensure_default()
    _root.error(msg, *args)

"""Per-tenant (space) cost accounting + SLO burn-rate layer.

Every ledger this repo keeps — the dispatch ledger (`ops/ivf.py
note_dispatch`), the process H2D byte accumulator (`ops/perf_model.py
note_h2d_bytes`), the engine phase spans — was global or per-partition.
No operator could answer "which tenant is burning the cluster". This
module adds the missing axis:

- a request-scoped **space context** (contextvar, mirroring the flight
  recorder's trace contextvar) stamped by the PS before any engine
  work and re-bound across the microbatch dispatcher's thread hop;
- a process-global :class:`SpaceAccountant` whose per-space meters are
  incremented *inside* the same calls that feed the global ledgers
  (observer hooks installed into ``note_dispatch`` / ``note_h2d_bytes``),
  so attribution is **conservation-exact by construction**: the sum
  over spaces of any meter equals the accountant's global total, and
  the h2d/dispatch totals move in lockstep with the process ledgers;
- integer micro-second device-time apportionment for co-batched shape
  buckets (row-share split with exact remainder handling — shares sum
  to the measured bucket total, never off by a microsecond);
- a fixed **top-K + "other"** metric label policy so thousands of
  spaces cannot explode the Prometheus series count (exact per-space
  numbers stay available on the JSON stats/heartbeat surfaces);
- a :class:`SpaceSLOEngine` for declared per-space latency/availability
  objectives: P²-sketch latency quantiles plus windowed good/bad
  counters feeding error-budget burn rates (SRE multiwindow: a fast
  5-minute window for paging, a slow 1-hour window for trend).

The accountant is process-global on purpose — the dispatch and H2D
ledgers it mirrors are process-global too, so per-PS accountants in one
process would double-count device work. Its ``scope_id`` rides the
heartbeat so the master's rollup can deduplicate co-located nodes.

Work arriving with no bound space (warmup passes, prefetch workers,
background builds) accrues to the reserved ``_system`` bucket, keeping
the conservation identity total == sum(spaces) unconditionally true.

Nothing here dispatches device programs or adds work to the serving
path beyond dict increments under a lock: zero added dispatches, zero
new compiled programs (gated by tests/test_accounting.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from typing import Any, Iterator

from vearch_tpu.tools import lockcheck

#: reserved bucket for work no request context claimed (warmup,
#: prefetch threads, background builds) — keeps sums conservation-exact
SYSTEM_SPACE = "_system"

#: reserved bucket for shadow ground-truth traffic (obs/quality.py):
#: recall-estimation re-executions bill their exact FLAT cost here so
#: tenant meters never inflate while conservation stays sum-exact
QUALITY_SPACE = "__quality__"

#: collapsed metric label once the per-space label budget is spent
OTHER_LABEL = "other"

#: distinct spaces that mint their own metric label; later arrivals
#: collapse into OTHER_LABEL. First-come stable: a label never changes
#: once assigned, so a mid-soak tenant churn cannot mint new series
#: past topk + 1.
SPACE_LABEL_TOPK = 12

#: meters every space account carries (all integers: conservation sums
#: must be exact, and floats drift)
METERS = (
    "requests",      # partition-level search RPCs billed (hedge extras excluded)
    "dispatches",    # device dispatches (same call as the dispatch ledger)
    "h2d_bytes",     # host->device bytes (same call as note_h2d_bytes)
    "device_us",     # engine device wall-time slices, µs (row-share split)
    "queue_wait_us",  # admission/gate wait, µs
    "rows",          # query rows served
    "cache_hits",    # PS result-cache hits (billed at zero device cost)
    "sheds",         # admission 429s (zero device work)
    "kills",         # deadline/slow/operator aborts
    "hedge_extras",  # duplicate hedge attempts (device cost real, request not double-billed)
)

_active_space: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "vearch_obs_active_space", default=None
)


def set_space(space: str | None) -> contextvars.Token:
    """Bind the request's space key ("db/space") for cost attribution;
    returns a token for :func:`reset_space`."""
    return _active_space.set(space)


def reset_space(token: contextvars.Token) -> None:
    _active_space.reset(token)


def current_space() -> str | None:
    """The calling context's bound space key, if any — captured at
    microbatch submit time to carry attribution across the dispatcher
    thread hop (same pattern as flight_recorder.current_trace)."""
    return _active_space.get()


@contextlib.contextmanager
def billed(space: str | None) -> Iterator[None]:
    """Scope helper: bind `space` for the duration of the block."""
    token = set_space(space)
    try:
        yield
    finally:
        reset_space(token)


@lockcheck.guarded
class SpaceAccountant:
    """Process-global per-space meters, conservation-exact.

    Every ``charge`` increments the space's meter AND the global total
    under one lock, so ``sum(spaces) == totals`` holds at every
    observable instant. The dispatch/h2d observer hooks are invoked
    from the exact same calls that feed the process-global ledgers, so
    the accountant's totals track those ledgers delta-for-delta.
    """

    _guarded_by = {
        "_spaces": "_lock",
        "_labels": "_lock",
        "_totals": "_lock",
    }

    def __init__(self, label_topk: int = SPACE_LABEL_TOPK):
        self.label_topk = int(label_topk)
        # rides the heartbeat so the master can deduplicate co-located
        # PS nodes sharing one process (and therefore one accountant)
        self.scope_id = uuid.uuid4().hex[:12]
        self._lock = lockcheck.make_lock("obs.accounting")
        self._spaces: dict[str, dict[str, int]] = {}
        self._totals: dict[str, int] = {m: 0 for m in METERS}
        self._labels: dict[str, str] = {}

    # -- internals (callers hold _lock) ---------------------------------

    def _meters(self, space: str) -> dict[str, int]:  # lint: holds[_lock]
        m = self._spaces.get(space)
        if m is None:
            m = self._spaces[space] = {k: 0 for k in METERS}
            # label minted at first charge (not at scrape) so the
            # assignment order is the traffic order, deterministically
            n_owned = sum(1 for v in self._labels.values()
                          if v != OTHER_LABEL)
            self._labels[space] = (
                space if n_owned < self.label_topk else OTHER_LABEL
            )
        return m

    # -- charging -------------------------------------------------------

    def charge(self, meter: str, n: int = 1,
               space: str | None = None) -> None:
        """Add `n` to `meter` for `space` (default: the bound context's
        space, else the `_system` bucket)."""
        sp = space if space is not None else (
            _active_space.get() or SYSTEM_SPACE)
        n = int(n)
        with self._lock:
            self._meters(sp)[meter] += n
            self._totals[meter] += n

    def touch(self, space: str) -> None:
        """Mint the space's account (and metric label) without charging
        anything — called when a partition is hosted so residency
        gauges render before the first request."""
        with self._lock:
            self._meters(space)

    def apportion_device_us(
        self, shares: list[tuple[str | None, int]], total_us: int
    ) -> list[int]:
        """Split a co-batched bucket's measured device time across its
        requests by row share, in integer microseconds, exactly: the
        returned slices sum to `total_us` (floor division, remainder to
        the last share). Each slice is charged to its share's space."""
        total_us = int(total_us)
        total_rows = sum(max(int(r), 0) for _, r in shares)
        out: list[int] = []
        acc = 0
        for i, (_, rows) in enumerate(shares):
            if i == len(shares) - 1:
                us = total_us - acc
            else:
                us = (total_us * max(int(rows), 0)) // max(total_rows, 1)
            acc += us
            out.append(us)
        with self._lock:
            for (space, _), us in zip(shares, out):
                sp = space or SYSTEM_SPACE
                self._meters(sp)["device_us"] += us
                self._totals["device_us"] += us
        return out

    # -- ledger observer hooks (installed by install()) ------------------

    def on_dispatch(self, tag: str) -> None:
        """Called from ops.ivf.note_dispatch — the same call that feeds
        the global dispatch ledger, so per-space counts reconcile."""
        self.charge("dispatches", 1)

    def on_h2d_bytes(self, n: int) -> None:
        """Called from ops.perf_model.note_h2d_bytes — the same call
        that feeds the process H2D byte ledger."""
        self.charge("h2d_bytes", n)

    # -- rendering ------------------------------------------------------

    def label(self, space: str) -> str:
        """The metric label for `space` under the top-K policy (minting
        the account if this is the first sighting)."""
        with self._lock:
            self._meters(space)
            return self._labels[space]

    def labelled(self, meter: str, scale: float = 1.0
                 ) -> dict[tuple[str, ...], float]:
        """Aggregate a meter by metric label for a callback metric —
        bounded at topk + 2 series regardless of tenant count."""
        with self._lock:
            out: dict[tuple[str, ...], float] = {}
            for sp, m in self._spaces.items():
                key = (self._labels.get(sp, OTHER_LABEL),)
                out[key] = out.get(key, 0.0) + float(m[meter]) * scale
            return out

    def snapshot(self) -> dict[str, Any]:
        """Exact per-space meters + totals (JSON surfaces: /ps/stats,
        the heartbeat usage block, tests). Unlike the metric labels,
        this is never collapsed — conservation checks need exact keys."""
        with self._lock:
            return {
                "scope_id": self.scope_id,
                "spaces": {sp: dict(m) for sp, m in self._spaces.items()},
                "totals": dict(self._totals),
                "labels": dict(self._labels),
            }

    def reset(self) -> None:
        """Test hook: drop meters AND label assignments (the label
        budget is first-come — a soak that churns 50 synthetic tenants
        must hand the budget back)."""
        with self._lock:
            self._spaces.clear()
            self._labels.clear()
            for k in self._totals:
                self._totals[k] = 0


#: process-global accountant — one per process, like the ledgers it mirrors.
ACCOUNTANT = SpaceAccountant()


def install() -> SpaceAccountant:
    """Hook the accountant into the dispatch + H2D ledgers (idempotent).
    Called by the PS at construction; safe to call from tests."""
    from vearch_tpu.ops import ivf, perf_model

    perf_model.set_h2d_observer(ACCOUNTANT.on_h2d_bytes)
    ivf.set_dispatch_observer(ACCOUNTANT.on_dispatch)
    return ACCOUNTANT


# -- per-space SLO engine (router tier) ---------------------------------------

#: SRE fast-burn page threshold: a 5-minute window burning the error
#: budget 14.4x faster than sustainable exhausts a 30-day budget in ~2
#: days — the classic multiwindow paging bound.
FAST_BURN_THRESHOLD = 14.4

#: no burn verdicts off a cold window: the first requests against a
#: space carry no budget evidence worth paging on
MIN_SLO_SAMPLES = 20

_FAST_WINDOW_S = 300.0
_SLOW_WINDOW_S = 3600.0
_N_BUCKETS = 60


class _BurnWindow:
    """Fixed-memory rolling good/bad window: N time buckets rotated by
    a monotonic clock. Not thread-safe — the owning engine locks."""

    def __init__(self, window_s: float, buckets: int = _N_BUCKETS):
        self.width = float(window_s) / buckets
        self.good = [0] * buckets
        self.bad = [0] * buckets
        self._epoch = 0  # absolute bucket index of the cursor

    def _rotate(self, now: float) -> int:
        epoch = int(now / self.width)
        ahead = epoch - self._epoch
        n = len(self.good)
        if ahead > 0:
            for i in range(min(ahead, n)):
                j = (self._epoch + 1 + i) % n
                self.good[j] = 0
                self.bad[j] = 0
            self._epoch = epoch
        return epoch % n

    def add(self, ok: bool, now: float) -> None:
        i = self._rotate(now)
        if ok:
            self.good[i] += 1
        else:
            self.bad[i] += 1

    def counts(self, now: float) -> tuple[int, int]:
        self._rotate(now)
        return sum(self.good), sum(self.bad)


@lockcheck.guarded
class SpaceSLOEngine:
    """Declared per-space objectives -> error-budget burn rates.

    An objective is a dict on the Space entity (``slo`` field):
    ``{"latency_ms": 50, "availability": 0.999}`` — a request is *bad*
    when it errors (429/499/5xx at the router) or outlives its latency
    target. Burn rate = bad_fraction / (1 - availability): 1.0 spends
    the budget exactly at the sustainable rate; >= 14.4 over the fast
    window is the paging condition (`fast_burn`).
    """

    _guarded_by = {
        "_objectives": "_lock",
        "_state": "_lock",
    }

    def __init__(self):
        self._lock = lockcheck.make_lock("obs.slo")
        self._objectives: dict[str, dict] = {}
        # space -> {fast: _BurnWindow, slow: _BurnWindow, q: P2 sketches,
        #           good: int, bad: int}
        self._state: dict[str, dict] = {}

    def set_objective(self, space: str, slo: dict | None) -> None:
        """Declare (or clear) a space's objective. Reconciled from the
        Space entity whenever the router (re)fetches its metadata."""
        with self._lock:
            if not slo:
                self._objectives.pop(space, None)
                self._state.pop(space, None)
                return
            if self._objectives.get(space) != slo:
                self._objectives[space] = dict(slo)

    def objective(self, space: str) -> dict | None:
        with self._lock:
            obj = self._objectives.get(space)
            return dict(obj) if obj else None

    def observe(self, space: str, latency_ms: float, ok: bool = True,
                now: float | None = None) -> None:
        """Score one logical request against the space's objective.
        No-op for spaces without a declared SLO. Hedge attempts never
        reach here — the router observes once per client request, so a
        won hedge bills once by construction."""
        from vearch_tpu.obs.quantiles import P2Estimator, TRACKED_QUANTILES

        now = time.monotonic() if now is None else now
        with self._lock:
            obj = self._objectives.get(space)
            if obj is None:
                return
            st = self._state.get(space)
            if st is None:
                st = self._state[space] = {
                    "fast": _BurnWindow(_FAST_WINDOW_S),
                    "slow": _BurnWindow(_SLOW_WINDOW_S),
                    "q": {q: P2Estimator(q) for q in TRACKED_QUANTILES},
                    "good": 0, "bad": 0,
                }
            target = obj.get("latency_ms")
            bad = (not ok) or (
                target is not None and latency_ms > float(target)
            )
            st["fast"].add(not bad, now)
            st["slow"].add(not bad, now)
            if bad:
                st["bad"] += 1
            else:
                st["good"] += 1
            for est in st["q"].values():
                est.observe(float(latency_ms))

    @staticmethod
    def _burn(good: int, bad: int, budget: float) -> float:
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / max(budget, 1e-9)

    def summary(self, now: float | None = None) -> dict[str, dict]:
        """Per-space SLO state for /router/stats, the master health
        rollup, and the doctor's `slo_burn` check."""
        now = time.monotonic() if now is None else now
        with self._lock:
            out: dict[str, dict] = {}
            for space, obj in self._objectives.items():
                st = self._state.get(space)
                avail = float(obj.get("availability", 0.999))
                budget = 1.0 - avail
                threshold = float(
                    obj.get("fast_burn_threshold", FAST_BURN_THRESHOLD))
                rec: dict[str, Any] = {
                    "objective": dict(obj),
                    "samples": 0,
                    "burn_fast": 0.0,
                    "burn_slow": 0.0,
                    "fast_burn": False,
                }
                if st is not None:
                    gf, bf = st["fast"].counts(now)
                    gs, bs = st["slow"].counts(now)
                    samples = st["good"] + st["bad"]
                    burn_fast = self._burn(gf, bf, budget)
                    rec.update({
                        "samples": samples,
                        "good": st["good"],
                        "bad": st["bad"],
                        "window_fast": {"good": gf, "bad": bf},
                        "window_slow": {"good": gs, "bad": bs},
                        "burn_fast": round(burn_fast, 3),
                        "burn_slow": round(
                            self._burn(gs, bs, budget), 3),
                        "fast_burn": bool(
                            gf + bf >= MIN_SLO_SAMPLES
                            and burn_fast >= threshold
                        ),
                        "latency_ms": {
                            str(q): round(est.value(), 3)
                            for q, est in st["q"].items()
                        },
                    })
                out[space] = rec
            return out

    def burn_gauge(self) -> dict[tuple[str, ...], float]:
        """Fast-window burn rate per space for the router's
        `vearch_space_slo_burn_rate` gauge. Objectives are operator-
        declared (bounded cardinality by construction), but the top-K
        policy still applies for defence in depth."""
        summary = self.summary()
        out: dict[tuple[str, ...], float] = {}
        for i, space in enumerate(sorted(summary)):
            key = (space if i < SPACE_LABEL_TOPK else OTHER_LABEL,)
            out[key] = max(out.get(key, 0.0),
                           float(summary[space]["burn_fast"]))
        return out

    def reset(self) -> None:
        with self._lock:
            self._objectives.clear()
            self._state.clear()

"""Streaming tail quantiles: the P^2 algorithm, fixed memory per series.

The tail-latency roadmap item needs p50/p95/p99 per (partition, op) on
every PS and a merged per-node view on the router — continuously, not
from a histogram whose buckets were guessed at deploy time. The P^2
estimator (Jain & Chlamtac, CACM 1985) tracks one quantile with five
markers and no sample buffer: O(1) memory and O(1) update, accuracy on
the order of a percent for smooth latency distributions. A
:class:`QuantileRegistry` bundles one estimator per tracked quantile
per key and serialises access; snapshots feed the
``vearch_ps_latency_quantile`` gauges and the ``/router/stats`` merged
view.
"""

from __future__ import annotations

from typing import Any, Iterable

from vearch_tpu.tools import lockcheck

#: the quantiles every sketch tracks; mirrored by the gauge's ``q``
#: label values, so this tuple is also the label-cardinality bound.
TRACKED_QUANTILES: tuple[float, ...] = (0.5, 0.95, 0.99)


class P2Estimator:
    """Single-quantile P^2 marker estimator.

    Five markers bracket [min, q/2-ish, q, (1+q)/2-ish, max]; each
    observation shifts marker positions and nudges heights with a
    piecewise-parabolic fit. Below five observations the raw sample is
    kept and quantiled by nearest rank. Not thread-safe — the owning
    registry serialises access.
    """

    __slots__ = ("q", "n", "_init", "_h", "_pos", "_des", "_inc")

    def __init__(self, q: float):
        self.q = float(q)
        self.n = 0
        self._init: list[float] = []
        self._h = [0.0] * 5
        self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
        self._des = [1.0, 1.0 + 2.0 * q, 1.0 + 4.0 * q, 3.0 + 2.0 * q,
                     5.0]
        self._inc = [0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0]

    def observe(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if self.n <= 5:
            self._init.append(x)
            if self.n == 5:
                self._init.sort()
                self._h = list(self._init)
            return
        h, pos = self._h, self._pos
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 3
            for i in range(1, 5):
                if x < h[i]:
                    k = i - 1
                    break
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._des[i] += self._inc[i]
        for i in range(1, 4):
            d = self._des[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (
                d <= -1.0 and pos[i - 1] - pos[i] < -1.0
            ):
                d = 1.0 if d >= 0.0 else -1.0
                hp = self._parabolic(i, d)
                if h[i - 1] < hp < h[i + 1]:
                    h[i] = hp
                else:
                    h[i] = self._linear(i, d)
                pos[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        h, pos = self._h, self._pos
        return h[i] + d / (pos[i + 1] - pos[i - 1]) * (
            (pos[i] - pos[i - 1] + d)
            * (h[i + 1] - h[i]) / (pos[i + 1] - pos[i])
            + (pos[i + 1] - pos[i] - d)
            * (h[i] - h[i - 1]) / (pos[i] - pos[i - 1])
        )

    def _linear(self, i: int, d: float) -> float:
        h, pos = self._h, self._pos
        j = i + int(d)
        return h[i] + d * (h[j] - h[i]) / (pos[j] - pos[i])

    def value(self) -> float:
        if self.n == 0:
            return 0.0
        if self.n <= 5:
            s = sorted(self._init)
            idx = int(round(self.q * (len(s) - 1)))
            return s[max(0, min(len(s) - 1, idx))]
        return self._h[2]


@lockcheck.guarded
class QuantileRegistry:
    """Keyed latency sketches: one P^2 estimator per tracked quantile.

    Keys are caller-chosen tuples — the PS uses ``(partition_id, op)``
    plus a node-level ``("_node", op)`` rollup; the router uses
    ``(ps_addr, op)``. ``snapshot()`` renders every key's quantile
    values and observation count in one pass for gauges and stats
    surfaces. Estimators never expire: key cardinality is bounded by
    topology (partitions hosted x ops), which is exactly the bound the
    metrics cardinality soak enforces.
    """

    _guarded_by = {"_sketches": "_lock"}

    def __init__(
        self, quantiles: Iterable[float] = TRACKED_QUANTILES,
        name: str = "obs.quantiles",
    ):
        self.quantiles = tuple(float(q) for q in quantiles)
        self._lock = lockcheck.make_lock(name)
        self._sketches: dict[tuple, list[P2Estimator]] = {}

    def observe(self, key: tuple, value: float) -> None:
        with self._lock:
            est = self._sketches.get(key)
            if est is None:
                est = [P2Estimator(q) for q in self.quantiles]
                self._sketches[key] = est
            for e in est:
                e.observe(value)

    def drop(self, key: tuple) -> None:
        """Forget a key (partition moved away); the next observation
        starts a fresh sketch."""
        with self._lock:
            self._sketches.pop(key, None)

    def snapshot(self) -> dict[tuple, dict[str, Any]]:
        """``{key: {"count": n, "q": {"0.5": v, ...}}}``; quantile keys
        are strings so snapshots survive a JSON round trip unchanged."""
        with self._lock:
            out: dict[tuple, dict[str, Any]] = {}
            for key, est in self._sketches.items():
                out[key] = {
                    "count": est[0].n if est else 0,
                    "q": {_qlabel(e.q): e.value() for e in est},
                }
            return out


def _qlabel(q: float) -> str:
    """Stable label text for a quantile: 0.5 -> "0.5", 0.95 -> "0.95"."""
    s = repr(q)
    return s.rstrip("0").rstrip(".") if "." in s else s

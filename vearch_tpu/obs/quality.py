"""Search-quality truth layer: live recall estimation + index-health drift.

Every other observability layer (runtime sampler, flight recorder,
accounting/SLO) watches performance and cost; this one watches whether
the *answers* are still right. Two signal families (docs/QUALITY.md):

- **Shadow recall sampling.** A deterministic keyed hash selects a small
  fraction of live search rows (default 1%); each sampled row is
  re-executed through the exact FLAT path over the raw store
  (``brute_force=True`` — bypasses the microbatcher, dispatches only the
  documented ``flat_scan``) and the served top-k is scored against that
  ground truth. Streaming per-(space, k-tier) estimators keep a decayed
  binomial (EWMA recall + Wilson interval) plus a rank-biased-overlap
  EWMA. Shadow work runs at negative admission priority (sheds first),
  bills to the reserved ``__quality__`` space (accounting.QUALITY_SPACE)
  so tenant meters stay exact, and the first execution per warm key runs
  inside the flight recorder's warmup scope so a cold FLAT compile is
  attributed to warmup, not flagged as serving drift.

- **Index-health drift gauges.** On a background cadence (or on demand
  in tests via :meth:`collect_health`) each hosted engine reports
  quantization reconstruction error (vs its value at train time), IVF
  cell-population imbalance, deleted-doc fraction, and unindexed
  tail-append fraction (engine.quality_info). Baseline-relative recon
  drift or structural imbalance marks the partition ``needs_retrain`` —
  the hint cluster/elastic.py surfaces for the autopilot.

Everything here is host-side numpy; the only device work is the
engine-owned shadow search (lint VL101 — this module never dispatches
directly). Index mutations MUST call :meth:`QualityMonitor.
note_index_mutation` (lint VL105) so estimators reset instead of
comparing fresh truth against a stale serving snapshot.
"""

from __future__ import annotations

import collections
import hashlib
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from vearch_tpu.cluster.metrics import internal_error
from vearch_tpu.obs import accounting
from vearch_tpu.obs import flight_recorder
from vearch_tpu.ops.perf_model import RECALL_K_TIERS
from vearch_tpu.tools import lockcheck

#: rank-biased-overlap persistence: weight of each next rank depth
RBO_P = 0.9

#: normal quantile for the Wilson recall interval (95% two-sided)
WILSON_Z = 1.96

#: ops that invalidate the train-time reconstruction baseline (the
#: quantizers themselves changed, not just the row set)
_RETRAIN_OPS = ("build", "rebuild", "train", "restore", "load")


def wilson_bounds(s: float, t: float, z: float = WILSON_Z
                  ) -> tuple[float, float]:
    """Wilson score interval for `s` successes in `t` trials (both may
    be decayed/fractional: the interval is then conservative for the
    effective sample size)."""
    if t <= 0:
        return 0.0, 1.0
    p = min(max(s / t, 0.0), 1.0)
    n = t
    denom = 1.0 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = z * math.sqrt(p * (1 - p) / n + z * z / (4 * n * n)) / denom
    return max(0.0, center - half), min(1.0, center + half)


def rank_biased_overlap(a: list, b: list, p: float = RBO_P) -> float:
    """Truncated-extrapolated RBO (Webber et al. 2010, eq. 30) between
    two rankings, evaluated to the shorter depth. 1.0 = identical
    ordering, 0.0 = disjoint; top ranks dominate (persistence `p`)."""
    k = min(len(a), len(b))
    if k == 0:
        return 1.0 if not a and not b else 0.0
    seen_a: set = set()
    seen_b: set = set()
    x = 0  # |A_d ∩ B_d|
    acc = 0.0
    for d in range(1, k + 1):
        ea, eb = a[d - 1], b[d - 1]
        if ea == eb:
            x += 1
        else:
            if ea in seen_b:
                x += 1
            if eb in seen_a:
                x += 1
        seen_a.add(ea)
        seen_b.add(eb)
        acc += (x / d) * (p ** d)
    return (x / k) * (p ** k) + (1 - p) / p * acc


@dataclass
class ShadowJob:
    """One sampled search row awaiting ground-truth re-execution."""

    pid: int
    space: str
    vectors: dict[str, np.ndarray]  # field -> [d] f32 query row
    k: int
    served: list  # served top-k keys, rank order
    data_version: int
    index_params: dict = field(default_factory=dict)
    # carried into the ground-truth request so truth answers the SAME
    # question the serving path did (a filtered search scored against
    # unfiltered truth would report phantom recall loss)
    filters: Any = None
    field_weights: dict = field(default_factory=dict)


class _RecallCell:
    """Decayed binomial recall estimator for one (space, k-tier)."""

    __slots__ = ("s", "t", "samples")

    def __init__(self):
        self.s = 0.0  # decayed hits
        self.t = 0.0  # decayed trials
        self.samples = 0  # undecayed count since last reset (gating)

    def update(self, hits: int, k: int, decay: float) -> None:
        self.s = (1.0 - decay) * self.s + float(hits)
        self.t = (1.0 - decay) * self.t + float(k)
        self.samples += 1

    def recall(self) -> float | None:
        return self.s / self.t if self.t > 0 else None


class _RboCell:
    __slots__ = ("value", "samples")

    def __init__(self):
        self.value = 0.0
        self.samples = 0

    def update(self, rbo: float, decay: float) -> None:
        if self.samples == 0:
            self.value = rbo
        else:
            self.value = (1.0 - decay) * self.value + decay * rbo
        self.samples += 1


#: shadow pipeline event names (the `event` label universe of
#: vearch_ps_quality_shadow_total — fixed, so cardinality is bounded)
SHADOW_EVENTS = (
    "sampled",   # row selected by the hash
    "executed",  # ground truth ran and scored
    "shed",      # negative-priority admission refused the shadow
    "stale",     # engine mutated between serve and shadow; sample dropped
    "dropped",   # queue full / engine gone
    "error",     # shadow execution raised (counted, never propagated)
)


@lockcheck.guarded
class QualityMonitor:
    """Per-PS recall estimation + index-health drift (one per PSServer;
    in-process multi-node tests host the same pid on several nodes, so
    this is deliberately NOT process-global)."""

    _guarded_by = {
        "_cells": "_lock",
        "_rbo": "_lock",
        "_floors": "_lock",
        "_counters": "_lock",
        "_queue": "_lock",
        "_warmed": "_lock",
        "_health": "_lock",
        "_recon_baseline": "_lock",
    }

    def __init__(
        self,
        get_engines: Callable[[], dict[int, Any]] | None = None,
        pid_space: Callable[[int], str | None] | None = None,
        admission: Any = None,
        sample_rate: float = 0.01,
        seed: int = 0,
        decay: float = 0.02,
        min_samples: int = 20,
        queue_cap: int = 256,
        health_interval_s: float = 10.0,
        recon_ratio_max: float = 1.5,
        imbalance_cv_max: float = 2.0,
        deleted_frac_max: float = 0.3,
        unindexed_frac_max: float = 0.5,
    ):
        self._get_engines = get_engines or (lambda: {})
        self._pid_space = pid_space or (lambda pid: None)
        self._admission = admission
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)
        self.decay = float(decay)
        self.min_samples = int(min_samples)
        self.queue_cap = int(queue_cap)
        self.health_interval_s = float(health_interval_s)
        self.recon_ratio_max = float(recon_ratio_max)
        self.imbalance_cv_max = float(imbalance_cv_max)
        self.deleted_frac_max = float(deleted_frac_max)
        self.unindexed_frac_max = float(unindexed_frac_max)
        self._seed_key = self.seed.to_bytes(8, "big", signed=False)
        self._lock = lockcheck.make_lock("obs.quality")
        self._cells: dict[tuple[str, int], _RecallCell] = {}
        self._rbo: dict[str, _RboCell] = {}
        self._floors: dict[str, float] = {}
        self._counters: dict[str, int] = {e: 0 for e in SHADOW_EVENTS}
        self._queue: collections.deque[ShadowJob] = collections.deque()
        self._warmed: set[tuple] = set()
        self._health: dict[int, dict[str, Any]] = {}
        self._recon_baseline: dict[tuple[int, str], float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- runtime knobs ---------------------------------------------------

    def configure(self, **kw) -> None:
        """Apply runtime knob changes (PS /engine/config `quality`
        block): sample_rate, decay, min_samples, health thresholds."""
        for name in ("sample_rate", "decay", "min_samples",
                     "health_interval_s", "recon_ratio_max",
                     "imbalance_cv_max", "deleted_frac_max",
                     "unindexed_frac_max"):
            if name in kw and kw[name] is not None:
                cast = int if name == "min_samples" else float
                setattr(self, name, cast(kw[name]))

    def set_floors(self, floors: dict[str, float]) -> None:
        """Replace the per-space recall floors (heartbeat-applied from
        the master's /register response; source is Space.slo)."""
        fl = {str(k): float(v) for k, v in (floors or {}).items()}
        with self._lock:
            self._floors = fl

    def set_floor(self, space: str, floor: float | None) -> None:
        with self._lock:
            if floor is None:
                self._floors.pop(space, None)
            else:
                self._floors[space] = float(floor)

    # -- deterministic sampling -----------------------------------------

    def sampled(self, row: np.ndarray, k: int) -> bool:
        """Keyed-hash row selection: a pure function of (seed, query
        bytes, k) — replicas serving the same traffic sample the same
        set, and reruns are exactly reproducible."""
        payload = (
            np.ascontiguousarray(row, dtype=np.float32).tobytes()
            + int(k).to_bytes(4, "big", signed=False)
        )
        h = hashlib.blake2b(payload, digest_size=8,
                            key=self._seed_key).digest()
        return int.from_bytes(h, "big") < self.sample_rate * 2.0 ** 64

    def observe_search(
        self,
        pid: int,
        space: str,
        vectors: dict[str, np.ndarray],
        k: int,
        results: list,
        data_version: int,
        index_params: dict | None = None,
        filters: Any = None,
        field_weights: dict | None = None,
    ) -> int:
        """Score a served search for sampling. `vectors` is the request's
        field->[B, d] batch (a flat [d] row counts as B=1); `results` is
        the served per-row sequence — SearchResult objects or plain key
        lists (the columnar wire shape). Enqueues a ShadowJob per
        sampled row; returns how many."""
        if self.sample_rate <= 0.0 or not vectors or not results:
            return 0
        fields = sorted(vectors)
        batches = {}
        for f in fields:
            # lint: allow[host-sync] copies the sampled (host) query payload for the shadow job, no device involved
            arr = np.asarray(vectors[f], dtype=np.float32)
            batches[f] = arr[None, :] if arr.ndim == 1 else arr
        nrows = min(len(results),
                    min(b.shape[0] for b in batches.values()))
        picked = 0
        for i in range(nrows):
            if not self.sampled(batches[fields[0]][i], k):
                continue
            r = results[i]
            served = (list(r) if isinstance(r, (list, tuple))
                      else [it.key for it in r.items])
            job = ShadowJob(
                pid=pid, space=space,
                vectors={f: batches[f][i].copy() for f in fields},
                k=int(k), served=served,
                data_version=int(data_version),
                index_params=dict(index_params or {}),
                filters=filters,
                field_weights=dict(field_weights or {}),
            )
            with self._lock:
                self._counters["sampled"] += 1
                if len(self._queue) >= self.queue_cap:
                    self._counters["dropped"] += 1
                else:
                    self._queue.append(job)
                    picked += 1
        return picked

    # -- shadow execution ------------------------------------------------

    def run_pending(self, limit: int | None = None) -> int:
        """Drain queued shadow jobs synchronously (worker thread body;
        also the test hook — no thread needed for determinism)."""
        done = 0
        while limit is None or done < limit:
            with self._lock:
                if not self._queue:
                    break
                job = self._queue.popleft()
            self._execute(job)
            done += 1
        return done

    def _execute(self, job: ShadowJob) -> None:
        eng = self._get_engines().get(job.pid)
        if eng is None:
            with self._lock:
                self._counters["dropped"] += 1
            return
        if getattr(eng, "data_version", None) != job.data_version:
            # rows were written/deleted between serve and shadow: the
            # served list and fresh ground truth are for different
            # corpora — scoring them would report phantom recall loss
            with self._lock:
                self._counters["stale"] += 1
            return
        adm = self._admission
        if adm is not None and not adm.try_admit(priority=-1):
            with self._lock:
                self._counters["shed"] += 1
            return
        try:
            truth = self._ground_truth(eng, job)
        except Exception as e:  # shadow work must never break serving
            internal_error("quality.shadow", e)
            with self._lock:
                self._counters["error"] += 1
            return
        finally:
            if adm is not None:
                adm.leave()
        self._score(job, truth)

    def _ground_truth(self, eng: Any, job: ShadowJob) -> list:
        """Exact FLAT top-k over the raw store, billed to __quality__.
        brute_force bypasses the microbatcher and runs the documented
        flat_scan dispatch; the first execution per warm key runs in
        the flight recorder's warmup scope so a cold FLAT compile is
        attributed to warmup rather than paged as serving drift."""
        from vearch_tpu.engine.engine import SearchRequest

        req = SearchRequest(
            vectors={f: q[None, :] for f, q in job.vectors.items()},
            k=job.k,
            filters=job.filters,
            include_fields=[],
            brute_force=True,
            field_weights=job.field_weights,
            index_params=job.index_params,
        )
        key = (job.pid, tuple(sorted(job.vectors)), job.k)
        with self._lock:
            cold = key not in self._warmed
        with accounting.billed(accounting.QUALITY_SPACE):
            if cold:
                with flight_recorder.RECORDER.warmup():
                    res = eng.search(req)
                with self._lock:
                    self._warmed.add(key)
            else:
                res = eng.search(req)
        return [it.key for it in res[0].items]

    def _score(self, job: ShadowJob, truth: list) -> None:
        rbo = rank_biased_overlap(job.served, truth)
        with self._lock:
            self._counters["executed"] += 1
            for kt in RECALL_K_TIERS:
                if kt > job.k:
                    continue
                hits = len(set(job.served[:kt]) & set(truth[:kt]))
                cell = self._cells.get((job.space, kt))
                if cell is None:
                    cell = self._cells[(job.space, kt)] = _RecallCell()
                cell.update(hits, kt, self.decay)
            rc = self._rbo.get(job.space)
            if rc is None:
                rc = self._rbo[job.space] = _RboCell()
            rc.update(rbo, self.decay)

    # -- staleness hook (lint VL105) -------------------------------------

    def note_index_mutation(self, pid: int | None = None,
                            space: str | None = None, op: str = "") -> None:
        """MUST be called by every code path that mutates index contents
        (absorb/build/delete/restore/split cutover — lint VL105): resets
        the affected recall estimators so fresh ground truth is never
        scored against pre-mutation serving behaviour, and invalidates
        the train-time reconstruction baseline when quantizers retrain.
        Safe to call at any frequency; it only clears streaming state."""
        with self._lock:
            if space is None:
                self._cells.clear()
                self._rbo.clear()
            else:
                for key in [k for k in self._cells if k[0] == space]:
                    del self._cells[key]
                self._rbo.pop(space, None)
            if op in _RETRAIN_OPS:
                if pid is None:
                    self._recon_baseline.clear()
                else:
                    for key in [k for k in self._recon_baseline
                                if k[0] == pid]:
                        del self._recon_baseline[key]
            if pid is not None:
                self._warmed = {w for w in self._warmed if w[0] != pid}
                self._health.pop(pid, None)

    # -- index-health drift ----------------------------------------------

    def collect_health(self) -> dict[int, dict[str, Any]]:
        """Sample every hosted engine's quality_info, compare recon
        error against its train-time baseline, and derive needs_retrain
        reasons. Called from the worker cadence and directly by tests."""
        out: dict[int, dict[str, Any]] = {}
        for pid, eng in dict(self._get_engines()).items():
            try:
                info = eng.quality_info()
            except Exception as e:
                internal_error("quality.health", e)
                continue
            reasons: list[str] = []
            if info.get("deleted_frac", 0.0) > self.deleted_frac_max:
                reasons.append(
                    f"deleted_frac={info['deleted_frac']:.3f}"
                    f">{self.deleted_frac_max}")
            fields = info.get("fields", {})
            for fname, f in fields.items():
                recon = f.get("recon_error")
                if recon is not None:
                    bkey = (pid, fname)
                    with self._lock:
                        base = self._recon_baseline.get(bkey)
                        if base is None and f.get("trained"):
                            # first sighting after (re)train: this IS
                            # the train-time value drift compares against
                            self._recon_baseline[bkey] = base = recon
                    f["recon_baseline"] = base
                    if (base is not None and base > 0
                            and recon > base * self.recon_ratio_max):
                        reasons.append(
                            f"{fname}: recon_error={recon:.4f} is "
                            f"{recon / base:.2f}x train-time {base:.4f}")
                cv = f.get("cell_imbalance_cv")
                if cv is not None and cv > self.imbalance_cv_max:
                    reasons.append(
                        f"{fname}: cell_imbalance_cv={cv:.2f}"
                        f">{self.imbalance_cv_max}")
                uf = f.get("unindexed_frac")
                if uf is not None and uf > self.unindexed_frac_max:
                    reasons.append(
                        f"{fname}: unindexed_frac={uf:.3f}"
                        f">{self.unindexed_frac_max}")
            info["needs_retrain"] = bool(reasons)
            info["reasons"] = reasons
            out[pid] = info
        with self._lock:
            self._health = out
        return out

    # -- worker ----------------------------------------------------------

    def start(self) -> None:
        """Background worker: drains shadow jobs and runs the health
        cadence. Idempotent; tests usually skip it and call
        run_pending()/collect_health() synchronously."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name="quality-worker", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _worker(self) -> None:
        next_health = time.monotonic() + self.health_interval_s
        while not self._stop.wait(0.05):
            try:
                self.run_pending()
                now = time.monotonic()
                if now >= next_health:
                    self.collect_health()
                    next_health = now + self.health_interval_s
            except Exception as e:  # the loop must survive anything
                internal_error("quality.worker", e)

    # -- read surfaces ---------------------------------------------------

    def recall_snapshot(self) -> dict[str, Any]:
        """Per-space estimator state: EWMA recall + Wilson bounds per
        k-tier, RBO, sample counts, floor + breach verdicts."""
        with self._lock:
            spaces: dict[str, dict[str, Any]] = {}
            for (space, kt), cell in self._cells.items():
                sp = spaces.setdefault(space, {"recall": {}})
                lo, hi = wilson_bounds(cell.s, cell.t)
                sp["recall"][str(kt)] = {
                    "estimate": cell.recall(),
                    "wilson_low": lo,
                    "wilson_high": hi,
                    "samples": cell.samples,
                }
            for space, rc in self._rbo.items():
                sp = spaces.setdefault(space, {"recall": {}})
                sp["rbo"] = rc.value
                sp["rbo_samples"] = rc.samples
            for space, sp in spaces.items():
                floor = self._floors.get(space)
                sp["floor"] = floor
                sp["breach"] = self._breached_locked(space, floor)
            return {"spaces": spaces, "counters": dict(self._counters)}

    def _breached_locked(self, space: str,
                         floor: float | None) -> bool:  # lint: holds[_lock]
        """Floor breach = enough evidence that even the OPTIMISTIC end
        of the recall interval sits under the floor, at any k-tier —
        Wilson-upper gating means a breach is statistical, not one bad
        sample after an absorb."""
        if floor is None:
            return False
        for (sp, _kt), cell in self._cells.items():
            if sp != space or cell.samples < self.min_samples:
                continue
            _lo, hi = wilson_bounds(cell.s, cell.t)
            if hi < floor:
                return True
        return False

    def breach_spaces(self) -> list[str]:
        with self._lock:
            return sorted(
                sp for sp, floor in self._floors.items()
                if self._breached_locked(sp, floor)
            )

    def health_snapshot(self) -> dict[int, dict[str, Any]]:
        with self._lock:
            return {pid: dict(h) for pid, h in self._health.items()}

    def partition_stats(self, pid: int) -> dict[str, Any] | None:
        """Per-partition quality block riding the heartbeat's partition
        stats → master _node_stats → elastic.compute_plan needs_retrain."""
        with self._lock:
            h = self._health.get(pid)
            return dict(h) if h is not None else None

    def obs_summary(self) -> dict[str, Any]:
        """Compact summary riding the heartbeat obs block → master
        _node_obs → /cluster/health degradation."""
        with self._lock:
            retrain = sorted(pid for pid, h in self._health.items()
                             if h.get("needs_retrain"))
        return {
            "recall_breach_spaces": self.breach_spaces(),
            "needs_retrain_pids": retrain,
        }

    def stats(self) -> dict[str, Any]:
        """The /ps/stats `quality` block."""
        with self._lock:
            depth = len(self._queue)
            floors = dict(self._floors)
        return {
            "sampling": {
                "rate": self.sample_rate,
                "seed": self.seed,
                "decay": self.decay,
                "min_samples": self.min_samples,
                "queue": depth,
                "counters": self.counters(),
            },
            "floors": floors,
            "recall": self.recall_snapshot()["spaces"],
            "health": {str(pid): h
                       for pid, h in self.health_snapshot().items()},
        }

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

"""Compile-audit flight recorder: post-warmup serving compiles, live.

`tests/test_perf_gates.py` asserts zero retrace after warmup — in CI.
In a running cluster a shape-churned request that forces XLA to compile
on the serving path stalls that request for orders of magnitude longer
than a dispatch, and nothing recorded it. The `register_jit` layer now
detects jit-cache growth around every call of a registered program
(`ops/perf_model.py`) and notifies this module's process-global
recorder, which:

- keeps a bounded ring of post-warmup compile events (program, shape
  signature, wall time of the triggering call, active trace id) served
  at ``GET /debug/compiles``;
- feeds the ``vearch_serving_compiles_total`` counter (label ``path`` =
  registered program name);
- suppresses expected compiles: anything under a ``warmup()`` scope
  (engine open/build/publish/restore, explicit warmup passes) is
  counted separately and kept out of the ring.

The recorder is process-global on purpose: the jit cache it audits is
process-global too, so per-PS recorders in one process would disagree
about which call compiled. Trace attribution stays per-request via a
contextvar the PS sets around engine calls.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from collections import deque
from typing import Any, Iterator

from vearch_tpu.ops import perf_model
from vearch_tpu.tools import lockcheck

_active_trace: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "vearch_obs_active_trace", default=None
)


def set_active_trace(trace_id: str | None) -> contextvars.Token:
    """Bind the request's trace id for compile attribution; returns a
    token for :func:`reset_active_trace`."""
    return _active_trace.set(trace_id)


def reset_active_trace(token: contextvars.Token) -> None:
    _active_trace.reset(token)


def current_trace() -> str | None:
    """The calling context's bound trace id, if any — used to carry
    attribution across thread hops (the microbatch dispatcher runs the
    device call on its own thread, where the contextvar is unset)."""
    return _active_trace.get()


@lockcheck.guarded
class CompileFlightRecorder:
    """Ring buffer + counters for serving-path compilations."""

    _guarded_by = {
        "_events": "_lock",
        "_counts": "_lock",
        "_seen": "_lock",
    }

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._lock = lockcheck.make_lock("obs.flight_recorder")
        self._events: deque[dict] = deque(maxlen=self.capacity)
        self._counts: dict[str, int] = {}  # program -> post-warmup n
        # (program, shape_sig) pairs already recorded: one compile per
        # specialisation, and a shield against the benign race where
        # two threads watch the same cache-size step
        self._seen: set[tuple[str, str]] = set()
        self._warmup_depth = 0  # int; reads/writes under _lock
        self.warmup_compiles = 0

    @contextlib.contextmanager
    def warmup(self) -> Iterator[None]:
        """Scope for *expected* compilation: engine open/build/publish/
        restore and explicit warmup passes. Re-entrant (refcounted)."""
        with self._lock:
            self._warmup_depth += 1
        try:
            yield
        finally:
            with self._lock:
                self._warmup_depth -= 1

    def in_warmup(self) -> bool:
        with self._lock:
            return self._warmup_depth > 0

    def on_compile(
        self, program: str, shape_sig: str, elapsed_ms: float
    ) -> None:
        """Observer callback installed into ``ops.perf_model``."""
        trace_id = _active_trace.get()
        with self._lock:
            if self._warmup_depth > 0:
                self.warmup_compiles += 1
                return
            key = (program, shape_sig)
            if key in self._seen:
                return
            self._seen.add(key)
            self._counts[program] = self._counts.get(program, 0) + 1
            self._events.append({
                # operator-facing stamp for log correlation, not math
                "ts": time.time(),  # lint: allow[wall-clock] event stamp for operator correlation
                "path": program,
                "shapes": shape_sig,
                "elapsed_ms": round(float(elapsed_ms), 3),
                "trace_id": trace_id,
            })

    def events(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def reset(self) -> None:
        """Test hook: drop recorded state (the jit caches themselves
        are untouched)."""
        with self._lock:
            self._events.clear()
            self._counts.clear()
            self._seen.clear()
            self.warmup_compiles = 0


#: process-global recorder — one per process, like the jit cache.
RECORDER = CompileFlightRecorder()


def install() -> CompileFlightRecorder:
    """Hook the recorder into the register_jit layer (idempotent)."""
    perf_model.set_compile_observer(RECORDER.on_compile)
    return RECORDER

"""Cluster doctor: one fan-out, one report, one exit code.

``python -m vearch_tpu doctor --master HOST:PORT`` asks the master for
the topology, then visits every PS and router to collect ``/metrics``,
``/ps/stats``, ``/debug/slowlog``, ``/debug/compiles``,
``/router/stats`` and ``/cluster/jobs``, folds everything into a
single JSON report with a human summary, and runs the standing
invariant checks:

- ``hbm_drift``          no PS reports footprint-model drift
- ``post_warmup_compiles`` no serving-path compile after warmup
- ``cardinality_ceiling``  every /metrics page stays under the series
                           ceiling the cardinality soak enforces in CI
- ``cluster_health``       the master rollup is not red
- ``slo_burn``             no space with a declared SLO is fast-burning
                           its error budget (router burn-rate windows)
- ``usage_conservation``   every PS's per-tenant meters sum exactly to
                           its accountant totals (docs/ACCOUNTING.md)
- ``search_quality``       no space's shadow-sampled recall sits
                           statistically under its declared floor, and
                           no partition's index-health drift gauges say
                           retrain (docs/QUALITY.md)
- ``obs_docs``             docs/OBSERVABILITY.md matches the source
                           (skipped when no source tree is present)

Exit 0 when every check passes; exit 1 with the violations named.
Usable both as an operator tool against a live deployment and as a
tier-1 smoke test against a 2-node standalone cluster.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any

from vearch_tpu.cluster import rpc

#: per-page series ceiling — keep in lockstep with the cardinality
#: soak's assert (tests/test_metrics_cardinality.py)
SERIES_CEILING = 600


def _series_count(metrics_text: str) -> int:
    return sum(
        1 for ln in metrics_text.splitlines()
        if ln and not ln.startswith("#")
    )


def _scrape(addr: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=timeout
    ) as r:
        return r.read().decode()


def _get(addr: str, path: str, auth: tuple[str, str] | None) -> Any:
    try:
        return rpc.call(addr, "GET", path, auth=auth)
    except Exception as e:  # unreachable node IS a finding, not a crash
        return {"_error": f"{type(e).__name__}: {e}"}


def collect(
    master_addr: str, auth: tuple[str, str] | None = None
) -> dict[str, Any]:
    """Fan out to every node; return the raw evidence report."""
    report: dict[str, Any] = {"master": master_addr}
    report["health"] = _get(master_addr, "/cluster/health", auth)
    report["jobs"] = _get(master_addr, "/cluster/jobs", auth)
    servers = _get(master_addr, "/servers", auth)
    routers = _get(master_addr, "/routers", auth)
    report["servers"] = []
    for srv in (servers.get("servers") or []):
        addr = srv.get("rpc_addr")
        entry: dict[str, Any] = {
            "node_id": srv.get("node_id"), "addr": addr,
        }
        entry["stats"] = _get(addr, "/ps/stats", auth)
        entry["compiles"] = _get(addr, "/debug/compiles", auth)
        slowlog = _get(addr, "/debug/slowlog", auth)
        entries = (
            slowlog.get("entries") if isinstance(slowlog, dict) else None
        ) or []
        entry["slowlog_len"] = len(entries)
        # recent span heads: enough to correlate a slow request with
        # its trace without shipping whole span trees in the report
        entry["span_heads"] = [
            {"trace_id": e.get("trace_id"), "op": e.get("op"),
             "elapsed_ms": e.get("elapsed_ms")}
            for e in entries[-5:]
        ]
        try:
            entry["metrics_series"] = _series_count(_scrape(addr))
        except Exception as e:
            entry["metrics_series"] = None
            entry["metrics_error"] = str(e)
        report["servers"].append(entry)
    report["routers"] = []
    for rt in (routers.get("routers") or []):
        addr = rt.get("addr")
        entry = {"addr": addr}
        entry["stats"] = _get(addr, "/router/stats", auth)
        try:
            entry["metrics_series"] = _series_count(_scrape(addr))
        except Exception as e:
            entry["metrics_series"] = None
            entry["metrics_error"] = str(e)
        report["routers"].append(entry)
    try:
        report["master_metrics_series"] = _series_count(
            _scrape(master_addr)
        )
    except Exception as e:
        report["master_metrics_series"] = None
        report["master_metrics_error"] = str(e)
    return report


def _check_obs_docs() -> tuple[bool | None, str]:
    """VL401 coverage from the installed source tree; (None, reason)
    when the tree or docs are not available (e.g. doctor run from a
    bare wheel against a remote cluster)."""
    import os

    from vearch_tpu.tools.lint import rules_obs

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)
    doc = os.path.join(repo_root, "docs", "OBSERVABILITY.md")
    if not os.path.exists(doc):
        return None, "docs/OBSERVABILITY.md not present; skipped"
    failures = rules_obs.drift_failures(
        *rules_obs.source_names(pkg_root), doc
    )
    if failures:
        return False, "; ".join(failures[:5])
    return True, "docs match source"


_LINT_CLEAN_MEMO: tuple[bool | None, str] | None = None


def _check_lint_clean() -> tuple[bool | None, str]:
    """The full vearch-lint suite — including the interprocedural
    VL5xx serving-path/lock-graph proofs — run in-process over the
    installed source tree. (None, reason) when the tree is not
    available (doctor from a bare wheel against a remote cluster).
    Memoized for the process: doctor can run many times per session
    and the whole-package scan costs seconds; the source tree does not
    change under a running process."""
    global _LINT_CLEAN_MEMO
    if _LINT_CLEAN_MEMO is not None:
        return _LINT_CLEAN_MEMO
    import os

    from vearch_tpu.tools.lint import (
        Allowlist, default_allowlist_path, run_paths,
    )

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(pkg_root, "tools", "lint")):
        return None, "source tree not present; skipped"
    findings = run_paths(
        [pkg_root], allowlist=Allowlist(default_allowlist_path()))
    hard = [f for f in findings if not f.suppressed]
    if hard:
        out = (False, "; ".join(
            f"{f.rule}[{f.tag}] {f.path}:{f.line}" for f in hard[:5]))
    else:
        allowed = sum(1 for f in findings if f.suppressed)
        out = (True, f"0 hard finding(s), {allowed} reason-waived")
    _LINT_CLEAN_MEMO = out
    return out


def run_checks(report: dict[str, Any]) -> list[dict[str, Any]]:
    """Evaluate the standing invariants over a collected report."""
    checks: list[dict[str, Any]] = []

    drifted = []
    for srv in report.get("servers", []):
        samp = (srv.get("stats") or {}).get("device_sampler") or {}
        if samp.get("drift"):
            drifted.append(
                f"node {srv.get('node_id')} "
                f"drift_bytes={samp.get('drift_bytes')}"
            )
    checks.append({
        "name": "hbm_drift", "ok": not drifted,
        "detail": ("; ".join(drifted) if drifted
                   else "measured HBM within model tolerance"),
    })

    compiled = []
    for srv in report.get("servers", []):
        comp = srv.get("compiles") or {}
        total = comp.get("total") or 0
        if total:
            paths = sorted((comp.get("counts") or {}).keys())
            compiled.append(
                f"node {srv.get('node_id')}: {total} "
                f"post-warmup compile(s) on {', '.join(paths)}"
            )
    checks.append({
        "name": "post_warmup_compiles", "ok": not compiled,
        "detail": ("; ".join(compiled) if compiled
                   else "zero serving-path compiles after warmup"),
    })

    over = []
    pages = [("master", report.get("master_metrics_series"))]
    pages += [(f"ps:{s.get('node_id')}", s.get("metrics_series"))
              for s in report.get("servers", [])]
    pages += [(f"router:{r.get('addr')}", r.get("metrics_series"))
              for r in report.get("routers", [])]
    for who, n in pages:
        if n is not None and n > SERIES_CEILING:
            over.append(f"{who}: {n} series > {SERIES_CEILING}")
    checks.append({
        "name": "cardinality_ceiling", "ok": not over,
        "detail": ("; ".join(over) if over
                   else f"all pages under {SERIES_CEILING} series"),
    })

    status = (report.get("health") or {}).get("status")
    checks.append({
        "name": "cluster_health", "ok": status in ("green", "yellow"),
        "detail": f"master rollup is {status!r}",
    })

    # sustained overload shedding: a few sheds are admission control
    # doing its job; a shed RATE above 10% of admitted traffic means
    # the cluster is turning clients away faster than it serves them —
    # capacity or limit tuning needed, not backoff
    shedding = []
    for srv in report.get("servers", []):
        adm = (srv.get("stats") or {}).get("admission") or {}
        shed = int(adm.get("shed_total") or 0)
        admitted = int(adm.get("admitted_total") or 0)
        if shed and shed > 0.10 * max(1, admitted):
            shedding.append(
                f"node {srv.get('node_id')}: {shed} shed vs "
                f"{admitted} admitted "
                f"(queue_limit={adm.get('queue_limit')})"
            )
    checks.append({
        "name": "shed_rate", "ok": not shedding,
        "detail": ("; ".join(shedding) if shedding
                   else "admission shed rate within bounds"),
    })

    # prefetch effectiveness: a disk-tier partition with prefetch on
    # and real traffic should be landing most lookups on pinned or
    # prefetch-confirmed slabs — a low pin+prefetch hit share means the
    # predictor is thrashing (transfers on the critical path) and the
    # operator should raise cache_mb/pin_slots or disable prefetch
    thrashing = []
    observed = 0
    for srv in report.get("servers", []):
        parts = (srv.get("stats") or {}).get("partitions") or {}
        for pid, part in parts.items():
            fields = ((part.get("tiering") or {}).get("fields") or {})
            for fname, tier in fields.items():
                hbm = tier.get("hbm") or {}
                pf = tier.get("prefetch") or {}
                lookups = int(hbm.get("hits") or 0) + int(
                    hbm.get("misses") or 0
                )
                if not pf.get("enabled") or lookups < 512:
                    continue
                observed += 1
                served = int(hbm.get("pin_hits") or 0) + int(
                    hbm.get("prefetch_hits") or 0
                )
                if served < 0.5 * lookups:
                    thrashing.append(
                        f"node {srv.get('node_id')} partition {pid} "
                        f"field {fname}: {served}/{lookups} "
                        f"pin+prefetch hits"
                    )
    checks.append({
        "name": "prefetch_effectiveness", "ok": not thrashing,
        "detail": ("; ".join(thrashing) if thrashing
                   else (f"{observed} disk-tier field(s) serving from "
                         f"pinned/prefetched slabs" if observed
                         else "no disk-tier traffic to judge")),
    })

    # shape-bucket padding waste: a little padding is the price of a
    # bounded compiled-program universe; sustained waste above 50% of
    # padded rows means the traffic's natural batch sizes sit just past
    # tier boundaries — the operator should revisit the bucket grid or
    # raise batch_delay_ms so buckets fill before dispatch
    wasteful = []
    judged = 0
    for srv in report.get("servers", []):
        parts = (srv.get("stats") or {}).get("partitions") or {}
        for pid, part in parts.items():
            sched = part.get("scheduler") or {}
            padded = int(sched.get("pad_padded_rows") or 0)
            if padded < 512:  # not enough traffic to judge
                continue
            judged += 1
            real = int(sched.get("pad_real_rows") or 0)
            if padded - real > 0.5 * padded:
                wasteful.append(
                    f"node {srv.get('node_id')} partition {pid}: "
                    f"{padded - real}/{padded} padded rows wasted "
                    f"({sched.get('padding_waste_pct')}%)"
                )
    checks.append({
        "name": "batch_padding_waste", "ok": not wasteful,
        "detail": ("; ".join(wasteful) if wasteful
                   else (f"{judged} partition(s) under 50% padding waste"
                         if judged
                         else "no bucketed traffic to judge")),
    })

    # per-space SLO burn: a space whose fast (5-minute) window burns
    # its declared error budget at page rate is a tenant-visible
    # incident — the check names the space and its burn multiple so
    # the operator knows WHO is out of budget, not just that someone is
    burning = []
    scored = 0
    for rt in report.get("routers", []):
        slo = ((rt.get("stats") or {}).get("slo") or {})
        for space, rec in slo.items():
            if not isinstance(rec, dict):
                continue
            scored += 1
            if rec.get("fast_burn"):
                burning.append(
                    f"{space} burning {rec.get('burn_fast')}x its error "
                    f"budget (router {rt.get('addr')}, "
                    f"objective {rec.get('objective')})"
                )
    checks.append({
        "name": "slo_burn", "ok": not burning,
        "detail": ("; ".join(burning) if burning
                   else (f"{scored} declared SLO(s) inside budget"
                         if scored else "no spaces declare an SLO")),
    })

    # per-tenant meter conservation: the accountant increments space
    # and total under one lock, so any mismatch is a billing bug, not
    # load noise — exact equality is the contract
    leaks = []
    for srv in report.get("servers", []):
        usage = (srv.get("stats") or {}).get("usage") or {}
        totals = usage.get("totals") or {}
        spaces_u = usage.get("spaces") or {}
        for meter, total in totals.items():
            summed = sum(int((m or {}).get(meter, 0))
                         for m in spaces_u.values())
            if summed != int(total):
                leaks.append(
                    f"node {srv.get('node_id')} meter {meter}: "
                    f"sum(spaces)={summed} != total={total}"
                )
    checks.append({
        "name": "usage_conservation", "ok": not leaks,
        "detail": ("; ".join(leaks) if leaks
                   else "per-space meters reconcile to totals exactly"),
    })

    # search-quality truth: a recall-floor breach means the cluster is
    # serving statistically-wrong answers with green replication; a
    # needs_retrain verdict means the index structure has drifted off
    # its train-time baseline. Both are actionable by name.
    bad_quality = []
    sampled_spaces = 0
    for srv in report.get("servers", []):
        q = (srv.get("stats") or {}).get("quality") or {}
        for space, rec in (q.get("recall") or {}).items():
            sampled_spaces += 1
            if rec.get("breach"):
                worst = min(
                    (t.get("estimate") for t in
                     (rec.get("recall") or {}).values()
                     if t.get("estimate") is not None),
                    default=None,
                )
                bad_quality.append(
                    f"node {srv.get('node_id')} space {space}: recall "
                    f"{worst} under floor {rec.get('floor')}"
                )
        for pid, h in (q.get("health") or {}).items():
            if h.get("needs_retrain"):
                bad_quality.append(
                    f"node {srv.get('node_id')} partition {pid} needs "
                    f"retrain: {'; '.join(h.get('reasons') or [])}"
                )
    checks.append({
        "name": "search_quality", "ok": not bad_quality,
        "detail": ("; ".join(bad_quality) if bad_quality
                   else (f"{sampled_spaces} sampled space(s) above "
                         f"floor, no retrain hints" if sampled_spaces
                         else "no shadow samples to judge")),
    })

    try:
        ok, detail = _check_obs_docs()
    except Exception as e:
        ok, detail = None, f"obs-docs check unavailable: {e}"
    checks.append({
        "name": "obs_docs",
        "ok": True if ok is None else ok,
        "skipped": ok is None,
        "detail": detail,
    })

    try:
        ok, detail = _check_lint_clean()
    except Exception as e:
        ok, detail = None, f"lint check unavailable: {e}"
    checks.append({
        "name": "lint_clean",
        "ok": True if ok is None else ok,
        "skipped": ok is None,
        "detail": detail,
    })
    return checks


def format_report(report: dict[str, Any]) -> str:
    """Human summary: the few lines an operator reads first."""
    lines = []
    health = report.get("health") or {}
    lines.append(
        f"cluster {report.get('master')}: "
        f"health={health.get('status')} "
        f"spaces={len(health.get('spaces') or [])} "
        f"ps_nodes={len(report.get('servers') or [])} "
        f"routers={len(report.get('routers') or [])}"
    )
    for srv in report.get("servers", []):
        stats = srv.get("stats") or {}
        samp = stats.get("device_sampler") or {}
        comp = srv.get("compiles") or {}
        lines.append(
            f"  ps {srv.get('node_id')} @ {srv.get('addr')}: "
            f"partitions={len(stats.get('partitions') or {})} "
            f"hbm_drift={'YES' if samp.get('drift') else 'no'} "
            f"post_warmup_compiles={comp.get('total') or 0} "
            f"slowlog={srv.get('slowlog_len')} "
            f"series={srv.get('metrics_series')}"
        )
    for rt in report.get("routers", []):
        lines.append(
            f"  router @ {rt.get('addr')}: "
            f"series={rt.get('metrics_series')}"
        )
    for chk in report.get("checks", []):
        mark = ("SKIP" if chk.get("skipped")
                else "ok" if chk["ok"] else "FAIL")
        lines.append(f"  [{mark:4}] {chk['name']}: {chk['detail']}")
    violations = report.get("violations") or []
    lines.append(
        "doctor: " + (
            f"{len(violations)} violation(s): "
            + ", ".join(v["name"] for v in violations)
            if violations else "all checks passed"
        )
    )
    return "\n".join(lines)


def run(
    master_addr: str,
    auth: tuple[str, str] | None = None,
) -> tuple[dict[str, Any], int]:
    """Collect + check. Returns (report, exit_code)."""
    report = collect(master_addr, auth=auth)
    checks = run_checks(report)
    report["checks"] = checks
    report["violations"] = [
        c for c in checks if not c["ok"] and not c.get("skipped")
    ]
    return report, (1 if report["violations"] else 0)


def main(argv: list[str]) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m vearch_tpu doctor",
        description="collect cluster evidence and check the standing "
                    "runtime invariants",
    )
    p.add_argument("--master", required=True, help="master HOST:PORT")
    p.add_argument("--user", default=None)
    p.add_argument("--password", default=None)
    p.add_argument("--json", action="store_true",
                   help="emit the full JSON report instead of the "
                        "human summary")
    args = p.parse_args(argv)
    auth = (
        (args.user, args.password)
        if args.user is not None and args.password is not None else None
    )
    report, code = run(args.master, auth=auth)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_report(report))
    return code

"""Runtime truth layer: live telemetry for the perf-model invariants.

CI asserts the footprint model, the zero-retrace gate, and the dispatch
ledgers (`ops/perf_model.py`, `tests/test_perf_gates.py`); nothing in a
*running* cluster used to measure whether device reality still matched.
This package closes that gap:

- ``quantiles``        fixed-memory P^2 latency sketches (tail gauges)
- ``flight_recorder``  ring buffer of post-warmup serving compiles
- ``sampler``          per-PS daemon reading the JAX runtime (live
                       HBM bytes, H2D counters, compiled programs,
                       footprint-model drift)
- ``doctor``           cluster-wide collector + invariant checks
- ``quality``          search-quality truth: shadow exact-rerank recall
                       sampling + index-health drift gauges
- ``accounting``       per-space cost meters + SLO burn engine

Nothing here dispatches device programs (lint VL101: obs/ is not a
dispatch package); the sampler only *reads* runtime introspection
surfaces.
"""

"""Device-runtime sampler: measure what the perf model only predicts.

A named daemon per PS periodically reads the JAX runtime:

- **live HBM bytes per device** — ``device.memory_stats()`` where the
  backend implements it (TPU/GPU), else summing the addressable shards
  of ``jax.live_arrays()`` per device (the CPU backend's only truthful
  accounting);
- **host->device transfer bytes** — the process-wide accumulator the
  mesh row caches and engine upload sites feed
  (``ops.perf_model.note_h2d_bytes``);
- **compiled program count** — ``perf_model.total_compiled_programs()``;
- **footprint-model drift** — measured live bytes vs the sum of the
  node's engines' ``VectorIndex.device_footprint_per_device_bytes()``.

Drift is one-sided: untracked allocations only push *measured* above
*model*, so the signal is ``max(0, measured - model - baseline)`` per
device, flagged when it exceeds ``slack + tolerance * model``. The
baseline is captured at sampler start so runtime-constant overheads
(weights of warmed programs, other tenants in shared test processes)
do not read as drift; what flips the flag is growth the model does not
know about. The flag rides the PS heartbeat into the master, which
degrades the ``/cluster/health`` rollup.

Everything here *reads* introspection surfaces — no dispatches, no
blocking on device work.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from vearch_tpu.ops import perf_model
from vearch_tpu.tools import lockcheck


def device_label(dev: Any) -> str:
    """Stable bounded label for a local device, e.g. ``cpu:0``."""
    return f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', 0)}"


def measure_live_bytes() -> dict[str, int]:
    """Per-device live buffer bytes from the runtime.

    Prefers ``device.memory_stats()['bytes_in_use']`` (TPU/GPU); falls
    back to walking ``jax.live_arrays()`` and attributing each
    addressable shard to its device (CPU backend).
    """
    import jax

    out: dict[str, int] = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            out[device_label(d)] = int(stats["bytes_in_use"])
    if out:
        return out
    acc: dict[str, int] = {
        device_label(d): 0 for d in jax.local_devices()
    }
    for arr in jax.live_arrays():
        try:
            for sh in arr.addressable_shards:
                lbl = device_label(sh.device)
                acc[lbl] = acc.get(lbl, 0) + int(sh.data.nbytes)
        except Exception:
            # deleted/donated buffers race the walk; skip, next sample
            # sees a consistent view
            continue
    return acc


@lockcheck.guarded
class DeviceSampler:
    """Named daemon sampling the JAX runtime on a fixed interval.

    ``model_bytes_fn`` returns the node's modeled per-device resident
    bytes (sum of engine index footprints). ``snapshot()`` hands the
    last sample to metric callbacks and ``/ps/stats``; ``sample_now()``
    forces a synchronous sample (tests, doctor probes).
    """

    _guarded_by = {"_state": "_lock"}

    def __init__(
        self,
        model_bytes_fn: Callable[[], int],
        interval_s: float = 5.0,
        drift_tolerance: float = 0.5,
        drift_slack_bytes: int = 64 << 20,
        name: str = "ps-device-sampler",
    ):
        self.model_bytes_fn = model_bytes_fn
        self.interval_s = float(interval_s)
        self.drift_tolerance = float(drift_tolerance)
        self.drift_slack_bytes = int(drift_slack_bytes)
        self._name = name
        self._lock = lockcheck.make_lock("obs.sampler")
        self._state: dict[str, Any] = {
            "samples": 0,
            "devices": {},
            "h2d_bytes_total": 0,
            "compiled_programs": 0,
            "model_per_device_bytes": 0,
            "baseline_per_device_bytes": {},
            "drift_bytes": 0,
            "drift": False,
        }
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        # first sample is synchronous so metric callbacks render real
        # values (and the full label set) from the very first scrape —
        # the cardinality soak baselines at that point
        self.sample_now()
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_now()
            except Exception:
                # sampling must never take the PS down; the stale
                # `samples` count makes a wedged sampler visible
                continue

    def sample_now(self) -> dict[str, Any]:
        devices = measure_live_bytes()
        model = int(self.model_bytes_fn() or 0)
        h2d = perf_model.h2d_bytes_total()
        compiled = perf_model.total_compiled_programs()
        with self._lock:
            first = self._state["samples"] == 0
            if first:
                # runtime-constant overhead the footprint model never
                # claimed to cover (compiled constants, co-tenants):
                # everything resident before serving starts
                self._state["baseline_per_device_bytes"] = {
                    lbl: max(0, b - model)
                    for lbl, b in devices.items()
                }
            base = self._state["baseline_per_device_bytes"]
            drift_bytes = 0
            for lbl, measured in devices.items():
                excess = measured - model - base.get(lbl, 0)
                drift_bytes = max(drift_bytes, excess)
            drift_bytes = max(0, drift_bytes)
            drift = drift_bytes > (
                self.drift_slack_bytes + self.drift_tolerance * model
            )
            self._state.update({
                "samples": self._state["samples"] + 1,
                "devices": devices,
                "h2d_bytes_total": h2d,
                "compiled_programs": compiled,
                "model_per_device_bytes": model,
                "drift_bytes": int(drift_bytes),
                "drift": bool(drift),
            })
            return dict(self._state)

    def rebaseline(self) -> None:
        """Re-capture the baseline (tests; operator after planned
        topology changes)."""
        with self._lock:
            self._state["samples"] = 0
        self.sample_now()

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            out = dict(self._state)
            out["devices"] = dict(out["devices"])
            return out


def monotonic_ms() -> float:
    """Shared latency clock for quantile observation sites."""
    return time.monotonic() * 1000.0

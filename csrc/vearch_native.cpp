// Native host-side hot loops for vearch-tpu.
//
// The reference implements its entire engine in C++ (internal/engine/);
// in the TPU-native re-design the dense math lives on the accelerator and
// the *host* hot loops move here instead:
//   - murmur3_batch: bulk doc-key -> slot hashing for the router's
//     PartitionDocs path (reference: client/client.go:245 murmur3.Sum32)
//   - merge_topk: the router's cross-partition top-k merge
//     (reference: client/client.go:779 sorted merge)
//   - read_fvecs / write_fvecs: .fvecs/.ivecs dataset IO
//     (reference: test/utils/data_utils.py readers, engine tools/)
//
// Built as a plain CPython extension (no pybind11 in this image); the
// python wrapper (vearch_tpu/native/__init__.py) compiles it on demand
// with g++ and falls back to numpy implementations when unavailable.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

uint32_t murmur3_32(const uint8_t* data, size_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
  uint32_t h = seed;
  const size_t nblocks = len / 4;
  for (size_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + i * 4, 4);
    k *= c1;
    k = (k << 15) | (k >> 17);
    k *= c2;
    h ^= k;
    h = (h << 13) | (h >> 19);
    h = h * 5 + 0xe6546b64u;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3:
      k ^= static_cast<uint32_t>(tail[2]) << 16;
      [[fallthrough]];
    case 2:
      k ^= static_cast<uint32_t>(tail[1]) << 8;
      [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= c1;
      k = (k << 15) | (k >> 17);
      k *= c2;
      h ^= k;
  }
  h ^= static_cast<uint32_t>(len);
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

// murmur3_batch(keys: list[bytes|str], seed=0) -> bytes (u32 LE array)
PyObject* py_murmur3_batch(PyObject*, PyObject* args) {
  PyObject* keys;
  unsigned int seed = 0;
  if (!PyArg_ParseTuple(args, "O|I", &keys, &seed)) return nullptr;
  PyObject* seq = PySequence_Fast(keys, "keys must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* out = PyBytes_FromStringAndSize(nullptr, n * 4);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  auto* dst =
      reinterpret_cast<uint32_t*>(PyBytes_AS_STRING(out));
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    const char* buf;
    Py_ssize_t len;
    PyObject* tmp = nullptr;
    if (PyUnicode_Check(item)) {
      buf = PyUnicode_AsUTF8AndSize(item, &len);
      if (!buf) {
        Py_DECREF(seq);
        Py_DECREF(out);
        return nullptr;
      }
    } else if (PyBytes_Check(item)) {
      buf = PyBytes_AS_STRING(item);
      len = PyBytes_GET_SIZE(item);
    } else {
      tmp = PyObject_Str(item);
      if (!tmp) {
        Py_DECREF(seq);
        Py_DECREF(out);
        return nullptr;
      }
      buf = PyUnicode_AsUTF8AndSize(tmp, &len);
      if (!buf) {
        Py_XDECREF(tmp);
        Py_DECREF(seq);
        Py_DECREF(out);
        return nullptr;
      }
    }
    dst[i] = murmur3_32(reinterpret_cast<const uint8_t*>(buf),
                        static_cast<size_t>(len), seed);
    Py_XDECREF(tmp);
  }
  Py_DECREF(seq);
  return out;
}

// merge_topk(scores: bytes f32[B*M], ids: bytes i64[B*M], B, M, k,
//            descending) -> (bytes f32[B*k], bytes i64[B*k])
PyObject* py_merge_topk(PyObject*, PyObject* args) {
  Py_buffer scores_buf, ids_buf;
  Py_ssize_t b, m, k;
  int descending = 1;
  if (!PyArg_ParseTuple(args, "y*y*nnn|p", &scores_buf, &ids_buf, &b, &m,
                        &k, &descending))
    return nullptr;
  if (scores_buf.len < static_cast<Py_ssize_t>(b * m * sizeof(float)) ||
      ids_buf.len < static_cast<Py_ssize_t>(b * m * sizeof(int64_t))) {
    PyBuffer_Release(&scores_buf);
    PyBuffer_Release(&ids_buf);
    PyErr_SetString(PyExc_ValueError, "buffer too small for B*M");
    return nullptr;
  }
  if (k > m) k = m;
  const float* scores = static_cast<const float*>(scores_buf.buf);
  const int64_t* ids = static_cast<const int64_t*>(ids_buf.buf);
  PyObject* out_s = PyBytes_FromStringAndSize(nullptr, b * k * sizeof(float));
  PyObject* out_i =
      PyBytes_FromStringAndSize(nullptr, b * k * sizeof(int64_t));
  if (!out_s || !out_i) {
    Py_XDECREF(out_s);
    Py_XDECREF(out_i);
    PyBuffer_Release(&scores_buf);
    PyBuffer_Release(&ids_buf);
    return nullptr;
  }
  auto* os = reinterpret_cast<float*>(PyBytes_AS_STRING(out_s));
  auto* oi = reinterpret_cast<int64_t*>(PyBytes_AS_STRING(out_i));
  std::vector<int32_t> idx(m);
  Py_BEGIN_ALLOW_THREADS;
  for (Py_ssize_t row = 0; row < b; row++) {
    const float* s = scores + row * m;
    const int64_t* id = ids + row * m;
    for (Py_ssize_t j = 0; j < m; j++) idx[j] = static_cast<int32_t>(j);
    auto cmp = [&](int32_t a, int32_t c) {
      return descending ? s[a] > s[c] : s[a] < s[c];
    };
    std::partial_sort(idx.begin(), idx.begin() + k, idx.end(), cmp);
    for (Py_ssize_t j = 0; j < k; j++) {
      os[row * k + j] = s[idx[j]];
      oi[row * k + j] = id[idx[j]];
    }
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&scores_buf);
  PyBuffer_Release(&ids_buf);
  return PyTuple_Pack(2, out_s, out_i);
}

// read_fvecs(path, max_n=-1) -> (bytes f32 data, n, d); .ivecs identical
// layout with i32 payload (caller reinterprets).
PyObject* py_read_fvecs(PyObject*, PyObject* args) {
  const char* path;
  Py_ssize_t max_n = -1;
  if (!PyArg_ParseTuple(args, "s|n", &path, &max_n)) return nullptr;
  FILE* f = fopen(path, "rb");
  if (!f) {
    PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
    return nullptr;
  }
  int32_t d = 0;
  if (fread(&d, 4, 1, f) != 1 || d <= 0 || d > (1 << 20)) {
    fclose(f);
    PyErr_SetString(PyExc_ValueError, "bad fvecs header");
    return nullptr;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  long row_bytes = 4L + 4L * d;
  long n = size / row_bytes;
  if (max_n >= 0 && n > max_n) n = max_n;
  fseek(f, 0, SEEK_SET);
  PyObject* out = PyBytes_FromStringAndSize(nullptr, n * 4L * d);
  if (!out) {
    fclose(f);
    return nullptr;
  }
  char* dst = PyBytes_AS_STRING(out);
  bool ok = true;
  Py_BEGIN_ALLOW_THREADS;
  for (long i = 0; i < n; i++) {
    int32_t dim;
    if (fread(&dim, 4, 1, f) != 1 || dim != d ||
        fread(dst + i * 4L * d, 4, static_cast<size_t>(d), f) !=
            static_cast<size_t>(d)) {
      ok = false;
      break;
    }
  }
  Py_END_ALLOW_THREADS;
  fclose(f);
  if (!ok) {
    Py_DECREF(out);
    PyErr_SetString(PyExc_ValueError, "truncated/inconsistent fvecs file");
    return nullptr;
  }
  return Py_BuildValue("(Nnn)", out, static_cast<Py_ssize_t>(n),
                       static_cast<Py_ssize_t>(d));
}

PyMethodDef methods[] = {
    {"murmur3_batch", py_murmur3_batch, METH_VARARGS,
     "Batch murmur3-32 of a sequence of keys -> u32 LE bytes"},
    {"merge_topk", py_merge_topk, METH_VARARGS,
     "Per-row partial-sort top-k merge over concatenated candidates"},
    {"read_fvecs", py_read_fvecs, METH_VARARGS,
     "Read an .fvecs/.ivecs file -> (bytes, n, d)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "vearch_native",
    "Native host hot loops for vearch-tpu", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_vearch_native(void) { return PyModule_Create(&module); }

// Hierarchical Navigable Small World graph index (host-side).
//
// The reference vendors hnswlib (reference: internal/engine/index/impl/
// hnswlib/gamma_index_hnswlib.cc:130). This is an independent
// implementation of the HNSW algorithm (Malkov & Yashunin, 2016) written
// for this framework's host runtime: the TPU serves dense scans for
// HBM-resident rows; the graph serves the beyond-HBM / low-latency-
// single-query regime where a pointer walk on the host beats shipping a
// batch to the device (index/hnsw.py picks the path).
//
// Design:
//   - flat storage: one contiguous f32 data block (grown by doubling) +
//     per-level neighbor arrays, M neighbors per node per level
//     (2M at level 0, as in the paper);
//   - insert: geometric level draw, greedy descent from the entry point,
//     searchLayer(efConstruction) per level, neighbor selection by the
//     paper's heuristic (closest-first with dominance pruning);
//   - search: greedy descent to level 1, searchLayer(ef) at level 0 with
//     an optional validity bitmap (soft-deleted/filtered docs are
//     excluded from results but still traversed, the standard filtered-
//     HNSW behavior);
//   - exposed through opaque integer handles (no PyTypeObject needed);
//     the python wrapper (vearch_tpu/native/__init__.py) owns handle
//     lifetime and locking (single writer; readers serialized by GIL).
//
// Metric: L2 or inner product. Scores returned similarity-oriented
// (higher = better): -distance^2 for L2, dot for IP.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <queue>
#include <random>
#include <unordered_map>
#include <vector>

namespace {

struct Hnsw {
  int dim = 0;
  int M = 16;
  int M0 = 32;           // level-0 degree (2*M)
  int ef_construction = 200;
  bool ip = false;       // false: L2, true: inner product
  double level_mult = 0; // 1/ln(M)
  std::mt19937_64 rng{0x5eed};

  int64_t n = 0;
  std::vector<float> data;              // [n, dim]
  std::vector<int32_t> levels;          // level per node
  // links[l] is a flat [n_at_or_above_l? no: n] * degree array; we keep
  // per-node vectors per level for simplicity of growth
  std::vector<std::vector<std::vector<int32_t>>> links;  // [node][level] -> neighbors
  int32_t entry = -1;
  int32_t max_level = -1;

  const float* vec(int64_t i) const { return data.data() + i * dim; }

  float dist(const float* a, const float* b) const {
    float acc = 0.f;
    if (ip) {
      for (int j = 0; j < dim; j++) acc += a[j] * b[j];
      return -acc;  // smaller = better internally
    }
    for (int j = 0; j < dim; j++) {
      const float t = a[j] - b[j];
      acc += t * t;
    }
    return acc;
  }

  int draw_level() {
    std::uniform_real_distribution<double> u(0.0, 1.0);
    double r = u(rng);
    if (r < 1e-12) r = 1e-12;
    return static_cast<int>(-std::log(r) * level_mult);
  }

  // search one layer from `ep`, keeping up to `ef` best candidates.
  // `valid` (nullable) filters which nodes may land in `best`; the
  // traversal frontier still crosses invalid nodes (standard filtered-
  // HNSW: dense deletes must not strand the walk, and k valid results
  // must survive — the index contract in index/base.py).
  // visited marker array is caller-provided (epoch trick).
  void search_layer(const float* q, int32_t ep_node, float ep_d, int level,
                    size_t ef, const uint8_t* valid,
                    std::vector<uint32_t>& visited, uint32_t epoch,
                    std::priority_queue<std::pair<float, int32_t>>& best)
      const {
    // best: max-heap on distance (worst on top), size <= ef
    using PD = std::pair<float, int32_t>;
    std::priority_queue<PD, std::vector<PD>, std::greater<PD>> cand;
    cand.emplace(ep_d, ep_node);
    if (!valid || valid[ep_node]) best.emplace(ep_d, ep_node);
    visited[ep_node] = epoch;
    while (!cand.empty()) {
      auto [cd, cn] = cand.top();
      if (best.size() >= ef && cd > best.top().first) break;
      cand.pop();
      for (int32_t nb : links[cn][level]) {
        if (visited[nb] == epoch) continue;
        visited[nb] = epoch;
        const float d = dist(q, vec(nb));
        if (best.size() < ef || d < best.top().first) {
          cand.emplace(d, nb);
          if (!valid || valid[nb]) {
            best.emplace(d, nb);
            if (best.size() > ef) best.pop();
          }
        }
      }
    }
  }

  // neighbor selection heuristic (paper alg. 4): pick up to m closest
  // candidates such that each kept candidate is closer to q than to any
  // already-kept one (dominance pruning keeps the graph navigable).
  void select_neighbors(const float* q,
                        std::vector<std::pair<float, int32_t>>& cand,
                        int m, std::vector<int32_t>& out) const {
    std::sort(cand.begin(), cand.end());
    out.clear();
    for (const auto& [d, node] : cand) {
      if (static_cast<int>(out.size()) >= m) break;
      bool dominated = false;
      for (int32_t kept : out) {
        if (dist(vec(node), vec(kept)) < d) {
          dominated = true;
          break;
        }
      }
      if (!dominated) out.push_back(node);
    }
    // backfill with closest dominated candidates if underfull (keeps
    // degree up in clustered data)
    if (static_cast<int>(out.size()) < m) {
      for (const auto& [d, node] : cand) {
        if (static_cast<int>(out.size()) >= m) break;
        if (std::find(out.begin(), out.end(), node) == out.end())
          out.push_back(node);
      }
    }
  }

  std::vector<uint32_t> visited_;
  uint32_t epoch_ = 0;

  void add_one(const float* q) {
    const int32_t id = static_cast<int32_t>(n);
    const int lvl = draw_level();
    levels.push_back(lvl);
    links.emplace_back(lvl + 1);
    data.insert(data.end(), q, q + dim);
    n++;
    visited_.resize(n, 0);

    if (entry < 0) {
      entry = id;
      max_level = lvl;
      return;
    }
    int32_t ep = entry;
    float ep_d = dist(q, vec(ep));
    // greedy descent through levels above lvl
    for (int l = max_level; l > lvl; l--) {
      bool improved = true;
      while (improved) {
        improved = false;
        for (int32_t nb : links[ep][l]) {
          const float d = dist(q, vec(nb));
          if (d < ep_d) {
            ep_d = d;
            ep = nb;
            improved = true;
          }
        }
      }
    }
    // connect at each level from min(lvl, max_level) down to 0
    for (int l = std::min(lvl, max_level); l >= 0; l--) {
      std::priority_queue<std::pair<float, int32_t>> best;
      if (++epoch_ == 0) {  // epoch wrap: clear markers
        std::fill(visited_.begin(), visited_.end(), 0u);
        epoch_ = 1;
      }
      search_layer(q, ep, ep_d, l, ef_construction, nullptr, visited_,
                   epoch_, best);
      std::vector<std::pair<float, int32_t>> cand;
      cand.reserve(best.size());
      while (!best.empty()) {
        cand.push_back(best.top());
        best.pop();
      }
      const int m = (l == 0) ? M0 : M;
      std::vector<int32_t> nbrs;
      select_neighbors(q, cand, m, nbrs);
      links[id][l] = nbrs;
      // backlinks + prune overfull neighbors
      for (int32_t nb : nbrs) {
        auto& nl = links[nb][l];
        nl.push_back(id);
        if (static_cast<int>(nl.size()) > m) {
          std::vector<std::pair<float, int32_t>> nc;
          nc.reserve(nl.size());
          for (int32_t x : nl) nc.emplace_back(dist(vec(nb), vec(x)), x);
          std::vector<int32_t> pruned;
          select_neighbors(vec(nb), nc, m, pruned);
          nl = pruned;
        }
      }
      if (!cand.empty()) {
        ep = cand.front().second;
        ep_d = cand.front().first;
      }
    }
    if (lvl > max_level) {
      max_level = lvl;
      entry = id;
    }
  }

  // k best valid nodes for one query; valid==nullptr means all valid
  void search(const float* q, int k, int ef, const uint8_t* valid,
              std::vector<std::pair<float, int32_t>>& out) {
    out.clear();
    if (entry < 0) return;
    int32_t ep = entry;
    float ep_d = dist(q, vec(ep));
    for (int l = max_level; l > 0; l--) {
      bool improved = true;
      while (improved) {
        improved = false;
        for (int32_t nb : links[ep][l]) {
          const float d = dist(q, vec(nb));
          if (d < ep_d) {
            ep_d = d;
            ep = nb;
            improved = true;
          }
        }
      }
    }
    std::priority_queue<std::pair<float, int32_t>> best;
    if (++epoch_ == 0) {
      std::fill(visited_.begin(), visited_.end(), 0u);
      epoch_ = 1;
    }
    const size_t ef_eff = static_cast<size_t>(std::max(ef, k));
    search_layer(q, ep, ep_d, 0, ef_eff, valid, visited_, epoch_, best);
    std::vector<std::pair<float, int32_t>> cand;
    cand.reserve(best.size());
    while (!best.empty()) {
      cand.push_back(best.top());
      best.pop();
    }
    std::sort(cand.begin(), cand.end());
    for (const auto& [d, node] : cand) {
      if (static_cast<int>(out.size()) >= k) break;
      out.emplace_back(d, node);
    }
  }
};

std::unordered_map<int64_t, Hnsw*> g_graphs;
int64_t g_next = 1;

Hnsw* get_graph(int64_t h) {
  auto it = g_graphs.find(h);
  if (it == g_graphs.end()) {
    PyErr_SetString(PyExc_ValueError, "invalid hnsw handle");
    return nullptr;
  }
  return it->second;
}

// hnsw_new(dim, M, ef_construction, ip: int, seed) -> handle
PyObject* py_hnsw_new(PyObject*, PyObject* args) {
  int dim, M, efc, ip;
  unsigned long long seed = 0x5eed;
  if (!PyArg_ParseTuple(args, "iiii|K", &dim, &M, &efc, &ip, &seed))
    return nullptr;
  auto* g = new Hnsw();
  g->dim = dim;
  g->M = std::max(2, M);
  g->M0 = 2 * g->M;
  g->ef_construction = std::max(efc, g->M0);
  g->ip = ip != 0;
  g->level_mult = 1.0 / std::log(static_cast<double>(g->M));
  g->rng.seed(seed);
  const int64_t h = g_next++;
  g_graphs[h] = g;
  return PyLong_FromLongLong(h);
}

PyObject* py_hnsw_free(PyObject*, PyObject* args) {
  long long h;
  if (!PyArg_ParseTuple(args, "L", &h)) return nullptr;
  auto it = g_graphs.find(h);
  if (it != g_graphs.end()) {
    delete it->second;
    g_graphs.erase(it);
  }
  Py_RETURN_NONE;
}

// hnsw_add(handle, rows: buffer f32[b*dim], b) -> first assigned id
PyObject* py_hnsw_add(PyObject*, PyObject* args) {
  long long h;
  Py_buffer buf;
  Py_ssize_t b;
  if (!PyArg_ParseTuple(args, "Ly*n", &h, &buf, &b)) return nullptr;
  Hnsw* g = get_graph(h);
  if (!g) {
    PyBuffer_Release(&buf);
    return nullptr;
  }
  if (buf.len < static_cast<Py_ssize_t>(b * g->dim * sizeof(float))) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "row buffer too small");
    return nullptr;
  }
  const float* rows = static_cast<const float*>(buf.buf);
  const int64_t first = g->n;
  Py_BEGIN_ALLOW_THREADS;
  for (Py_ssize_t i = 0; i < b; i++) g->add_one(rows + i * g->dim);
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&buf);
  return PyLong_FromLongLong(first);
}

// hnsw_search(handle, queries f32[B*dim], B, k, ef, valid u8[n]|None)
//   -> (bytes f32 scores[B*k], bytes i64 ids[B*k])  (-inf/-1 padding)
PyObject* py_hnsw_search(PyObject*, PyObject* args) {
  long long h;
  Py_buffer qbuf;
  Py_ssize_t B, k;
  int ef;
  PyObject* valid_obj = Py_None;
  if (!PyArg_ParseTuple(args, "Ly*nni|O", &h, &qbuf, &B, &k, &ef,
                        &valid_obj))
    return nullptr;
  Hnsw* g = get_graph(h);
  if (!g) {
    PyBuffer_Release(&qbuf);
    return nullptr;
  }
  if (qbuf.len < static_cast<Py_ssize_t>(B * g->dim * sizeof(float))) {
    PyBuffer_Release(&qbuf);
    PyErr_SetString(PyExc_ValueError, "query buffer too small for B*dim");
    return nullptr;
  }
  Py_buffer vbuf;
  const uint8_t* valid = nullptr;
  bool have_v = false;
  if (valid_obj != Py_None) {
    if (PyObject_GetBuffer(valid_obj, &vbuf, PyBUF_SIMPLE) != 0) {
      PyBuffer_Release(&qbuf);
      return nullptr;
    }
    if (vbuf.len < g->n) {
      PyBuffer_Release(&vbuf);
      PyBuffer_Release(&qbuf);
      PyErr_SetString(PyExc_ValueError, "valid mask shorter than n");
      return nullptr;
    }
    valid = static_cast<const uint8_t*>(vbuf.buf);
    have_v = true;
  }
  PyObject* out_s = PyBytes_FromStringAndSize(nullptr, B * k * sizeof(float));
  PyObject* out_i =
      PyBytes_FromStringAndSize(nullptr, B * k * sizeof(int64_t));
  if (!out_s || !out_i) {
    Py_XDECREF(out_s);
    Py_XDECREF(out_i);
    if (have_v) PyBuffer_Release(&vbuf);
    PyBuffer_Release(&qbuf);
    return nullptr;
  }
  auto* os = reinterpret_cast<float*>(PyBytes_AS_STRING(out_s));
  auto* oi = reinterpret_cast<int64_t*>(PyBytes_AS_STRING(out_i));
  const float* qs = static_cast<const float*>(qbuf.buf);
  Py_BEGIN_ALLOW_THREADS;
  std::vector<std::pair<float, int32_t>> hits;
  for (Py_ssize_t qi = 0; qi < B; qi++) {
    g->search(qs + qi * g->dim, static_cast<int>(k), ef, valid, hits);
    Py_ssize_t j = 0;
    for (; j < static_cast<Py_ssize_t>(hits.size()) && j < k; j++) {
      // similarity-oriented: -L2^2; for IP internal dist is -dot, so
      // negation yields the dot either way
      os[qi * k + j] = -hits[j].first;
      oi[qi * k + j] = hits[j].second;
    }
    for (; j < k; j++) {
      os[qi * k + j] = -HUGE_VALF;
      oi[qi * k + j] = -1;
    }
  }
  Py_END_ALLOW_THREADS;
  if (have_v) PyBuffer_Release(&vbuf);
  PyBuffer_Release(&qbuf);
  return PyTuple_Pack(2, out_s, out_i);
}

// hnsw_save(handle, path) / hnsw_load(dim,M,efc,ip,path) -> handle
PyObject* py_hnsw_save(PyObject*, PyObject* args) {
  long long h;
  const char* path;
  if (!PyArg_ParseTuple(args, "Ls", &h, &path)) return nullptr;
  Hnsw* g = get_graph(h);
  if (!g) return nullptr;
  FILE* f = fopen(path, "wb");
  if (!f) {
    PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
    return nullptr;
  }
  const uint32_t magic = 0x48565354u;  // "TSVH"
  int64_t n = g->n;
  fwrite(&magic, 4, 1, f);
  fwrite(&g->dim, 4, 1, f);
  fwrite(&g->M, 4, 1, f);
  fwrite(&n, 8, 1, f);
  fwrite(&g->entry, 4, 1, f);
  fwrite(&g->max_level, 4, 1, f);
  fwrite(g->levels.data(), 4, static_cast<size_t>(n), f);
  fwrite(g->data.data(), 4, static_cast<size_t>(n) * g->dim, f);
  for (int64_t i = 0; i < n; i++) {
    for (int l = 0; l <= g->levels[i]; l++) {
      const auto& nl = g->links[i][l];
      const int32_t sz = static_cast<int32_t>(nl.size());
      fwrite(&sz, 4, 1, f);
      fwrite(nl.data(), 4, static_cast<size_t>(sz), f);
    }
  }
  fclose(f);
  Py_RETURN_NONE;
}

PyObject* py_hnsw_load(PyObject*, PyObject* args) {
  int dim, M, efc, ip;
  const char* path;
  if (!PyArg_ParseTuple(args, "iiiis", &dim, &M, &efc, &ip, &path))
    return nullptr;
  FILE* f = fopen(path, "rb");
  if (!f) {
    PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
    return nullptr;
  }
  uint32_t magic = 0;
  int fdim = 0, fM = 0;
  int64_t n = 0;
  auto fail = [&](const char* msg) -> PyObject* {
    fclose(f);
    PyErr_SetString(PyExc_ValueError, msg);
    return nullptr;
  };
  if (fread(&magic, 4, 1, f) != 1 || magic != 0x48565354u)
    return fail("bad hnsw file magic");
  if (fread(&fdim, 4, 1, f) != 1 || fdim != dim)
    return fail("hnsw file dimension mismatch");
  if (fread(&fM, 4, 1, f) != 1) return fail("truncated hnsw file");
  if (fread(&n, 8, 1, f) != 1 || n < 0) return fail("truncated hnsw file");
  auto* g = new Hnsw();
  g->dim = dim;
  g->M = std::max(2, fM);
  g->M0 = 2 * g->M;
  g->ef_construction = std::max(efc, g->M0);
  g->ip = ip != 0;
  g->level_mult = 1.0 / std::log(static_cast<double>(g->M));
  bool ok = fread(&g->entry, 4, 1, f) == 1 &&
            fread(&g->max_level, 4, 1, f) == 1;
  // every loaded field that later indexes an array is bounds-checked:
  // a bit-flipped snapshot must fail the load, not segfault a search
  ok = ok && n <= (int64_t{1} << 40) && g->entry >= -1 && g->entry < n &&
       g->max_level >= -1 && g->max_level < 64 &&
       (n == 0) == (g->entry < 0);
  if (ok) {
    g->n = n;
    g->levels.resize(n);
    g->data.resize(static_cast<size_t>(n) * dim);
    g->visited_.resize(n, 0);
    ok = fread(g->levels.data(), 4, static_cast<size_t>(n), f) ==
             static_cast<size_t>(n) &&
         fread(g->data.data(), 4, static_cast<size_t>(n) * dim, f) ==
             static_cast<size_t>(n) * dim;
    for (int64_t i = 0; ok && i < n; i++)
      ok = g->levels[i] >= 0 && g->levels[i] <= g->max_level;
    // the greedy descent starts at entry and indexes links[entry][l]
    // for every l up to max_level — entry must actually live there
    if (ok && n > 0) ok = g->levels[g->entry] == g->max_level;
  }
  if (ok) {
    g->links.resize(n);
    for (int64_t i = 0; ok && i < n; i++) {
      g->links[i].resize(g->levels[i] + 1);
      for (int l = 0; ok && l <= g->levels[i]; l++) {
        int32_t sz = 0;
        ok = fread(&sz, 4, 1, f) == 1 && sz >= 0 && sz <= 4 * g->M0;
        if (ok) {
          g->links[i][l].resize(sz);
          ok = fread(g->links[i][l].data(), 4, static_cast<size_t>(sz),
                     f) == static_cast<size_t>(sz);
          for (int32_t nb : g->links[i][l])
            ok = ok && nb >= 0 && nb < n && g->levels[nb] >= l;
        }
      }
    }
  }
  fclose(f);
  if (!ok) {
    delete g;
    PyErr_SetString(PyExc_ValueError, "truncated/corrupt hnsw file");
    return nullptr;
  }
  const int64_t h = g_next++;
  g_graphs[h] = g;
  return PyLong_FromLongLong(h);
}

PyObject* py_hnsw_count(PyObject*, PyObject* args) {
  long long h;
  if (!PyArg_ParseTuple(args, "L", &h)) return nullptr;
  Hnsw* g = get_graph(h);
  if (!g) return nullptr;
  return PyLong_FromLongLong(g->n);
}

PyMethodDef methods[] = {
    {"hnsw_new", py_hnsw_new, METH_VARARGS, "Create a graph -> handle"},
    {"hnsw_free", py_hnsw_free, METH_VARARGS, "Destroy a graph"},
    {"hnsw_add", py_hnsw_add, METH_VARARGS, "Append rows -> first id"},
    {"hnsw_search", py_hnsw_search, METH_VARARGS,
     "Filtered k-NN search -> (scores bytes, ids bytes)"},
    {"hnsw_save", py_hnsw_save, METH_VARARGS, "Serialize graph to file"},
    {"hnsw_load", py_hnsw_load, METH_VARARGS, "Load graph from file"},
    {"hnsw_count", py_hnsw_count, METH_VARARGS, "Node count"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "vearch_hnsw",
    "HNSW graph index (host-side) for vearch-tpu", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_vearch_hnsw(void) { return PyModule_Create(&module); }

#!/usr/bin/env python
"""Cluster-profile smoke suite: the process-level equivalent of the
reference's cluster CI (reference: .github/workflows/CI_cluster.yml:33-51
runs pytest against the docker-compose fabric of 3 masters + routers +
PSes + MinIO, with failure injection).

One command brings up the full topology as REAL subprocesses of
`python -m vearch_tpu` — 3 metadata-raft masters, 2 routers, 3 partition
servers — plus an in-process S3 endpoint, then asserts:

  1. replicated writes + search through BOTH routers;
  2. master leader kill -9 -> API keeps serving via the survivors;
  3. S3 backup -> delete-all -> restore round-trip;
  4. PS kill -9 -> replicated partition keeps serving reads.

Exit 0 = all green. Run: `python cloud/smoke.py` (CPU-only, ~2 min).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from vearch_tpu.cluster import rpc  # noqa: E402
from vearch_tpu.cluster.rpc import RpcError  # noqa: E402
from vearch_tpu.sdk.client import VearchClient  # noqa: E402
from tests.test_objectstore_s3 import MockS3  # noqa: E402

D = 8
ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def spawn(args: list[str]) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "vearch_tpu", *args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=ENV, cwd=REPO,
    )


def wait_http(addr: str, deadline: float = 60.0) -> None:
    t0 = time.time()
    while time.time() - t0 < deadline:
        try:
            rpc.call(addr, "GET", "/", timeout=2.0)
            return
        except RpcError:
            time.sleep(0.3)
    raise SystemExit(f"FAIL: {addr} did not come up")


def main(data_dir: str) -> int:
    mports = free_ports(3)
    rports = free_ports(2)
    gports = free_ports(2)
    try:
        import grpc as _grpc  # noqa: F401

        has_grpc = subprocess.run(
            ["protoc", "--version"], capture_output=True
        ).returncode == 0
    except Exception:
        has_grpc = False
    peers = ",".join(f"{i + 1}=127.0.0.1:{p}" for i, p in enumerate(mports))
    master_list = ",".join(f"127.0.0.1:{p}" for p in mports)
    procs: dict[str, subprocess.Popen] = {}
    s3 = MockS3()
    try:
        for i, p in enumerate(mports):
            procs[f"master{i + 1}"] = spawn([
                "--role", "master", "--port", str(p),
                "--data-dir", f"{data_dir}/m{i + 1}",
                "--node-id", str(i + 1), "--peers", peers,
            ])
        for addr in master_list.split(","):
            wait_http(addr)
        for i in range(3):
            procs[f"ps{i + 1}"] = spawn([
                "--role", "ps", "--data-dir", f"{data_dir}/ps{i + 1}",
                "--master-addr", master_list,
            ])
        for i, p in enumerate(rports):
            procs[f"router{i + 1}"] = spawn([
                "--role", "router", "--port", str(p),
                "--master-addr", master_list,
                # only when servable: a router crashes at startup if
                # asked for gRPC without grpcio/protoc in the image
                *(["--grpc-port", str(gports[i])] if has_grpc else []),
            ])
        r1 = VearchClient(f"127.0.0.1:{rports[0]}")
        r2 = VearchClient(f"127.0.0.1:{rports[1]}")
        t0 = time.time()
        while not (r1.is_live() and r2.is_live()):
            if time.time() - t0 > 60:
                raise SystemExit("FAIL: routers never went live")
            time.sleep(0.5)
        # all 3 PS registered?
        t0 = time.time()
        while len(rpc.call(master_list, "GET", "/servers")["servers"]) < 3:
            if time.time() - t0 > 60:
                raise SystemExit("FAIL: <3 PS registered")
            time.sleep(0.5)

        # 1. replicated space, writes via r1, reads via r2
        r1.create_database("db")
        r1.create_space("db", {
            "name": "s", "partition_num": 3, "replica_num": 2,
            "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                        "index": {"index_type": "FLAT", "metric_type": "L2",
                                  "params": {}}}],
        })
        rng = np.random.default_rng(0)
        vecs = rng.standard_normal((120, D)).astype(np.float32)
        r1.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                              for i in range(120)])
        hits = r2.search("db", "s", [{"field": "v", "feature": vecs[7]}],
                         limit=1)
        assert hits[0][0]["_id"] == "d7", hits
        print("smoke 1 OK: replicated writes, cross-router reads")

        # 2. kill -9 the master leader; the API must keep serving
        leader = None
        for name in ("master1", "master2", "master3"):
            idx = int(name[-1]) - 1
            st = rpc.call(f"127.0.0.1:{mports[idx]}", "GET", "/")
            if st.get("meta_leader"):
                leader = name
                break
        leader = leader or "master1"
        procs[leader].send_signal(signal.SIGKILL)
        procs[leader].wait()
        t0 = time.time()
        while True:
            try:
                r2.get_space("db", "s")
                break
            except RpcError:
                if time.time() - t0 > 60:
                    raise SystemExit(
                        "FAIL: API dead after master leader kill")
                time.sleep(0.5)
        hits = r1.search("db", "s", [{"field": "v", "feature": vecs[9]}],
                         limit=1)
        assert hits[0][0]["_id"] == "d9"
        # mutations need a NEW metadata leader elected among survivors
        t0 = time.time()
        while True:
            leads = []
            for name, proc in procs.items():
                if not name.startswith("master") or proc.poll() is not None:
                    continue
                idx = int(name[-1]) - 1
                try:
                    st = rpc.call(f"127.0.0.1:{mports[idx]}", "GET", "/",
                                  timeout=2.0)
                    leads.append(bool(st.get("meta_leader")))
                except RpcError:
                    pass
            if any(leads):
                break
            if time.time() - t0 > 60:
                raise SystemExit("FAIL: no new metadata leader elected")
            time.sleep(0.5)
        print(f"smoke 2 OK: {leader} killed, survivors re-elected + serve")

        # 3. S3 backup -> wipe -> restore
        spec = {"type": "s3", "endpoint": s3.addr, "bucket": "bk",
                "access_key": "ak", "secret_key": "sk"}
        out = rpc.call(master_list, "POST", "/backup/dbs/db/spaces/s",
                       {"command": "create", "store": spec})
        assert out["version"] == 1, out
        r1.delete("db", "s", document_ids=[f"d{i}" for i in range(120)])
        assert r1.search("db", "s", [{"field": "v", "feature": vecs[7]}],
                         limit=1)[0] == []
        out = rpc.call(master_list, "POST", "/backup/dbs/db/spaces/s",
                       {"command": "restore", "store": spec, "version": 1})
        assert sum(p["doc_count"] for p in out["partitions"]) == 120
        hits = r2.search("db", "s", [{"field": "v", "feature": vecs[7]}],
                         limit=1)
        assert hits[0][0]["_id"] == "d7"
        print("smoke 3 OK: S3 backup/restore round-trip")

        # 3b. round-4 surface: gRPC front door + online field index
        if not has_grpc:
            print("smoke 3b SKIP: grpcio/protoc not installed")
        else:
            import grpc as grpclib

            from vearch_tpu.cluster.grpc_server import load_pb2

            pb2 = load_pb2()
            ch = grpclib.insecure_channel(f"127.0.0.1:{gports[0]}")
            se = ch.unary_unary(
                "/vearch_tpu.Router/Search",
                request_serializer=pb2.SearchRequest.SerializeToString,
                response_deserializer=pb2.SearchResponse.FromString,
            )
            resp = se(pb2.SearchRequest(
                db_name="db", space_name="s",
                vectors=[pb2.VectorQuery(field="v",
                                         feature=vecs[7].tolist())],
                limit=1), timeout=30)
            assert resp.results[0].items[0].id == "d7"
            ch.close()
            print("smoke 3b OK: gRPC search through router1")
        # 3c. online schema evolution: add a scalar field, index it
        # live, filter on it — through the multi-master list
        rpc.call(master_list, "PUT", "/dbs/db/spaces/s",
                 {"fields": [{"name": "grade", "data_type": "integer"}]})
        t0 = time.time()
        while True:
            try:
                r1.upsert("db", "s", [{"_id": "g1", "grade": 7,
                                       "v": vecs[0].tolist()}])
                break
            except RpcError:
                # replicas can still be settling right after the
                # restore swapped partition state under them
                if time.time() - t0 > 30:
                    raise
                time.sleep(1.0)
        rpc.call(master_list, "POST", "/field_index",
                 {"db_name": "db", "space_name": "s", "field": "grade",
                  "index_type": "INVERTED", "background": False})
        docs = r1.query("db", "s", filters={
            "operator": "AND", "conditions": [
                {"operator": "=", "field": "grade", "value": 7}]},
            limit=10)
        assert [d["_id"] for d in docs] == ["g1"], docs
        print("smoke 3c OK: live field addition + online field index")

        # 4. kill -9 one PS; replica_num=2 keeps every partition served
        procs["ps1"].send_signal(signal.SIGKILL)
        procs["ps1"].wait()
        t0 = time.time()
        while True:
            try:
                hits = r1.search("db", "s",
                                 [{"field": "v", "feature": vecs[11]}],
                                 limit=1)
                if hits[0] and hits[0][0]["_id"] == "d11":
                    break
            except RpcError:
                pass
            if time.time() - t0 > 90:
                raise SystemExit("FAIL: search dead after PS kill")
            time.sleep(0.5)
        print("smoke 4 OK: PS killed, replicas keep serving")
        print("CLUSTER SMOKE: ALL GREEN")
        return 0
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        s3.stop()


if __name__ == "__main__":
    import tempfile

    with tempfile.TemporaryDirectory(prefix="vearch_smoke_") as d:
        sys.exit(main(d))

#!/usr/bin/env python
"""Quickstart: standalone cluster, a space, some vectors, one profiled
search with its phase breakdown printed.

Runs entirely in-process (master + 2 PS + router threads) — no
deployment needed:

    JAX_PLATFORMS=cpu python examples/python/quickstart.py

The profiled search at the end is the profile=true explain surface
(docs/OBSERVABILITY.md): per-partition phase timings and the device
dispatches the query actually launched, next to the perf model's
prediction for the matched serving path.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from vearch_tpu.cluster.standalone import StandaloneCluster  # noqa: E402
from vearch_tpu.sdk.client import VearchClient  # noqa: E402

D = 32
N = 200


def main() -> int:
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as data_dir:
        cluster = StandaloneCluster(data_dir=data_dir, n_ps=2)
        cluster.start()
        try:
            client = VearchClient(cluster.router_addr)
            client.create_database("quickstart")
            client.create_space("quickstart", {
                "name": "articles",
                "partition_num": 2,
                "fields": [
                    {"name": "topic", "data_type": "integer"},
                    {"name": "embedding", "data_type": "vector",
                     "dimension": D,
                     "index": {"index_type": "FLAT",
                               "metric_type": "L2", "params": {}}},
                ],
            })

            vecs = rng.standard_normal((N, D)).astype(np.float32)
            extra = rng.standard_normal((20, D)).astype(np.float32)
            client.upsert("quickstart", "articles", [
                {"_id": f"doc-{i}", "topic": i % 5, "embedding": vecs[i]}
                for i in range(N)
            ])
            print(f"indexed {N} docs across 2 partitions")

            # plain search: per-query hit lists
            hits = client.search(
                "quickstart", "articles",
                [{"field": "embedding", "feature": vecs[42]}], limit=3)
            print("top hit:", hits[0][0]["_id"],
                  f"(score {hits[0][0]['_score']:.4f})")
            assert hits[0][0]["_id"] == "doc-42"

            # profiled search: documents + the explain breakdown
            out = client.search(
                "quickstart", "articles",
                [{"field": "embedding", "feature": vecs[42]}],
                limit=3, profile=True)
            prof = out["profile"]
            print(f"\nprofile ({prof['partition_count']} partitions, "
                  f"router merge {prof['merge_ms']} ms):")
            for pid, part in sorted(prof["partitions"].items()):
                print(f"  partition {pid}: rpc {part['rpc_ms']} ms, "
                      f"{part['doc_count']} docs")
                for phase, ms in sorted(part["phases"].items()):
                    print(f"    {phase:<14} {ms:8.3f} ms")
                disp = part["dispatches"]
                print(f"    dispatches    {disp['tags']} "
                      f"(path={disp['path']}, "
                      f"predicted={disp['predicted']})")
                assert disp["tags"] == disp["predicted"]

            # profiled write: the same explain surface for mutations —
            # raft propose wait, WAL append+fsync, commit wait, apply
            out = client.upsert("quickstart", "articles", [
                {"_id": f"doc-{N + i}", "topic": i % 5,
                 "embedding": extra[i]}
                for i in range(20)
            ], profile=True)
            wprof = out["profile"]
            print(f"\nwrite profile ({wprof['partition_count']} "
                  f"partitions, router merge {wprof['merge_ms']} ms):")
            for pid, part in sorted(wprof["partitions"].items()):
                print(f"  partition {pid}: rpc {part['rpc_ms']} ms, "
                      f"{part['doc_count']} docs")
                for phase in ("propose_wait", "wal_append",
                              "commit_wait", "apply", "total"):
                    print(f"    {phase:<14} "
                          f"{part['phases'][phase]:8.3f} ms")

            # background index build, watched through GET /ps/jobs
            from vearch_tpu.cluster import rpc

            print("\nbackground index build:")
            for ps in cluster.ps_nodes:
                for pid in list(ps.engines):
                    rpc.call(ps.addr, "POST", "/ps/index/build",
                             {"partition_id": pid, "background": True})
            for ps in cluster.ps_nodes:
                while True:
                    jobs = rpc.call(ps.addr, "GET", "/ps/jobs")["jobs"]
                    if jobs and all(j["status"] != "running"
                                    for j in jobs):
                        break
                    time.sleep(0.05)
                for j in sorted(jobs, key=lambda j: j["partition_id"]):
                    print(f"  partition {j['partition_id']}: "
                          f"{j['op']} {j['status']} in "
                          f"{j['duration_seconds']}s "
                          f"(phases {sorted(j['phases_ms'])})")

            # slow-query log: an absurdly low threshold logs every
            # search with its phase breakdown (ops would use ~500ms)
            for ps in cluster.ps_nodes:
                pid = next(iter(ps.engines))
                rpc.call(ps.addr, "POST", "/ps/engine/config",
                         {"partition_id": pid,
                          "config": {"slow_log_ms": 0.001}})
            client.search(
                "quickstart", "articles",
                [{"field": "embedding", "feature": vecs[7]}], limit=3)
            print("\nslow-query log (threshold 0.001 ms):")
            logged = 0
            for ps in cluster.ps_nodes:
                log = rpc.call(ps.addr, "GET", "/debug/slowlog")
                for e in log["entries"]:
                    logged += 1
                    print(f"  partition {e['partition']}: {e['op']} "
                          f"{e['elapsed_ms']} ms "
                          f"(phases {sorted(e['phases'])})")
            assert logged >= 1

            print("\nquickstart OK")
            return 0
        finally:
            cluster.stop()


if __name__ == "__main__":
    sys.exit(main())

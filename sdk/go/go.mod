module github.com/vearch-tpu/vearch-tpu/sdk/go

go 1.21

// Package vearchtpu is the Go client SDK for the vearch-tpu cluster.
//
// It speaks the router/master REST surface (route names mirror upstream
// vearch, reference: sdk/go/vearch_client.go public surface), stdlib
// only. All document operations go through a router address; admin
// operations are proxied by the router to the master.
//
// NOTE: this environment ships no Go toolchain, so this package is
// written to be vet-clean but is compile-verified by consumers, not CI
// here (see docs/PARITY.md).
package vearchtpu

import (
	"bytes"
	"encoding/base64"
	"encoding/json"
	"fmt"
	"io"
	"net/http"
	"time"
)

// Client talks to a vearch-tpu router (documents) and, through its
// master proxy, the control plane.
type Client struct {
	RouterURL string // e.g. "http://127.0.0.1:8817"
	Username  string // optional BasicAuth
	Password  string
	HTTP      *http.Client
}

// New creates a client with a 120s default timeout.
func New(routerURL string) *Client {
	return &Client{
		RouterURL: routerURL,
		HTTP:      &http.Client{Timeout: 120 * time.Second},
	}
}

// WithAuth sets BasicAuth credentials for every request.
func (c *Client) WithAuth(user, password string) *Client {
	c.Username, c.Password = user, password
	return c
}

// APIError carries the server's error code and message.
type APIError struct {
	Code int    `json:"code"`
	Msg  string `json:"msg"`
}

func (e *APIError) Error() string {
	return fmt.Sprintf("vearch-tpu: code=%d msg=%s", e.Code, e.Msg)
}

type envelope struct {
	Code int             `json:"code"`
	Msg  string          `json:"msg"`
	Data json.RawMessage `json:"data"`
}

func (c *Client) do(method, path string, body any, out any) error {
	var rd io.Reader
	if body != nil {
		raw, err := json.Marshal(body)
		if err != nil {
			return err
		}
		rd = bytes.NewReader(raw)
	}
	req, err := http.NewRequest(method, c.RouterURL+path, rd)
	if err != nil {
		return err
	}
	req.Header.Set("Content-Type", "application/json")
	if c.Username != "" {
		tok := base64.StdEncoding.EncodeToString(
			[]byte(c.Username + ":" + c.Password))
		req.Header.Set("Authorization", "Basic "+tok)
	}
	resp, err := c.HTTP.Do(req)
	if err != nil {
		return err
	}
	defer resp.Body.Close()
	raw, err := io.ReadAll(resp.Body)
	if err != nil {
		return err
	}
	var env envelope
	if err := json.Unmarshal(raw, &env); err != nil {
		return fmt.Errorf("vearch-tpu: bad response (%d): %w",
			resp.StatusCode, err)
	}
	if env.Code != 0 {
		return &APIError{Code: env.Code, Msg: env.Msg}
	}
	if out != nil && len(env.Data) > 0 {
		return json.Unmarshal(env.Data, out)
	}
	return nil
}

// -- entities ---------------------------------------------------------------

// Field describes one schema field at space-create time.
type Field struct {
	Name      string         `json:"name"`
	DataType  string         `json:"data_type"` // string/integer/float/date/vector/...
	Dimension int            `json:"dimension,omitempty"`
	Index     map[string]any `json:"index,omitempty"` // {index_type, metric_type, params}
}

// RulePartition is one range of a RANGE partition rule.
type RulePartition struct {
	Name  string `json:"name"`
	Value any    `json:"value"`
}

// PartitionRule routes writes by a scalar field.
type PartitionRule struct {
	Type   string          `json:"type"` // "RANGE"
	Field  string          `json:"field"`
	Ranges []RulePartition `json:"ranges"`
}

// SpaceConfig is the body of a create-space request.
type SpaceConfig struct {
	Name          string         `json:"name"`
	PartitionNum  int            `json:"partition_num,omitempty"`
	ReplicaNum    int            `json:"replica_num,omitempty"`
	Fields        []Field        `json:"fields"`
	PartitionRule *PartitionRule `json:"partition_rule,omitempty"`
}

// Document is an upsert payload: field name -> value; "_id" optional.
type Document map[string]any

// SearchVector names one query vector batch for a field; Feature is a
// flattened [b*d] batch. MinScore/MaxScore bound the field's
// metric-oriented score (L2: squared distance, lower = closer).
type SearchVector struct {
	Field    string    `json:"field"`
	Feature  []float32 `json:"feature"`
	MinScore *float64  `json:"min_score,omitempty"`
	MaxScore *float64  `json:"max_score,omitempty"`
}

// SearchRequest mirrors POST /document/search.
type SearchRequest struct {
	DBName      string         `json:"db_name"`
	SpaceName   string         `json:"space_name"`
	Vectors     []SearchVector `json:"vectors"`
	Limit       int            `json:"limit,omitempty"`
	RequestID   string         `json:"request_id,omitempty"` // for /ps/kill

	Filters     map[string]any `json:"filters,omitempty"`
	Fields      []string       `json:"fields,omitempty"`
	IndexParams map[string]any `json:"index_params,omitempty"`
	Ranker      map[string]any `json:"ranker,omitempty"`
	LoadBalance string         `json:"load_balance,omitempty"`
	Trace       bool           `json:"trace,omitempty"`
}

// Hit is one search result row (dynamic fields ride alongside).
type Hit map[string]any

// ID returns the document id of a hit.
func (h Hit) ID() string { s, _ := h["_id"].(string); return s }

// Score returns the metric-oriented score of a hit.
func (h Hit) Score() float64 { f, _ := h["_score"].(float64); return f }

// -- databases --------------------------------------------------------------

// CreateDatabase creates a database.
func (c *Client) CreateDatabase(db string) error {
	return c.do("POST", "/dbs/"+db, nil, nil)
}

// DropDatabase removes an empty database.
func (c *Client) DropDatabase(db string) error {
	return c.do("DELETE", "/dbs/"+db, nil, nil)
}

// ListDatabases returns all databases.
func (c *Client) ListDatabases() ([]map[string]any, error) {
	var out struct {
		DBs []map[string]any `json:"dbs"`
	}
	err := c.do("GET", "/dbs", nil, &out)
	return out.DBs, err
}

// -- spaces -----------------------------------------------------------------

// CreateSpace creates a space in db.
func (c *Client) CreateSpace(db string, cfg SpaceConfig) (map[string]any, error) {
	var out map[string]any
	err := c.do("POST", "/dbs/"+db+"/spaces", cfg, &out)
	return out, err
}

// DropSpace deletes a space and its partitions cluster-wide.
func (c *Client) DropSpace(db, space string) error {
	return c.do("DELETE", "/dbs/"+db+"/spaces/"+space, nil, nil)
}

// GetSpace fetches space metadata (partitions, rule, schema).
func (c *Client) GetSpace(db, space string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/dbs/"+db+"/spaces/"+space, nil, &out)
	return out, err
}

// UpdatePartitionRule adds or drops rule partitions online.
// op is "ADD" (with rule.Ranges) or "DROP" (with partitionName).
func (c *Client) UpdatePartitionRule(db, space, op, partitionName string,
	rule *PartitionRule) (map[string]any, error) {
	body := map[string]any{
		"db_name": db, "space_name": space, "operator_type": op,
	}
	if partitionName != "" {
		body["partition_name"] = partitionName
	}
	if rule != nil {
		body["partition_rule"] = rule
	}
	var out map[string]any
	err := c.do("POST", "/partitions/rule", body, &out)
	return out, err
}

// -- documents --------------------------------------------------------------

// Upsert inserts or updates documents; returns assigned ids.
func (c *Client) Upsert(db, space string, docs []Document) ([]string, error) {
	var out struct {
		Total int      `json:"total"`
		IDs   []string `json:"document_ids"`
	}
	err := c.do("POST", "/document/upsert", map[string]any{
		"db_name": db, "space_name": space, "documents": docs,
	}, &out)
	return out.IDs, err
}

// Search runs a batched vector search; result is one hit list per query.
func (c *Client) Search(req SearchRequest) ([][]Hit, error) {
	var out struct {
		Documents [][]Hit `json:"documents"`
	}
	err := c.do("POST", "/document/search", req, &out)
	return out.Documents, err
}

// Query fetches documents by id or by scalar filter with pagination.
func (c *Client) Query(db, space string, ids []string,
	filters map[string]any, limit, offset int) ([]Hit, error) {
	body := map[string]any{
		"db_name": db, "space_name": space,
		"limit": limit, "offset": offset,
	}
	if len(ids) > 0 {
		body["document_ids"] = ids
	}
	if filters != nil {
		body["filters"] = filters
	}
	var out struct {
		Documents []Hit `json:"documents"`
	}
	err := c.do("POST", "/document/query", body, &out)
	return out.Documents, err
}

// Delete removes documents by id or by filter; limit bounds a filtered
// delete globally (0 keeps the explicit zero budget: nothing deleted;
// pass a negative limit for "no limit").
func (c *Client) Delete(db, space string, ids []string,
	filters map[string]any, limit int) (int, error) {
	body := map[string]any{"db_name": db, "space_name": space}
	if len(ids) > 0 {
		body["document_ids"] = ids
	}
	if filters != nil {
		body["filters"] = filters
	}
	if limit >= 0 {
		body["limit"] = limit
	}
	var out struct {
		Total int `json:"total"`
	}
	err := c.do("POST", "/document/delete", body, &out)
	return out.Total, err
}

// -- index ops --------------------------------------------------------------

// Flush checkpoints every partition of the space.
func (c *Client) Flush(db, space string) error {
	return c.do("POST", "/index/flush",
		map[string]any{"db_name": db, "space_name": space}, nil)
}

// ForceMerge triggers index training/build on every partition.
func (c *Client) ForceMerge(db, space string) error {
	return c.do("POST", "/index/forcemerge",
		map[string]any{"db_name": db, "space_name": space}, nil)
}

// Rebuild retrains indexes from scratch on every partition.
func (c *Client) Rebuild(db, space string) error {
	return c.do("POST", "/index/rebuild",
		map[string]any{"db_name": db, "space_name": space}, nil)
}

// -- aliases / users / health ----------------------------------------------

// CreateAlias points alias at db/space.
func (c *Client) CreateAlias(alias, db, space string) error {
	return c.do("POST",
		"/alias/"+alias+"/dbs/"+db+"/spaces/"+space, nil, nil)
}

// DropAlias removes an alias.
func (c *Client) DropAlias(alias string) error {
	return c.do("DELETE", "/alias/"+alias, nil, nil)
}

// IsLive reports whether the cluster answers health checks.
func (c *Client) IsLive() bool {
	return c.do("GET", "/cluster/health", nil, nil) == nil
}

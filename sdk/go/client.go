// Package vearchtpu is the Go client SDK for the vearch-tpu cluster.
//
// It speaks the router/master REST surface (route names mirror upstream
// vearch, reference: sdk/go/vearch_client.go public surface), stdlib
// only. All document operations go through a router address; admin
// operations are proxied by the router to the master.
//
// NOTE: this environment ships no Go toolchain, so this package is
// written to be vet-clean but is compile-verified by consumers, not CI
// here (see docs/PARITY.md).
package vearchtpu

import (
	"bytes"
	"encoding/base64"
	"encoding/json"
	"fmt"
	"io"
	"net/http"
	"time"
)

// Client talks to a vearch-tpu router (documents) and, through its
// master proxy, the control plane.
type Client struct {
	RouterURL string // e.g. "http://127.0.0.1:8817"
	Username  string // optional BasicAuth
	Password  string
	HTTP      *http.Client
}

// New creates a client with a 120s default timeout.
func New(routerURL string) *Client {
	return &Client{
		RouterURL: routerURL,
		HTTP:      &http.Client{Timeout: 120 * time.Second},
	}
}

// WithAuth sets BasicAuth credentials for every request.
func (c *Client) WithAuth(user, password string) *Client {
	c.Username, c.Password = user, password
	return c
}

// APIError carries the server's error code and message.
type APIError struct {
	Code int    `json:"code"`
	Msg  string `json:"msg"`
}

func (e *APIError) Error() string {
	return fmt.Sprintf("vearch-tpu: code=%d msg=%s", e.Code, e.Msg)
}

type envelope struct {
	Code int             `json:"code"`
	Msg  string          `json:"msg"`
	Data json.RawMessage `json:"data"`
}

func (c *Client) do(method, path string, body any, out any) error {
	var rd io.Reader
	if body != nil {
		raw, err := json.Marshal(body)
		if err != nil {
			return err
		}
		rd = bytes.NewReader(raw)
	}
	req, err := http.NewRequest(method, c.RouterURL+path, rd)
	if err != nil {
		return err
	}
	req.Header.Set("Content-Type", "application/json")
	if c.Username != "" {
		tok := base64.StdEncoding.EncodeToString(
			[]byte(c.Username + ":" + c.Password))
		req.Header.Set("Authorization", "Basic "+tok)
	}
	resp, err := c.HTTP.Do(req)
	if err != nil {
		return err
	}
	defer resp.Body.Close()
	raw, err := io.ReadAll(resp.Body)
	if err != nil {
		return err
	}
	var env envelope
	if err := json.Unmarshal(raw, &env); err != nil {
		return fmt.Errorf("vearch-tpu: bad response (%d): %w",
			resp.StatusCode, err)
	}
	if env.Code != 0 {
		return &APIError{Code: env.Code, Msg: env.Msg}
	}
	if out != nil && len(env.Data) > 0 {
		return json.Unmarshal(env.Data, out)
	}
	return nil
}

// -- entities ---------------------------------------------------------------

// Field describes one schema field at space-create time.
type Field struct {
	Name      string         `json:"name"`
	DataType  string         `json:"data_type"` // string/integer/float/date/vector/...
	Dimension int            `json:"dimension,omitempty"`
	Index     map[string]any `json:"index,omitempty"` // {index_type, metric_type, params}
}

// RulePartition is one range of a RANGE partition rule.
type RulePartition struct {
	Name  string `json:"name"`
	Value any    `json:"value"`
}

// PartitionRule routes writes by a scalar field.
type PartitionRule struct {
	Type   string          `json:"type"` // "RANGE"
	Field  string          `json:"field"`
	Ranges []RulePartition `json:"ranges"`
}

// SpaceConfig is the body of a create-space request.
type SpaceConfig struct {
	Name          string         `json:"name"`
	PartitionNum  int            `json:"partition_num,omitempty"`
	ReplicaNum    int            `json:"replica_num,omitempty"`
	Fields        []Field        `json:"fields"`
	PartitionRule *PartitionRule `json:"partition_rule,omitempty"`
}

// Document is an upsert payload: field name -> value; "_id" optional.
type Document map[string]any

// SearchVector names one query vector batch for a field; Feature is a
// flattened [b*d] batch. MinScore/MaxScore bound the field's
// metric-oriented score (L2: squared distance, lower = closer).
type SearchVector struct {
	Field    string    `json:"field"`
	Feature  []float32 `json:"feature"`
	MinScore *float64  `json:"min_score,omitempty"`
	MaxScore *float64  `json:"max_score,omitempty"`
}

// SearchRequest mirrors POST /document/search.
type SearchRequest struct {
	DBName      string         `json:"db_name"`
	SpaceName   string         `json:"space_name"`
	Vectors     []SearchVector `json:"vectors"`
	Limit       int            `json:"limit,omitempty"`
	RequestID   string         `json:"request_id,omitempty"` // for /ps/kill

	Filters     map[string]any `json:"filters,omitempty"`
	Fields      []string       `json:"fields,omitempty"`
	IndexParams map[string]any `json:"index_params,omitempty"`
	Ranker      map[string]any `json:"ranker,omitempty"`
	LoadBalance string         `json:"load_balance,omitempty"`
	// Sort orders the top-k result set by scalar fields; same forms as
	// the REST surface: "field" | [{"field": "asc"}] |
	// [{"field": {"order": "desc", "missing": "_last"}}].
	Sort     any  `json:"sort,omitempty"`
	PageSize int  `json:"page_size,omitempty"`
	PageNum  int  `json:"page_num,omitempty"`
	Trace    bool `json:"trace,omitempty"`
}

// Hit is one search result row (dynamic fields ride alongside).
type Hit map[string]any

// ID returns the document id of a hit.
func (h Hit) ID() string { s, _ := h["_id"].(string); return s }

// Score returns the metric-oriented score of a hit.
func (h Hit) Score() float64 { f, _ := h["_score"].(float64); return f }

// -- databases --------------------------------------------------------------

// CreateDatabase creates a database.
func (c *Client) CreateDatabase(db string) error {
	return c.do("POST", "/dbs/"+db, nil, nil)
}

// DropDatabase removes an empty database.
func (c *Client) DropDatabase(db string) error {
	return c.do("DELETE", "/dbs/"+db, nil, nil)
}

// ListDatabases returns all databases.
func (c *Client) ListDatabases() ([]map[string]any, error) {
	var out struct {
		DBs []map[string]any `json:"dbs"`
	}
	err := c.do("GET", "/dbs", nil, &out)
	return out.DBs, err
}

// -- spaces -----------------------------------------------------------------

// CreateSpace creates a space in db.
func (c *Client) CreateSpace(db string, cfg SpaceConfig) (map[string]any, error) {
	var out map[string]any
	err := c.do("POST", "/dbs/"+db+"/spaces", cfg, &out)
	return out, err
}

// DropSpace deletes a space and its partitions cluster-wide.
func (c *Client) DropSpace(db, space string) error {
	return c.do("DELETE", "/dbs/"+db+"/spaces/"+space, nil, nil)
}

// GetSpace fetches space metadata (partitions, rule, schema).
func (c *Client) GetSpace(db, space string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/dbs/"+db+"/spaces/"+space, nil, &out)
	return out, err
}

// UpdatePartitionRule adds or drops rule partitions online.
// op is "ADD" (with rule.Ranges) or "DROP" (with partitionName).
func (c *Client) UpdatePartitionRule(db, space, op, partitionName string,
	rule *PartitionRule) (map[string]any, error) {
	body := map[string]any{
		"db_name": db, "space_name": space, "operator_type": op,
	}
	if partitionName != "" {
		body["partition_name"] = partitionName
	}
	if rule != nil {
		body["partition_rule"] = rule
	}
	var out map[string]any
	err := c.do("POST", "/partitions/rule", body, &out)
	return out, err
}

// -- documents --------------------------------------------------------------

// Upsert inserts or updates documents; returns assigned ids.
func (c *Client) Upsert(db, space string, docs []Document) ([]string, error) {
	var out struct {
		Total int      `json:"total"`
		IDs   []string `json:"document_ids"`
	}
	err := c.do("POST", "/document/upsert", map[string]any{
		"db_name": db, "space_name": space, "documents": docs,
	}, &out)
	return out.IDs, err
}

// Search runs a batched vector search; result is one hit list per query.
func (c *Client) Search(req SearchRequest) ([][]Hit, error) {
	var out struct {
		Documents [][]Hit `json:"documents"`
	}
	err := c.do("POST", "/document/search", req, &out)
	return out.Documents, err
}

// Query fetches documents by id or by scalar filter with pagination.
func (c *Client) Query(db, space string, ids []string,
	filters map[string]any, limit, offset int) ([]Hit, error) {
	return c.QuerySorted(db, space, ids, filters, limit, offset, nil)
}

// QuerySorted is Query with a scalar-field sort spec (same forms as
// search; "_score" is invalid for query). Cross-partition pages stay
// consistent: the router merges globally before slicing.
func (c *Client) QuerySorted(db, space string, ids []string,
	filters map[string]any, limit, offset int, sort any) ([]Hit, error) {
	body := map[string]any{
		"db_name": db, "space_name": space,
		"limit": limit, "offset": offset,
	}
	if len(ids) > 0 {
		body["document_ids"] = ids
	}
	if filters != nil {
		body["filters"] = filters
	}
	if sort != nil {
		body["sort"] = sort
	}
	var out struct {
		Documents []Hit `json:"documents"`
	}
	err := c.do("POST", "/document/query", body, &out)
	return out.Documents, err
}

// Delete removes documents by id or by filter; limit bounds a filtered
// delete globally (0 keeps the explicit zero budget: nothing deleted;
// pass a negative limit for "no limit").
func (c *Client) Delete(db, space string, ids []string,
	filters map[string]any, limit int) (int, error) {
	body := map[string]any{"db_name": db, "space_name": space}
	if len(ids) > 0 {
		body["document_ids"] = ids
	}
	if filters != nil {
		body["filters"] = filters
	}
	if limit >= 0 {
		body["limit"] = limit
	}
	var out struct {
		Total int `json:"total"`
	}
	err := c.do("POST", "/document/delete", body, &out)
	return out.Total, err
}

// -- index ops --------------------------------------------------------------

// Flush checkpoints every partition of the space.
func (c *Client) Flush(db, space string) error {
	return c.do("POST", "/index/flush",
		map[string]any{"db_name": db, "space_name": space}, nil)
}

// ForceMerge triggers index training/build on every partition.
func (c *Client) ForceMerge(db, space string) error {
	return c.do("POST", "/index/forcemerge",
		map[string]any{"db_name": db, "space_name": space}, nil)
}

// Rebuild retrains indexes from scratch on every partition.
func (c *Client) Rebuild(db, space string) error {
	return c.do("POST", "/index/rebuild",
		map[string]any{"db_name": db, "space_name": space}, nil)
}

// -- aliases / users / health ----------------------------------------------

// CreateAlias points alias at db/space.
func (c *Client) CreateAlias(alias, db, space string) error {
	return c.do("POST",
		"/alias/"+alias+"/dbs/"+db+"/spaces/"+space, nil, nil)
}

// DropAlias removes an alias.
func (c *Client) DropAlias(alias string) error {
	return c.do("DELETE", "/alias/"+alias, nil, nil)
}

// IsLive reports whether the cluster answers health checks.
func (c *Client) IsLive() bool {
	return c.do("GET", "/cluster/health", nil, nil) == nil
}

// -- space update / detail ---------------------------------------------------

// UpdateSpace applies online space changes (partition_num expansion,
// new scalar fields) via PUT /dbs/{db}/spaces/{space}.
func (c *Client) UpdateSpace(db, space string,
	config map[string]any) (map[string]any, error) {
	var out map[string]any
	err := c.do("PUT", "/dbs/"+db+"/spaces/"+space, config, &out)
	return out, err
}

// SpaceDetail returns space metadata with per-partition doc/size/status
// stats (GET ?detail=true).
func (c *Client) SpaceDetail(db, space string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/dbs/"+db+"/spaces/"+space+"?detail=true",
		nil, &out)
	return out, err
}

// -- scalar field indexes ----------------------------------------------------

// AddFieldIndex builds a scalar index (INVERTED/BITMAP) on field,
// cluster-wide. background=false blocks until built.
func (c *Client) AddFieldIndex(db, space, field, indexType string,
	background bool) error {
	return c.do("POST", "/field_index", map[string]any{
		"db_name": db, "space_name": space, "field": field,
		"index_type": indexType, "background": background,
	}, nil)
}

// RemoveFieldIndex drops a scalar index from field.
func (c *Client) RemoveFieldIndex(db, space, field string) error {
	return c.do("POST", "/field_index", map[string]any{
		"db_name": db, "space_name": space, "field": field,
		"index_type": "NONE",
	}, nil)
}

// -- backup / restore --------------------------------------------------------

// BackupCreate starts an async backup job to storeRoot (local/NFS path)
// or store (s3 spec); poll BackupJob with the returned job_id.
func (c *Client) BackupCreate(db, space string, storeRoot string,
	store map[string]any) (map[string]any, error) {
	body := map[string]any{"command": "create", "async": true}
	if storeRoot != "" {
		body["store_root"] = storeRoot
	}
	if store != nil {
		body["store"] = store
	}
	var out map[string]any
	err := c.do("POST", "/backup/dbs/"+db+"/spaces/"+space, body, &out)
	return out, err
}

// BackupJob polls an async backup job's progress record.
func (c *Client) BackupJob(jobID string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/backup/jobs/"+jobID, nil, &out)
	return out, err
}

// BackupList returns the stored backup versions for the space.
func (c *Client) BackupList(db, space string, storeRoot string,
	store map[string]any) ([]int, error) {
	body := map[string]any{"command": "list"}
	if storeRoot != "" {
		body["store_root"] = storeRoot
	}
	if store != nil {
		body["store"] = store
	}
	var out struct {
		Versions []int `json:"versions"`
	}
	err := c.do("POST", "/backup/dbs/"+db+"/spaces/"+space, body, &out)
	return out.Versions, err
}

// BackupRestore rewinds the space to a stored version (verify-then-swap
// on every replica).
func (c *Client) BackupRestore(db, space string, version int,
	storeRoot string, store map[string]any) (map[string]any, error) {
	body := map[string]any{"command": "restore", "version": version}
	if storeRoot != "" {
		body["store_root"] = storeRoot
	}
	if store != nil {
		body["store"] = store
	}
	var out map[string]any
	err := c.do("POST", "/backup/dbs/"+db+"/spaces/"+space, body, &out)
	return out, err
}

// BackupDelete removes a stored version (ref-counted blob GC).
func (c *Client) BackupDelete(db, space string, version int,
	storeRoot string, store map[string]any) error {
	body := map[string]any{"command": "delete", "version": version}
	if storeRoot != "" {
		body["store_root"] = storeRoot
	}
	if store != nil {
		body["store"] = store
	}
	return c.do("POST", "/backup/dbs/"+db+"/spaces/"+space, body, nil)
}

// -- cluster views / ops -----------------------------------------------------

// ClusterStats returns per-node partition stats (heartbeat-fed).
func (c *Client) ClusterStats() (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/cluster/stats", nil, &out)
	return out, err
}

// ClusterHealth returns the per-space green/yellow/red roll-up.
func (c *Client) ClusterHealth() (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/cluster/health", nil, &out)
	return out, err
}

// Members lists the metadata-raft masters with the leader flag.
func (c *Client) Members() ([]map[string]any, error) {
	var out struct {
		Members []map[string]any `json:"members"`
	}
	err := c.do("GET", "/members", nil, &out)
	return out.Members, err
}

// MemberAdd registers a new master {node_id, addr} with the group.
func (c *Client) MemberAdd(nodeID int, addr string) (map[string]any, error) {
	var out map[string]any
	err := c.do("POST", "/members/add",
		map[string]any{"node_id": nodeID, "addr": addr}, &out)
	return out, err
}

// MemberRemove removes a master from the group.
func (c *Client) MemberRemove(nodeID int) (map[string]any, error) {
	var out map[string]any
	err := c.do("POST", "/members/remove",
		map[string]any{"node_id": nodeID}, &out)
	return out, err
}

// Servers lists registered PS nodes.
func (c *Client) Servers() (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/servers", nil, &out)
	return out, err
}

// Routers lists live router instances.
func (c *Client) Routers() (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/routers", nil, &out)
	return out, err
}

// Partitions lists every partition cluster-wide.
func (c *Client) Partitions() (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/partitions", nil, &out)
	return out, err
}

// ChangeMember moves a partition replica: method is "add" or "remove".
func (c *Client) ChangeMember(partitionID, nodeID int,
	method string) (map[string]any, error) {
	var out map[string]any
	err := c.do("POST", "/partitions/change_member", map[string]any{
		"partition_id": partitionID, "node_id": nodeID, "method": method,
	}, &out)
	return out, err
}

// FailServers lists durable failure records awaiting recovery.
func (c *Client) FailServers() (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/schedule/fail_server", nil, &out)
	return out, err
}

// ClearFailServer erases a failure record for node_id.
func (c *Client) ClearFailServer(nodeID int) error {
	return c.do("DELETE", fmt.Sprintf("/schedule/fail_server/%d", nodeID),
		nil, nil)
}

// RecoverServer forces a recovery pass for a failed node.
func (c *Client) RecoverServer(nodeID int) (map[string]any, error) {
	var out map[string]any
	err := c.do("POST", "/schedule/recover_server",
		map[string]any{"node_id": nodeID}, &out)
	return out, err
}

// CleanLock clears expired space-mutation locks (admin escape hatch).
func (c *Client) CleanLock() (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/clean_lock", nil, &out)
	return out, err
}

// -- runtime config ----------------------------------------------------------

// SetConfig updates runtime engine config for db/space cluster-wide
// (cache sizes, refresh interval...).
func (c *Client) SetConfig(db, space string,
	config map[string]any) (map[string]any, error) {
	var out map[string]any
	err := c.do("POST", "/config/"+db+"/"+space, config, &out)
	return out, err
}

// GetConfig reads the stored runtime config for db/space.
func (c *Client) GetConfig(db, space string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/config/"+db+"/"+space, nil, &out)
	return out, err
}

// -- users / roles (RBAC) ----------------------------------------------------

// CreateUser creates a user with a role.
func (c *Client) CreateUser(name, password, role string) error {
	return c.do("POST", "/users", map[string]any{
		"name": name, "password": password, "role_name": role,
	}, nil)
}

// GetUser fetches a user record.
func (c *Client) GetUser(name string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/users/"+name, nil, &out)
	return out, err
}

// UpdateUser changes a user's password and/or role.
func (c *Client) UpdateUser(name string, fields map[string]any) error {
	body := map[string]any{"name": name}
	for k, v := range fields {
		body[k] = v
	}
	return c.do("PUT", "/users", body, nil)
}

// DeleteUser removes a user.
func (c *Client) DeleteUser(name string) error {
	return c.do("DELETE", "/users/"+name, nil, nil)
}

// CreateRole creates a role with a privilege map
// (resource -> ReadOnly|WriteOnly|WriteRead).
func (c *Client) CreateRole(name string, privileges map[string]string) error {
	return c.do("POST", "/roles", map[string]any{
		"name": name, "privileges": privileges,
	}, nil)
}

// UpdateRole replaces a role's privilege map.
func (c *Client) UpdateRole(name string, privileges map[string]string) error {
	return c.do("PUT", "/roles", map[string]any{
		"name": name, "privileges": privileges,
	}, nil)
}

// GetRole fetches a role record.
func (c *Client) GetRole(name string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/roles/"+name, nil, &out)
	return out, err
}

// -- aliases (read side) -----------------------------------------------------

// GetAlias resolves one alias.
func (c *Client) GetAlias(alias string) (map[string]any, error) {
	var out map[string]any
	err := c.do("GET", "/alias/"+alias, nil, &out)
	return out, err
}

//! Rust client SDK for the vearch-tpu cluster REST surface (route names
//! mirror upstream vearch; reference: sdk/rust public surface). Blocking
//! HTTP via `ureq`, JSON via `serde_json`.
//!
//! NOTE: no Rust toolchain ships in this build image, so this crate is
//! compile-verified by consumers rather than CI here (docs/PARITY.md).

use serde::{Deserialize, Serialize};
use serde_json::{json, Value};

/// Server-side error envelope `{code, msg}` or a transport failure.
#[derive(Debug)]
pub enum Error {
    Api { code: i64, msg: String },
    Transport(String),
}

impl std::fmt::Display for Error {
    fn fmt(&self, f: &mut std::fmt::Formatter<'_>) -> std::fmt::Result {
        match self {
            Error::Api { code, msg } => {
                write!(f, "vearch-tpu: code={code} msg={msg}")
            }
            Error::Transport(e) => write!(f, "vearch-tpu transport: {e}"),
        }
    }
}

impl std::error::Error for Error {}

pub type Result<T> = std::result::Result<T, Error>;

/// One schema field at space-create time.
#[derive(Serialize, Deserialize, Debug, Clone, Default)]
pub struct Field {
    pub name: String,
    pub data_type: String,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub dimension: Option<u32>,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub index: Option<Value>,
}

/// Create-space request body.
#[derive(Serialize, Deserialize, Debug, Clone, Default)]
pub struct SpaceConfig {
    pub name: String,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub partition_num: Option<u32>,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub replica_num: Option<u32>,
    pub fields: Vec<Field>,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub partition_rule: Option<Value>,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub anti_affinity: Option<String>,
}

/// One query vector batch (`feature` is the flattened `[b*d]` batch).
/// `min_score`/`max_score` bound the field's metric-oriented score
/// (L2: squared distance, lower = closer).
#[derive(Serialize, Deserialize, Debug, Clone)]
pub struct SearchVector {
    pub field: String,
    pub feature: Vec<f32>,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub min_score: Option<f64>,
    #[serde(skip_serializing_if = "Option::is_none")]
    pub max_score: Option<f64>,
}

/// Client for a vearch-tpu router (documents) and its master proxy.
pub struct Client {
    router_url: String,
    auth: Option<String>,
    agent: ureq::Agent,
}

impl Client {
    /// `router_url` like `"http://127.0.0.1:8817"`.
    pub fn new(router_url: impl Into<String>) -> Self {
        Client {
            router_url: router_url.into().trim_end_matches('/').to_string(),
            auth: None,
            agent: ureq::AgentBuilder::new()
                .timeout(std::time::Duration::from_secs(120))
                .build(),
        }
    }

    /// Enables BasicAuth on every request.
    pub fn with_auth(mut self, user: &str, password: &str) -> Self {
        use base64::Engine as _;
        let tok = base64::engine::general_purpose::STANDARD
            .encode(format!("{user}:{password}"));
        self.auth = Some(format!("Basic {tok}"));
        self
    }

    fn call(&self, method: &str, path: &str, body: Option<Value>)
            -> Result<Value> {
        let url = format!("{}{}", self.router_url, path);
        let mut req = self.agent.request(method, &url)
            .set("Content-Type", "application/json");
        if let Some(a) = &self.auth {
            req = req.set("Authorization", a);
        }
        let resp = match body {
            Some(b) => req.send_string(&b.to_string()),
            None => req.call(),
        };
        let (status, text) = match resp {
            Ok(r) => {
                let s = r.status();
                (s, r.into_string()
                    .map_err(|e| Error::Transport(e.to_string()))?)
            }
            Err(ureq::Error::Status(s, r)) => {
                (s, r.into_string()
                    .map_err(|e| Error::Transport(e.to_string()))?)
            }
            Err(e) => return Err(Error::Transport(e.to_string())),
        };
        let v: Value = serde_json::from_str(&text)
            .map_err(|e| Error::Transport(e.to_string()))?;
        match v.get("code").and_then(Value::as_i64) {
            Some(0) => Ok(v.get("data").cloned().unwrap_or(v)),
            Some(code) => Err(Error::Api {
                code,
                msg: v["msg"].as_str().unwrap_or("").to_string(),
            }),
            // no envelope (proxy/LB error page): trust the HTTP status —
            // a 502 JSON body must never read as a successful write
            None if status < 300 => Ok(v),
            None => Err(Error::Api {
                code: i64::from(status),
                msg: text.chars().take(200).collect(),
            }),
        }
    }

    // -- databases / spaces --------------------------------------------

    pub fn create_database(&self, db: &str) -> Result<Value> {
        self.call("POST", &format!("/dbs/{db}"), None)
    }

    pub fn drop_database(&self, db: &str) -> Result<Value> {
        self.call("DELETE", &format!("/dbs/{db}"), None)
    }

    pub fn create_space(&self, db: &str, cfg: &SpaceConfig) -> Result<Value> {
        let body = serde_json::to_value(cfg)
            .map_err(|e| Error::Transport(e.to_string()))?;
        self.call("POST", &format!("/dbs/{db}/spaces"), Some(body))
    }

    pub fn get_space(&self, db: &str, space: &str) -> Result<Value> {
        self.call("GET", &format!("/dbs/{db}/spaces/{space}"), None)
    }

    pub fn drop_space(&self, db: &str, space: &str) -> Result<Value> {
        self.call("DELETE", &format!("/dbs/{db}/spaces/{space}"), None)
    }

    // -- documents -----------------------------------------------------

    /// `documents`: array of objects, `_id` optional.
    pub fn upsert(&self, db: &str, space: &str, documents: Value)
            -> Result<Value> {
        self.call("POST", "/document/upsert", Some(json!({
            "db_name": db, "space_name": space, "documents": documents,
        })))
    }

    /// Returns `documents`: one hit list per query.
    pub fn search(&self, db: &str, space: &str, vectors: &[SearchVector],
                  limit: u32, extra: Option<Value>) -> Result<Value> {
        let mut body = json!({
            "db_name": db, "space_name": space,
            "vectors": vectors, "limit": limit,
        });
        if let (Some(obj), Some(Value::Object(ex))) =
                (body.as_object_mut(), extra) {
            for (k, v) in ex {
                obj.insert(k, v);
            }
        }
        self.call("POST", "/document/search", Some(body))
    }

    pub fn query(&self, db: &str, space: &str, ids: &[&str],
                 filters: Option<Value>, limit: u32, offset: u32)
            -> Result<Value> {
        let mut body = json!({
            "db_name": db, "space_name": space,
            "limit": limit, "offset": offset,
        });
        let obj = body.as_object_mut().unwrap();
        if !ids.is_empty() {
            obj.insert("document_ids".into(), json!(ids));
        }
        if let Some(f) = filters {
            obj.insert("filters".into(), f);
        }
        self.call("POST", "/document/query", Some(body))
    }

    /// `limit`: global delete budget (None = unbounded, 0 = nothing).
    pub fn delete(&self, db: &str, space: &str, ids: &[&str],
                  filters: Option<Value>, limit: Option<u64>)
            -> Result<Value> {
        let mut body = json!({"db_name": db, "space_name": space});
        let obj = body.as_object_mut().unwrap();
        if !ids.is_empty() {
            obj.insert("document_ids".into(), json!(ids));
        }
        if let Some(f) = filters {
            obj.insert("filters".into(), f);
        }
        if let Some(l) = limit {
            obj.insert("limit".into(), json!(l));
        }
        self.call("POST", "/document/delete", Some(body))
    }

    // -- index ops -----------------------------------------------------

    pub fn flush(&self, db: &str, space: &str) -> Result<Value> {
        self.index_op("/index/flush", db, space)
    }

    pub fn force_merge(&self, db: &str, space: &str) -> Result<Value> {
        self.index_op("/index/forcemerge", db, space)
    }

    pub fn rebuild(&self, db: &str, space: &str) -> Result<Value> {
        self.index_op("/index/rebuild", db, space)
    }

    fn index_op(&self, path: &str, db: &str, space: &str) -> Result<Value> {
        self.call("POST", path, Some(json!({
            "db_name": db, "space_name": space,
        })))
    }


    // -- sorted reads --------------------------------------------------

    /// Query with a scalar-field sort spec (same JSON forms as search:
    /// `"field"`, `[{"field":"asc"}]`,
    /// `[{"field":{"order":"desc","missing":"_last"}}]`).
    pub fn query_sorted(&self, db: &str, space: &str, filters: Option<Value>,
                        limit: i64, offset: i64, sort: Value)
                        -> Result<Value> {
        let mut body = json!({
            "db_name": db, "space_name": space,
            "limit": limit, "offset": offset, "sort": sort,
        });
        if let Some(f) = filters {
            body["filters"] = f;
        }
        self.call("POST", "/document/query", Some(body))
    }

    // -- space update / detail -----------------------------------------

    /// Online space changes (partition_num expansion, new fields).
    pub fn update_space(&self, db: &str, space: &str, config: Value)
                        -> Result<Value> {
        self.call("PUT", &format!("/dbs/{db}/spaces/{space}"), Some(config))
    }

    /// Space metadata with per-partition stats (`?detail=true`).
    pub fn space_detail(&self, db: &str, space: &str) -> Result<Value> {
        self.call("GET",
                  &format!("/dbs/{db}/spaces/{space}?detail=true"), None)
    }

    // -- scalar field indexes ------------------------------------------

    /// `index_type` INVERTED/BITMAP builds; NONE removes.
    pub fn field_index(&self, db: &str, space: &str, field: &str,
                       index_type: &str, background: bool) -> Result<Value> {
        self.call("POST", "/field_index", Some(json!({
            "db_name": db, "space_name": space, "field": field,
            "index_type": index_type, "background": background,
        })))
    }

    // -- backup / restore ----------------------------------------------

    /// Backup command create/list/restore/delete; `create` with
    /// `async_job` runs as an async job — poll [`Client::backup_job`].
    /// `store` is `{"store_root": "/path"}` or `{"store": {s3 spec}}`.
    pub fn backup(&self, db: &str, space: &str, command: &str,
                  version: Option<i64>, store: Value, async_job: bool)
                  -> Result<Value> {
        let mut body = json!({"command": command});
        if let Some(v) = version {
            body["version"] = json!(v);
        }
        if async_job {
            body["async"] = json!(true);
        }
        if let Some(obj) = store.as_object() {
            for (k, v) in obj {
                body[k] = v.clone();
            }
        }
        self.call("POST",
                  &format!("/backup/dbs/{db}/spaces/{space}"), Some(body))
    }

    /// Async backup-job progress record.
    pub fn backup_job(&self, job_id: &str) -> Result<Value> {
        self.call("GET", &format!("/backup/jobs/{job_id}"), None)
    }

    // -- aliases -------------------------------------------------------

    pub fn create_alias(&self, alias: &str, db: &str, space: &str)
                        -> Result<Value> {
        self.call("POST",
                  &format!("/alias/{alias}/dbs/{db}/spaces/{space}"), None)
    }

    pub fn get_alias(&self, alias: &str) -> Result<Value> {
        self.call("GET", &format!("/alias/{alias}"), None)
    }

    pub fn drop_alias(&self, alias: &str) -> Result<Value> {
        self.call("DELETE", &format!("/alias/{alias}"), None)
    }

    // -- cluster views / ops -------------------------------------------

    pub fn cluster_stats(&self) -> Result<Value> {
        self.call("GET", "/cluster/stats", None)
    }

    pub fn cluster_health(&self) -> Result<Value> {
        self.call("GET", "/cluster/health", None)
    }

    pub fn members(&self) -> Result<Value> {
        self.call("GET", "/members", None)
    }

    pub fn member_add(&self, node_id: i64, addr: &str) -> Result<Value> {
        self.call("POST", "/members/add", Some(json!({
            "node_id": node_id, "addr": addr,
        })))
    }

    pub fn member_remove(&self, node_id: i64) -> Result<Value> {
        self.call("POST", "/members/remove", Some(json!({
            "node_id": node_id,
        })))
    }

    pub fn servers(&self) -> Result<Value> {
        self.call("GET", "/servers", None)
    }

    pub fn partitions(&self) -> Result<Value> {
        self.call("GET", "/partitions", None)
    }

    /// Moves a partition replica; `method` is `"add"` or `"remove"`.
    pub fn change_member(&self, partition_id: i64, node_id: i64,
                         method: &str) -> Result<Value> {
        self.call("POST", "/partitions/change_member", Some(json!({
            "partition_id": partition_id, "node_id": node_id,
            "method": method,
        })))
    }

    pub fn fail_servers(&self) -> Result<Value> {
        self.call("GET", "/schedule/fail_server", None)
    }

    pub fn recover_server(&self, node_id: i64) -> Result<Value> {
        self.call("POST", "/schedule/recover_server", Some(json!({
            "node_id": node_id,
        })))
    }

    // -- runtime config ------------------------------------------------

    pub fn set_config(&self, db: &str, space: &str, config: Value)
                      -> Result<Value> {
        self.call("POST", &format!("/config/{db}/{space}"), Some(config))
    }

    pub fn get_config(&self, db: &str, space: &str) -> Result<Value> {
        self.call("GET", &format!("/config/{db}/{space}"), None)
    }

    // -- users / roles (RBAC) ------------------------------------------

    pub fn create_user(&self, name: &str, password: &str, role_name: &str)
                       -> Result<Value> {
        self.call("POST", "/users", Some(json!({
            "name": name, "password": password, "role_name": role_name,
        })))
    }

    pub fn get_user(&self, name: &str) -> Result<Value> {
        self.call("GET", &format!("/users/{name}"), None)
    }

    pub fn delete_user(&self, name: &str) -> Result<Value> {
        self.call("DELETE", &format!("/users/{name}"), None)
    }

    /// `privileges` e.g. `{"ResourceAll": "ReadOnly"}`.
    pub fn create_role(&self, name: &str, privileges: Value)
                       -> Result<Value> {
        self.call("POST", "/roles", Some(json!({
            "name": name, "privileges": privileges,
        })))
    }

    pub fn get_role(&self, name: &str) -> Result<Value> {
        self.call("GET", &format!("/roles/{name}"), None)
    }


    /// Online partition-rule admin: `op` ADD (with `rule`) or DROP
    /// (with `partition_name`).
    pub fn partition_rule(&self, db: &str, space: &str, op: &str,
                          partition_name: Option<&str>,
                          rule: Option<Value>) -> Result<Value> {
        let mut body = json!({
            "db_name": db, "space_name": space, "operator_type": op,
        });
        if let Some(p) = partition_name {
            body["partition_name"] = json!(p);
        }
        if let Some(r) = rule {
            body["partition_rule"] = r;
        }
        self.call("POST", "/partitions/rule", Some(body))
    }

    pub fn routers(&self) -> Result<Value> {
        self.call("GET", "/routers", None)
    }

    pub fn is_live(&self) -> bool {
        self.call("GET", "/cluster/health", None).is_ok()
    }
}

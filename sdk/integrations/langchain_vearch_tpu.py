"""LangChain vector-store integration for vearch-tpu.

Mirrors the reference's LangChain integration surface (reference:
sdk/integrations/langchain — add_texts / similarity_search /
similarity_search_with_score / delete / from_texts over the Python
SDK). `langchain` is not a hard dependency: when `langchain_core` is
importable the class registers as a real `VectorStore` subclass and
returns its `Document` type; otherwise it works standalone with a
lightweight Document stand-in, so the adapter is testable (and usable)
without LangChain installed.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Iterable, Sequence

try:  # pragma: no cover - exercised only when langchain is installed
    from langchain_core.documents import Document  # type: ignore
    from langchain_core.vectorstores import VectorStore  # type: ignore

    _HAVE_LANGCHAIN = True
except Exception:  # langchain absent: duck-typed stand-ins
    _HAVE_LANGCHAIN = False

    class Document:  # type: ignore[no-redef]
        def __init__(self, page_content: str, metadata: dict | None = None):
            self.page_content = page_content
            self.metadata = metadata or {}

        def __repr__(self) -> str:
            return f"Document({self.page_content!r})"

    class VectorStore:  # type: ignore[no-redef]
        pass


class VearchTpuVectorStore(VectorStore):
    """Store texts + embeddings in a vearch-tpu space.

    embedding: either an object with `embed_documents(texts)` /
    `embed_query(text)` (the LangChain Embeddings protocol) or a plain
    callable `texts -> [[float]]`.
    """

    def __init__(
        self,
        client,
        db_name: str,
        space_name: str,
        embedding,
        dimension: int | None = None,
        text_field: str = "text",
        vector_field: str = "vector",
        index_type: str = "FLAT",
        metric_type: str = "L2",
        index_params: dict | None = None,
        create: bool = True,
    ):
        self.client = client
        self.db_name = db_name
        self.space_name = space_name
        self.embedding = embedding
        self.text_field = text_field
        self.vector_field = vector_field
        if create:
            from . import ensure_space

            if dimension is None:
                dimension = len(self._embed_query("dimension probe"))
            ensure_space(client, db_name, space_name, [
                {"name": text_field, "data_type": "string"},
                {"name": "metadata", "data_type": "string"},
                {"name": vector_field, "data_type": "vector",
                 "dimension": dimension,
                 "index": {"index_type": index_type,
                           "metric_type": metric_type,
                           "params": index_params or {}}},
            ])

    # -- embedding dispatch --------------------------------------------------

    def _embed_documents(self, texts: list[str]) -> list[list[float]]:
        if hasattr(self.embedding, "embed_documents"):
            return self.embedding.embed_documents(texts)
        return [list(map(float, v)) for v in self.embedding(texts)]

    def _embed_query(self, text: str) -> list[float]:
        if hasattr(self.embedding, "embed_query"):
            return self.embedding.embed_query(text)
        return list(map(float, self.embedding([text])[0]))

    # -- VectorStore surface -------------------------------------------------

    def add_texts(
        self,
        texts: Iterable[str],
        metadatas: list[dict] | None = None,
        ids: list[str] | None = None,
        **kwargs: Any,
    ) -> list[str]:
        import json

        texts = list(texts)
        ids = ids or [uuid.uuid4().hex for _ in texts]
        metadatas = metadatas or [{} for _ in texts]
        if len(ids) != len(texts) or len(metadatas) != len(texts):
            # validate BEFORE embedding: the embed call may be a paid API
            raise ValueError(
                f"length mismatch: {len(texts)} texts, {len(ids)} ids, "
                f"{len(metadatas)} metadatas"
            )
        vectors = self._embed_documents(texts)
        docs = [
            {"_id": i, self.text_field: t, "metadata": json.dumps(m),
             self.vector_field: v}
            for i, t, m, v in zip(ids, texts, metadatas, vectors)
        ]
        self.client.upsert(self.db_name, self.space_name, docs)
        return ids

    def similarity_search_with_score(
        self, query: str, k: int = 4, filter: dict | None = None,
        **kwargs: Any
    ) -> list[tuple[Document, float]]:
        import json

        vec = self._embed_query(query)
        hits = self.client.search(
            self.db_name, self.space_name,
            [{"field": self.vector_field, "feature": vec}], limit=k,
            filters=filter,
        )
        out: list[tuple[Document, float]] = []
        for h in hits[0]:
            meta = {}
            try:
                meta = json.loads(h.get("metadata") or "{}")
            except Exception:
                pass
            meta["_id"] = h["_id"]
            out.append((
                Document(page_content=h.get(self.text_field, ""),
                         metadata=meta),
                float(h["_score"]),
            ))
        return out

    def similarity_search(
        self, query: str, k: int = 4, **kwargs: Any
    ) -> list[Document]:
        return [
            d for d, _ in self.similarity_search_with_score(query, k,
                                                            **kwargs)
        ]

    def delete(self, ids: list[str] | None = None, **kwargs: Any
               ) -> bool | None:
        if not ids:
            return False
        self.client.delete(self.db_name, self.space_name, document_ids=ids)
        return True

    @classmethod
    def from_texts(
        cls,
        texts: list[str],
        embedding,
        metadatas: list[dict] | None = None,
        *,
        client=None,
        db_name: str = "langchain",
        space_name: str = "langchain",
        **kwargs: Any,
    ) -> "VearchTpuVectorStore":
        if client is None:  # not assert: must survive python -O
            raise ValueError("pass client=VearchClient(router_addr)")
        store = cls(client, db_name, space_name, embedding, **kwargs)
        store.add_texts(texts, metadatas)
        return store

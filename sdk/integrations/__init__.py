"""Shared helpers for the framework integrations."""

from __future__ import annotations


def ensure_space(client, db_name: str, space_name: str,
                 fields: list[dict], partition_num: int = 1) -> None:
    """Create the database and space, tolerating only already-exists
    (409); every other failure — bad credentials, unreachable cluster,
    invalid schema — surfaces immediately instead of at first use."""
    from vearch_tpu.cluster.rpc import RpcError

    try:
        client.create_database(db_name)
    except RpcError as e:
        if e.code != 409:
            raise
    try:
        client.create_space(db_name, {
            "name": space_name,
            "partition_num": partition_num,
            "fields": fields,
        })
    except RpcError as e:
        if e.code != 409:
            raise

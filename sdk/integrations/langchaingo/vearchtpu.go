// Package vearchtpu implements a langchaingo vectorstores.VectorStore
// backed by a vearch-tpu cluster (reference intent:
// sdk/integrations/* ship framework adapters; langchaingo's Vearch
// store upstream speaks the same REST surface this adapter does via
// the Go SDK in sdk/go).
//
// Usage:
//
//	store, _ := vearchtpu.New(client, embedder,
//	    vearchtpu.WithSpace("db", "docs", 768))
//	store.AddDocuments(ctx, docs)
//	hits, _ := store.SimilaritySearch(ctx, "query", 4)
//
// NOTE: no Go toolchain ships in this image; compile-verified by
// consumers (same policy as sdk/go, docs/PARITY.md).
package vearchtpu

import (
	"context"
	"fmt"

	"github.com/tmc/langchaingo/embeddings"
	"github.com/tmc/langchaingo/schema"
	"github.com/tmc/langchaingo/vectorstores"

	vearch "github.com/vearch-tpu/sdk/go"
)

// Store adapts a vearch-tpu space to langchaingo's VectorStore.
type Store struct {
	client   *vearch.Client
	embedder embeddings.Embedder
	db       string
	space    string
	dim      int
	textKey  string
}

var _ vectorstores.VectorStore = (*Store)(nil)

// Option configures a Store.
type Option func(*Store)

// WithSpace names the target db/space and the embedding dimension.
func WithSpace(db, space string, dim int) Option {
	return func(s *Store) { s.db, s.space, s.dim = db, space, dim }
}

// WithTextKey overrides the scalar field storing the document text
// (default "text").
func WithTextKey(k string) Option {
	return func(s *Store) { s.textKey = k }
}

// New builds a Store; EnsureSpace creates the backing space when absent.
func New(client *vearch.Client, embedder embeddings.Embedder,
	opts ...Option) (*Store, error) {
	s := &Store{client: client, embedder: embedder, textKey: "text"}
	for _, o := range opts {
		o(s)
	}
	if s.db == "" || s.space == "" || s.dim == 0 {
		return nil, fmt.Errorf("vearchtpu: WithSpace(db, space, dim) is required")
	}
	return s, nil
}

// EnsureSpace creates the database and space (FLAT Cosine + text field)
// if they do not exist yet.
func (s *Store) EnsureSpace() error {
	_ = s.client.CreateDatabase(s.db) // idempotent-ish: exists -> error ignored
	_, err := s.client.CreateSpace(s.db, vearch.SpaceConfig{
		Name: s.space, PartitionNum: 1, ReplicaNum: 1,
		Fields: []vearch.Field{
			{Name: s.textKey, DataType: "string"},
			{Name: "embedding", DataType: "vector", Dimension: s.dim,
				Index: map[string]any{
					"index_type": "FLAT", "metric_type": "Cosine",
					"params": map[string]any{},
				}},
		},
	})
	if err != nil {
		if apiErr, ok := err.(*vearch.APIError); ok && apiErr.Code == 409 {
			return nil // already exists
		}
		return err
	}
	return nil
}

// AddDocuments embeds and upserts docs; returns assigned ids.
func (s *Store) AddDocuments(ctx context.Context, docs []schema.Document,
	_ ...vectorstores.Option) ([]string, error) {
	texts := make([]string, len(docs))
	for i, d := range docs {
		texts[i] = d.PageContent
	}
	vecs, err := s.embedder.EmbedDocuments(ctx, texts)
	if err != nil {
		return nil, err
	}
	rows := make([]vearch.Document, len(docs))
	for i, d := range docs {
		row := vearch.Document{
			s.textKey: d.PageContent, "embedding": vecs[i],
		}
		for k, v := range d.Metadata {
			row[k] = v
		}
		rows[i] = row
	}
	return s.client.Upsert(s.db, s.space, rows)
}

// SimilaritySearch embeds the query and returns the top numDocuments
// segments as schema.Documents with the similarity score attached.
// vectorstores.WithScoreThreshold is honored (hits below it are
// dropped); WithFilters expects the server's filter AST
// ({operator, conditions}) and rides the request as-is.
func (s *Store) SimilaritySearch(ctx context.Context, query string,
	numDocuments int, options ...vectorstores.Option) ([]schema.Document, error) {
	opts := vectorstores.Options{}
	for _, o := range options {
		o(&opts)
	}
	qv, err := s.embedder.EmbedQuery(ctx, query)
	if err != nil {
		return nil, err
	}
	req := vearch.SearchRequest{
		DBName: s.db, SpaceName: s.space,
		Vectors: []vearch.SearchVector{{Field: "embedding", Feature: qv}},
		Limit:   numDocuments,
	}
	if f, ok := opts.Filters.(map[string]any); ok {
		req.Filters = f
	}
	hits, err := s.client.Search(req)
	if err != nil {
		return nil, err
	}
	if len(hits) == 0 {
		return nil, nil
	}
	out := make([]schema.Document, 0, len(hits[0]))
	for _, h := range hits[0] {
		if opts.ScoreThreshold > 0 &&
			float32(h.Score()) < opts.ScoreThreshold {
			continue
		}
		text, _ := h[s.textKey].(string)
		meta := map[string]any{}
		for k, v := range h {
			if k != s.textKey && k != "_id" && k != "_score" {
				meta[k] = v
			}
		}
		out = append(out, schema.Document{
			PageContent: text, Metadata: meta, Score: float32(h.Score()),
		})
	}
	return out, nil
}

// RemoveByIDs deletes documents by id (langchaingo has no standard
// delete; exposed for parity with the other adapters).
func (s *Store) RemoveByIDs(_ context.Context, ids []string) (int, error) {
	return s.client.Delete(s.db, s.space, ids, nil, -1)
}

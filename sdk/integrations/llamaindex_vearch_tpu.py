"""LlamaIndex vector-store integration for vearch-tpu.

Mirrors the reference's LlamaIndex integration surface (reference:
sdk/integrations/llama-index — add / delete / query over the Python
SDK). `llama_index` is not a hard dependency: when importable, the
store returns real `VectorStoreQueryResult` objects; otherwise small
duck-typed stand-ins with the same attributes, so the adapter is
usable and testable without LlamaIndex installed.

The store expects "nodes" with `node_id`, `get_content()` and
`embedding` (the LlamaIndex BaseNode protocol) and answers
`VectorStoreQuery`-shaped queries (`query_embedding`,
`similarity_top_k`).
"""

from __future__ import annotations

import json
from typing import Any, Sequence

try:  # pragma: no cover - only with llama_index installed
    from llama_index.core.schema import TextNode  # type: ignore
    from llama_index.core.vector_stores.types import (  # type: ignore
        VectorStoreQuery,
        VectorStoreQueryResult,
    )

    _HAVE_LLAMA = True
except Exception:
    _HAVE_LLAMA = False

    class TextNode:  # type: ignore[no-redef]
        def __init__(self, text: str = "", id_: str = "",
                     embedding: list[float] | None = None,
                     metadata: dict | None = None):
            self.text = text
            self.node_id = id_
            self.embedding = embedding
            self.metadata = metadata or {}

        def get_content(self, **kwargs: Any) -> str:
            return self.text

    class VectorStoreQuery:  # type: ignore[no-redef]
        def __init__(self, query_embedding=None, similarity_top_k: int = 2):
            self.query_embedding = query_embedding
            self.similarity_top_k = similarity_top_k

    class VectorStoreQueryResult:  # type: ignore[no-redef]
        def __init__(self, nodes=None, similarities=None, ids=None):
            self.nodes = nodes
            self.similarities = similarities
            self.ids = ids


class VearchTpuLlamaVectorStore:
    """LlamaIndex-protocol vector store over a vearch-tpu cluster."""

    stores_text = True

    def __init__(
        self,
        client,
        db_name: str,
        space_name: str,
        dimension: int,
        index_type: str = "FLAT",
        metric_type: str = "L2",
        index_params: dict | None = None,
        create: bool = True,
    ):
        self.client = client
        self.db_name = db_name
        self.space_name = space_name
        if create:
            from . import ensure_space

            ensure_space(client, db_name, space_name, [
                {"name": "text", "data_type": "string"},
                {"name": "metadata", "data_type": "string"},
                # source-document id so delete(ref_doc_id) can purge
                # every node of a document (the LlamaIndex contract)
                {"name": "ref_doc_id", "data_type": "string"},
                {"name": "vector", "data_type": "vector",
                 "dimension": dimension,
                 "index": {"index_type": index_type,
                           "metric_type": metric_type,
                           "params": index_params or {}}},
            ])

    def add(self, nodes: Sequence, **kwargs: Any) -> list[str]:
        docs = []
        for node in nodes:
            if node.embedding is None:
                raise ValueError(
                    f"node {node.node_id!r} has no embedding; run an "
                    f"embedding model before add()"
                )
            docs.append({
                "_id": node.node_id,
                "text": node.get_content(),
                "metadata": json.dumps(getattr(node, "metadata", {}) or {}),
                "ref_doc_id": str(getattr(node, "ref_doc_id", None)
                                  or node.node_id),
                "vector": list(map(float, node.embedding)),
            })
        self.client.upsert(self.db_name, self.space_name, docs)
        return [d["_id"] for d in docs]

    def delete_nodes(self, node_ids: list[str] | None = None,
                     **kwargs: Any) -> None:
        if node_ids:
            self.client.delete(self.db_name, self.space_name,
                               document_ids=node_ids)

    def delete(self, ref_doc_id: str, **kwargs: Any) -> None:
        """Remove every node derived from one source document (the
        LlamaIndex document-level deletion contract) via the stored
        ref_doc_id column."""
        self.client.delete(self.db_name, self.space_name, filters={
            "operator": "AND",
            "conditions": [{"field": "ref_doc_id", "operator": "=",
                            "value": str(ref_doc_id)}],
        })

    def query(self, query, **kwargs: Any):
        if query.query_embedding is None:
            raise ValueError("query has no query_embedding")
        if getattr(query, "filters", None):
            # metadata rides as an opaque JSON string here; silently
            # ignoring MetadataFilters would return excluded documents
            raise ValueError(
                "MetadataFilters are not supported by this store yet; "
                "filter on scalar fields via the vearch-tpu SDK instead"
            )
        hits = self.client.search(
            self.db_name, self.space_name,
            [{"field": "vector",
              "feature": list(map(float, query.query_embedding))}],
            limit=int(query.similarity_top_k),
        )
        nodes, sims, ids = [], [], []
        for h in hits[0]:
            meta = {}
            try:
                meta = json.loads(h.get("metadata") or "{}")
            except Exception:
                pass
            nodes.append(TextNode(text=h.get("text", ""), id_=h["_id"],
                                  metadata=meta))
            sims.append(float(h["_score"]))
            ids.append(h["_id"])
        return VectorStoreQueryResult(nodes=nodes, similarities=sims,
                                      ids=ids)

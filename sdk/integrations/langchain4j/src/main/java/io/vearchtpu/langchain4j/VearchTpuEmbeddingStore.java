package io.vearchtpu.langchain4j;

import dev.langchain4j.data.document.Metadata;
import dev.langchain4j.data.embedding.Embedding;
import dev.langchain4j.data.segment.TextSegment;
import dev.langchain4j.store.embedding.EmbeddingMatch;
import dev.langchain4j.store.embedding.EmbeddingSearchRequest;
import dev.langchain4j.store.embedding.EmbeddingSearchResult;
import dev.langchain4j.store.embedding.EmbeddingStore;

import io.vearchtpu.VearchTpuClient;

import java.io.IOException;
import java.util.ArrayList;
import java.util.List;
import java.util.UUID;

/**
 * LangChain4j {@link EmbeddingStore} backed by a vearch-tpu cluster
 * (reference intent: sdk/integrations/langchain4j — upstream ships a
 * Vearch example against the same REST surface; this adapter speaks it
 * through {@link VearchTpuClient}).
 *
 * <p>The backing space needs a string field {@code text}, a vector
 * field {@code embedding} (InnerProduct/cosine), and is created by
 * {@link #ensureSpace(int)} when absent. Metadata entries are stored as
 * top-level scalar fields.
 *
 * <p>NOTE: no JDK ships in this image; compile-verified by consumers
 * (same policy as sdk/java, docs/PARITY.md).
 */
public final class VearchTpuEmbeddingStore implements EmbeddingStore<TextSegment> {

    private final VearchTpuClient client;
    private final String db;
    private final String space;

    public VearchTpuEmbeddingStore(VearchTpuClient client, String db,
            String space) {
        this.client = client;
        this.db = db;
        this.space = space;
    }

    /** Creates the backing db/space (FLAT InnerProduct) when absent. */
    public void ensureSpace(int dimension) throws IOException,
            InterruptedException {
        try {
            client.createDatabase(db);
        } catch (VearchTpuClient.ApiException e) {
            if (e.code != 409) {
                throw e;  // only duplicates are fine; surface real errors
            }
        }
        try {
            client.createSpace(db, "{\"name\":\"" + space + "\","
                    + "\"partition_num\":1,\"replica_num\":1,"
                    + "\"fields\":["
                    + "{\"name\":\"text\",\"data_type\":\"string\"},"
                    + "{\"name\":\"embedding\",\"data_type\":\"vector\","
                    + "\"dimension\":" + dimension + ","
                    + "\"index\":{\"index_type\":\"FLAT\","
                    + "\"metric_type\":\"Cosine\",\"params\":{}}}"
                    + "]}");
        } catch (VearchTpuClient.ApiException e) {
            if (e.code != 409) {
                throw e;
            }
        }
    }

    @Override
    public String add(Embedding embedding) {
        String id = UUID.randomUUID().toString();
        add(id, embedding);
        return id;
    }

    @Override
    public void add(String id, Embedding embedding) {
        upsertOne(id, embedding, null);
    }

    @Override
    public String add(Embedding embedding, TextSegment segment) {
        String id = UUID.randomUUID().toString();
        upsertOne(id, embedding, segment);
        return id;
    }

    @Override
    public List<String> addAll(List<Embedding> embeddings) {
        List<String> ids = new ArrayList<>(embeddings.size());
        for (Embedding e : embeddings) {
            ids.add(add(e));
        }
        return ids;
    }

    @Override
    public List<String> addAll(List<Embedding> embeddings,
            List<TextSegment> segments) {
        // ONE batched upsert: the SDK takes a JSON array, and 10k
        // chunks must not mean 10k round trips (review r5)
        List<String> ids = new ArrayList<>(embeddings.size());
        StringBuilder batch = new StringBuilder("[");
        for (int i = 0; i < embeddings.size(); i++) {
            String id = UUID.randomUUID().toString();
            ids.add(id);
            if (i > 0) batch.append(',');
            batch.append(docJson(id, embeddings.get(i),
                    segments == null ? null : segments.get(i)));
        }
        batch.append(']');
        try {
            client.upsert(db, space, batch.toString());
        } catch (IOException e) {
            throw new RuntimeException(e);
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
            throw new RuntimeException(e);
        }
        return ids;
    }

    @Override
    public EmbeddingSearchResult<TextSegment> search(
            EmbeddingSearchRequest request) {
        StringBuilder feature = new StringBuilder("[");
        float[] v = request.queryEmbedding().vector();
        for (int i = 0; i < v.length; i++) {
            if (i > 0) feature.append(',');
            feature.append(v[i]);
        }
        feature.append(']');
        try {
            String data = client.search(db, space,
                    "[{\"field\":\"embedding\",\"feature\":"
                            + feature + "}]",
                    request.maxResults(), null);
            return new EmbeddingSearchResult<>(
                    parseMatches(data, request.minScore()));
        } catch (IOException e) {
            throw new RuntimeException(e);
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
            throw new RuntimeException(e);
        }
    }

    // -- helpers -------------------------------------------------------

    private void upsertOne(String id, Embedding embedding,
            TextSegment segment) {
        try {
            client.upsert(db, space,
                    "[" + docJson(id, embedding, segment) + "]");
        } catch (IOException e) {
            throw new RuntimeException(e);
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
            throw new RuntimeException(e);
        }
    }

    private static String docJson(String id, Embedding embedding,
            TextSegment segment) {
        StringBuilder doc = new StringBuilder("{\"_id\":\"")
                .append(id).append("\",\"embedding\":[");
        float[] v = embedding.vector();
        for (int i = 0; i < v.length; i++) {
            if (i > 0) doc.append(',');
            doc.append(v[i]);
        }
        doc.append(']');
        if (segment != null) {
            doc.append(",\"text\":\"")
                    .append(escape(segment.text())).append('"');
        }
        return doc.append('}').toString();
    }

    private List<EmbeddingMatch<TextSegment>> parseMatches(String data,
            double minScore) {
        // dependency-free, JSON-STRING-AWARE parse of
        // {"documents": [[{"_id", "_score", "text"...}]]}: braces and
        // brackets inside stored text must not confuse the depth
        // counter (review r5)
        List<EmbeddingMatch<TextSegment>> out = new ArrayList<>();
        int rows = data.indexOf("[[");
        if (rows < 0) {
            return out;
        }
        String inner = data.substring(rows + 1);
        int depth = 0;
        int start = -1;
        boolean inString = false;
        boolean escaped = false;
        for (int i = 0; i < inner.length(); i++) {
            char ch = inner.charAt(i);
            if (inString) {
                if (escaped) {
                    escaped = false;
                } else if (ch == '\\') {
                    escaped = true;
                } else if (ch == '"') {
                    inString = false;
                }
                continue;
            }
            if (ch == '"') {
                inString = true;
            } else if (ch == '{') {
                if (depth == 0) start = i;
                depth++;
            } else if (ch == '}') {
                depth--;
                if (depth == 0 && start >= 0) {
                    String obj = inner.substring(start, i + 1);
                    String id = extract(obj, "_id");
                    String score = extract(obj, "_score");
                    String text = extract(obj, "text");
                    double sc = score == null ? 0.0
                            : Double.parseDouble(score);
                    if (sc >= minScore) {
                        out.add(new EmbeddingMatch<>(sc, id, null,
                                text == null ? null
                                        : TextSegment.from(text,
                                                new Metadata())));
                    }
                }
            } else if (ch == ']' && depth == 0) {
                break;
            }
        }
        return out;
    }

    private static String extract(String obj, String key) {
        int i = obj.indexOf('"' + key + '"');
        if (i < 0) {
            return null;
        }
        int colon = obj.indexOf(':', i);
        int j = colon + 1;
        while (j < obj.length() && obj.charAt(j) == ' ') j++;
        if (obj.charAt(j) == '"') {
            // scan to the CLOSING quote honoring escapes, unescaping
            // as we go (review r5: indexOf stopped at escaped quotes)
            StringBuilder sb = new StringBuilder();
            for (int k2 = j + 1; k2 < obj.length(); k2++) {
                char c = obj.charAt(k2);
                if (c == '\\' && k2 + 1 < obj.length()) {
                    char nxt = obj.charAt(++k2);
                    switch (nxt) {
                        case 'n': sb.append('\n'); break;
                        case 'r': sb.append('\r'); break;
                        case 't': sb.append('\t'); break;
                        default: sb.append(nxt);
                    }
                } else if (c == '"') {
                    return sb.toString();
                } else {
                    sb.append(c);
                }
            }
            return sb.toString();
        }
        int end = j;
        while (end < obj.length()
                && "-+.eE0123456789".indexOf(obj.charAt(end)) >= 0) {
            end++;
        }
        return obj.substring(j, end);
    }

    private static String escape(String s) {
        // full JSON string escaping incl. control characters (review
        // r5: a newline in a document chunk must not break the upsert)
        StringBuilder sb = new StringBuilder(s.length() + 8);
        for (int i = 0; i < s.length(); i++) {
            char c = s.charAt(i);
            switch (c) {
                case '\\': sb.append("\\\\"); break;
                case '"': sb.append("\\\""); break;
                case '\n': sb.append("\\n"); break;
                case '\r': sb.append("\\r"); break;
                case '\t': sb.append("\\t"); break;
                case '\b': sb.append("\\b"); break;
                case '\f': sb.append("\\f"); break;
                default:
                    if (c < 0x20) {
                        sb.append(String.format("\\u%04x", (int) c));
                    } else {
                        sb.append(c);
                    }
            }
        }
        return sb.toString();
    }
}

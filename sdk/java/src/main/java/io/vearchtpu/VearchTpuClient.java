package io.vearchtpu;

import java.io.IOException;
import java.net.URI;
import java.net.http.HttpClient;
import java.net.http.HttpRequest;
import java.net.http.HttpResponse;
import java.nio.charset.StandardCharsets;
import java.time.Duration;
import java.util.Base64;
import java.util.List;
import java.util.Map;

/**
 * Java client SDK for the vearch-tpu cluster REST surface (route names
 * mirror upstream vearch; reference: sdk/java public surface). Built on
 * java.net.http only — bodies are passed and returned as JSON strings
 * so the SDK stays dependency-free; pair it with any JSON library.
 *
 * NOTE: no JDK ships in this build image, so the class is
 * compile-verified by consumers rather than CI here (docs/PARITY.md).
 */
public final class VearchTpuClient {

    /** Server-side error envelope {@code {code, msg}}. */
    public static final class ApiException extends IOException {
        public final int code;

        public ApiException(int code, String msg) {
            super("vearch-tpu: code=" + code + " msg=" + msg);
            this.code = code;
        }
    }

    private final String routerUrl;
    private final HttpClient http;
    private String basicAuth;

    /** @param routerUrl e.g. {@code "http://127.0.0.1:8817"} */
    public VearchTpuClient(String routerUrl) {
        this.routerUrl = routerUrl.endsWith("/")
                ? routerUrl.substring(0, routerUrl.length() - 1) : routerUrl;
        this.http = HttpClient.newBuilder()
                .connectTimeout(Duration.ofSeconds(10)).build();
    }

    /** Enables BasicAuth on every request. */
    public VearchTpuClient withAuth(String user, String password) {
        this.basicAuth = Base64.getEncoder().encodeToString(
                (user + ":" + password).getBytes(StandardCharsets.UTF_8));
        return this;
    }

    /**
     * Raw JSON call. Returns the {@code data} member of the response
     * envelope as a JSON string (or the whole body when no envelope).
     */
    public String call(String method, String path, String jsonBody)
            throws IOException, InterruptedException {
        HttpRequest.Builder b = HttpRequest.newBuilder()
                .uri(URI.create(routerUrl + path))
                .timeout(Duration.ofSeconds(120))
                .header("Content-Type", "application/json");
        if (basicAuth != null) {
            b.header("Authorization", "Basic " + basicAuth);
        }
        HttpRequest.BodyPublisher body = jsonBody == null
                ? HttpRequest.BodyPublishers.noBody()
                : HttpRequest.BodyPublishers.ofString(jsonBody);
        HttpRequest req = b.method(method, body).build();
        HttpResponse<String> resp =
                http.send(req, HttpResponse.BodyHandlers.ofString());
        String raw = resp.body();
        Integer code = extractInt(raw, "\"code\"");
        if (code == null) {
            // no envelope (proxy/LB error page): trust the HTTP status —
            // a 502 body must never read as a successful write
            if (resp.statusCode() >= 300) {
                throw new ApiException(resp.statusCode(),
                        raw.substring(0, Math.min(raw.length(), 200)));
            }
            return raw;
        }
        if (code != 0) {
            throw new ApiException(code, extractString(raw, "\"msg\""));
        }
        int i = raw.indexOf("\"data\"");
        if (i < 0) {
            return raw;
        }
        return raw.substring(raw.indexOf(':', i) + 1,
                raw.lastIndexOf('}')).trim();
    }

    // -- databases / spaces --------------------------------------------------

    public String createDatabase(String db)
            throws IOException, InterruptedException {
        return call("POST", "/dbs/" + db, null);
    }

    public String dropDatabase(String db)
            throws IOException, InterruptedException {
        return call("DELETE", "/dbs/" + db, null);
    }

    /** @param spaceConfigJson {@code {name, partition_num, fields: [...]}} */
    public String createSpace(String db, String spaceConfigJson)
            throws IOException, InterruptedException {
        return call("POST", "/dbs/" + db + "/spaces", spaceConfigJson);
    }

    public String getSpace(String db, String space)
            throws IOException, InterruptedException {
        return call("GET", "/dbs/" + db + "/spaces/" + space, null);
    }

    public String dropSpace(String db, String space)
            throws IOException, InterruptedException {
        return call("DELETE", "/dbs/" + db + "/spaces/" + space, null);
    }

    // -- documents -----------------------------------------------------------

    /** @param documentsJson JSON array of documents (each may carry _id) */
    public String upsert(String db, String space, String documentsJson)
            throws IOException, InterruptedException {
        return call("POST", "/document/upsert",
                "{\"db_name\":" + q(db) + ",\"space_name\":" + q(space)
                        + ",\"documents\":" + documentsJson + "}");
    }

    /**
     * @param vectorsJson JSON array like
     *   {@code [{"field":"emb","feature":[...]}]} (flattened batch)
     */
    public String search(String db, String space, String vectorsJson,
            int limit, String extraJsonFields)
            throws IOException, InterruptedException {
        StringBuilder sb = new StringBuilder()
                .append("{\"db_name\":").append(q(db))
                .append(",\"space_name\":").append(q(space))
                .append(",\"vectors\":").append(vectorsJson)
                .append(",\"limit\":").append(limit);
        if (extraJsonFields != null && !extraJsonFields.isEmpty()) {
            sb.append(',').append(extraJsonFields);
        }
        return call("POST", "/document/search", sb.append('}').toString());
    }

    public String query(String db, String space, List<String> documentIds,
            String filtersJson, int limit, int offset)
            throws IOException, InterruptedException {
        StringBuilder sb = new StringBuilder()
                .append("{\"db_name\":").append(q(db))
                .append(",\"space_name\":").append(q(space))
                .append(",\"limit\":").append(limit)
                .append(",\"offset\":").append(offset);
        if (documentIds != null && !documentIds.isEmpty()) {
            sb.append(",\"document_ids\":[");
            for (int i = 0; i < documentIds.size(); i++) {
                if (i > 0) sb.append(',');
                sb.append(q(documentIds.get(i)));
            }
            sb.append(']');
        }
        if (filtersJson != null) {
            sb.append(",\"filters\":").append(filtersJson);
        }
        return call("POST", "/document/query", sb.append('}').toString());
    }

    public String delete(String db, String space, List<String> documentIds,
            String filtersJson, Integer limit)
            throws IOException, InterruptedException {
        StringBuilder sb = new StringBuilder()
                .append("{\"db_name\":").append(q(db))
                .append(",\"space_name\":").append(q(space));
        if (documentIds != null && !documentIds.isEmpty()) {
            sb.append(",\"document_ids\":[");
            for (int i = 0; i < documentIds.size(); i++) {
                if (i > 0) sb.append(',');
                sb.append(q(documentIds.get(i)));
            }
            sb.append(']');
        }
        if (filtersJson != null) {
            sb.append(",\"filters\":").append(filtersJson);
        }
        if (limit != null) {
            sb.append(",\"limit\":").append(limit);
        }
        return call("POST", "/document/delete", sb.append('}').toString());
    }

    // -- index ops -----------------------------------------------------------

    public String flush(String db, String space)
            throws IOException, InterruptedException {
        return indexOp("/index/flush", db, space);
    }

    public String forceMerge(String db, String space)
            throws IOException, InterruptedException {
        return indexOp("/index/forcemerge", db, space);
    }

    public String rebuild(String db, String space)
            throws IOException, InterruptedException {
        return indexOp("/index/rebuild", db, space);
    }

    private String indexOp(String path, String db, String space)
            throws IOException, InterruptedException {
        return call("POST", path, "{\"db_name\":" + q(db)
                + ",\"space_name\":" + q(space) + "}");
    }


    // -- sorted reads --------------------------------------------------------

    /**
     * Query with a scalar-field sort spec (same JSON forms as search:
     * {@code "field"}, {@code [{"field":"asc"}]},
     * {@code [{"field":{"order":"desc","missing":"_last"}}]}).
     */
    public String querySorted(String db, String space,
            String filtersJson, int limit, int offset, String sortJson)
            throws IOException, InterruptedException {
        StringBuilder sb = new StringBuilder()
                .append("{\"db_name\":").append(q(db))
                .append(",\"space_name\":").append(q(space))
                .append(",\"limit\":").append(limit)
                .append(",\"offset\":").append(offset);
        if (filtersJson != null) {
            sb.append(",\"filters\":").append(filtersJson);
        }
        if (sortJson != null) {
            sb.append(",\"sort\":").append(sortJson);
        }
        return call("POST", "/document/query", sb.append('}').toString());
    }

    // -- space update / detail ----------------------------------------------

    /** Online space changes (partition_num expansion, new fields). */
    public String updateSpace(String db, String space, String configJson)
            throws IOException, InterruptedException {
        return call("PUT", "/dbs/" + db + "/spaces/" + space, configJson);
    }

    /** Space metadata with per-partition stats (?detail=true). */
    public String spaceDetail(String db, String space)
            throws IOException, InterruptedException {
        return call("GET",
                "/dbs/" + db + "/spaces/" + space + "?detail=true", null);
    }

    // -- scalar field indexes ------------------------------------------------

    /** indexType INVERTED/BITMAP builds; NONE removes. */
    public String fieldIndex(String db, String space, String field,
            String indexType, boolean background)
            throws IOException, InterruptedException {
        return call("POST", "/field_index",
                "{\"db_name\":" + q(db) + ",\"space_name\":" + q(space)
                        + ",\"field\":" + q(field)
                        + ",\"index_type\":" + q(indexType)
                        + ",\"background\":" + background + "}");
    }

    // -- backup / restore ----------------------------------------------------

    /**
     * Backup command: create/list/restore/delete. create defaults to an
     * async job — poll {@link #backupJob(String)} with the returned
     * job_id. storeJson names the destination:
     * {@code {"store_root": "/path"}} or an s3 spec under "store".
     */
    public String backup(String db, String space, String command,
            Integer version, String storeJson, boolean async)
            throws IOException, InterruptedException {
        StringBuilder sb = new StringBuilder()
                .append("{\"command\":").append(q(command));
        if (version != null) {
            sb.append(",\"version\":").append(version);
        }
        if (async) {
            sb.append(",\"async\":true");
        }
        if (storeJson != null && !storeJson.isEmpty()) {
            // accept a braced JSON object (as documented) and merge its
            // members into the body, matching the Go/Rust SDKs
            String inner = storeJson.trim();
            if (inner.startsWith("{") && inner.endsWith("}")) {
                inner = inner.substring(1, inner.length() - 1).trim();
            }
            if (!inner.isEmpty()) {
                sb.append(',').append(inner);
            }
        }
        return call("POST", "/backup/dbs/" + db + "/spaces/" + space,
                sb.append('}').toString());
    }

    /** Async backup-job progress record. */
    public String backupJob(String jobId)
            throws IOException, InterruptedException {
        return call("GET", "/backup/jobs/" + jobId, null);
    }

    // -- aliases -------------------------------------------------------------

    public String createAlias(String alias, String db, String space)
            throws IOException, InterruptedException {
        return call("POST", "/alias/" + alias + "/dbs/" + db
                + "/spaces/" + space, null);
    }

    public String getAlias(String alias)
            throws IOException, InterruptedException {
        return call("GET", "/alias/" + alias, null);
    }

    public String dropAlias(String alias)
            throws IOException, InterruptedException {
        return call("DELETE", "/alias/" + alias, null);
    }

    // -- cluster views / ops -------------------------------------------------

    public String clusterStats()
            throws IOException, InterruptedException {
        return call("GET", "/cluster/stats", null);
    }

    public String clusterHealth()
            throws IOException, InterruptedException {
        return call("GET", "/cluster/health", null);
    }

    public String members()
            throws IOException, InterruptedException {
        return call("GET", "/members", null);
    }

    public String memberAdd(int nodeId, String addr)
            throws IOException, InterruptedException {
        return call("POST", "/members/add",
                "{\"node_id\":" + nodeId + ",\"addr\":" + q(addr) + "}");
    }

    public String memberRemove(int nodeId)
            throws IOException, InterruptedException {
        return call("POST", "/members/remove",
                "{\"node_id\":" + nodeId + "}");
    }

    public String servers()
            throws IOException, InterruptedException {
        return call("GET", "/servers", null);
    }

    public String partitions()
            throws IOException, InterruptedException {
        return call("GET", "/partitions", null);
    }

    public String changeMember(int partitionId, int nodeId, String method)
            throws IOException, InterruptedException {
        return call("POST", "/partitions/change_member",
                "{\"partition_id\":" + partitionId
                        + ",\"node_id\":" + nodeId
                        + ",\"method\":" + q(method) + "}");
    }

    public String failServers()
            throws IOException, InterruptedException {
        return call("GET", "/schedule/fail_server", null);
    }

    public String recoverServer(int nodeId)
            throws IOException, InterruptedException {
        return call("POST", "/schedule/recover_server",
                "{\"node_id\":" + nodeId + "}");
    }

    // -- runtime config ------------------------------------------------------

    public String setConfig(String db, String space, String configJson)
            throws IOException, InterruptedException {
        return call("POST", "/config/" + db + "/" + space, configJson);
    }

    public String getConfig(String db, String space)
            throws IOException, InterruptedException {
        return call("GET", "/config/" + db + "/" + space, null);
    }

    // -- users / roles (RBAC) ------------------------------------------------

    public String createUser(String name, String password, String roleName)
            throws IOException, InterruptedException {
        return call("POST", "/users",
                "{\"name\":" + q(name) + ",\"password\":" + q(password)
                        + ",\"role_name\":" + q(roleName) + "}");
    }

    public String getUser(String name)
            throws IOException, InterruptedException {
        return call("GET", "/users/" + name, null);
    }

    public String deleteUser(String name)
            throws IOException, InterruptedException {
        return call("DELETE", "/users/" + name, null);
    }

    /** @param privilegesJson e.g. {@code {"ResourceAll":"ReadOnly"}} */
    public String createRole(String name, String privilegesJson)
            throws IOException, InterruptedException {
        return call("POST", "/roles",
                "{\"name\":" + q(name) + ",\"privileges\":"
                        + privilegesJson + "}");
    }

    public String getRole(String name)
            throws IOException, InterruptedException {
        return call("GET", "/roles/" + name, null);
    }


    /** Online partition-rule admin: op ADD (with ruleJson) or DROP
     * (with partitionName). */
    public String partitionRule(String db, String space, String op,
            String partitionName, String ruleJson)
            throws IOException, InterruptedException {
        StringBuilder sb = new StringBuilder()
                .append("{\"db_name\":").append(q(db))
                .append(",\"space_name\":").append(q(space))
                .append(",\"operator_type\":").append(q(op));
        if (partitionName != null) {
            sb.append(",\"partition_name\":").append(q(partitionName));
        }
        if (ruleJson != null) {
            sb.append(",\"partition_rule\":").append(ruleJson);
        }
        return call("POST", "/partitions/rule", sb.append('}').toString());
    }

    public String routers()
            throws IOException, InterruptedException {
        return call("GET", "/routers", null);
    }

    public boolean isLive() {
        try {
            call("GET", "/cluster/health", null);
            return true;
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();  // preserve cancellation
            return false;
        } catch (Exception e) {
            return false;
        }
    }

    // -- helpers -------------------------------------------------------------

    private static String q(String s) {
        StringBuilder sb = new StringBuilder(s.length() + 2).append('"');
        for (int i = 0; i < s.length(); i++) {
            char c = s.charAt(i);
            switch (c) {
                case '\\': sb.append("\\\\"); break;
                case '"': sb.append("\\\""); break;
                case '\n': sb.append("\\n"); break;
                case '\r': sb.append("\\r"); break;
                case '\t': sb.append("\\t"); break;
                default:
                    if (c < 0x20) {
                        sb.append(String.format("\\u%04x", (int) c));
                    } else {
                        sb.append(c);
                    }
            }
        }
        return sb.append('"').toString();
    }

    /** The numeric value after {@code key}, or null when absent/non-numeric. */
    private static Integer extractInt(String json, String key) {
        int i = json.indexOf(key);
        if (i < 0) return null;
        int start = json.indexOf(':', i) + 1;
        int end = start;
        while (end < json.length()
                && (Character.isDigit(json.charAt(end))
                    || json.charAt(end) == '-'
                    || Character.isWhitespace(json.charAt(end)))) {
            end++;
        }
        String num = json.substring(start, end).trim();
        if (num.isEmpty()) return null;
        return Integer.parseInt(num);
    }

    private static String extractString(String json, String key) {
        int i = json.indexOf(key);
        if (i < 0) return "";
        int start = json.indexOf('"', json.indexOf(':', i)) + 1;
        int end = start;
        while (end < json.length() && json.charAt(end) != '"') {
            if (json.charAt(end) == '\\') end++;
            end++;
        }
        return json.substring(start, end);
    }
}

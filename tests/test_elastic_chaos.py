"""Elasticity chaos: kill the PS on the receiving end of a migration
and of a split mid-flight. The invariants under fire: the source keeps
serving, failed jobs land terminal (never wedge "running"), children
of a failed split are garbage-collected so a retry starts clean, and
no acked doc is ever lost."""

import time

import numpy as np
import pytest

from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient

D = 8


def _mk_space(cl, partition_num=1):
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": partition_num, "replica_num": 1,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })


def _wait_registered(master_addr, node_id, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        servers = rpc.call(master_addr, "GET", "/servers")["servers"]
        if any(s["node_id"] == node_id for s in servers):
            return
        time.sleep(0.1)
    raise AssertionError(f"PS {node_id} never registered")


def _wait_job(cl, job_id, timeout_s=90.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        job = cl.elastic_job(job_id)
        if job["status"] != "running":
            return job
        time.sleep(0.2)
    raise AssertionError(f"job {job_id} still running after {timeout_s}s")


def test_kill_target_ps_mid_migration(tmp_path, rng):
    """The migration target dies mid-catchup: the job must land
    terminal, the source must keep serving throughout, and every doc
    must survive. (A racy win — the job finishing before the kill
    lands — is accepted; the cluster must be consistent either way.)"""
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1)
    c.start()
    target = None
    try:
        cl = VearchClient(c.router_addr, master_addr=c.master_addr)
        _mk_space(cl)
        vecs = rng.standard_normal((400, D)).astype(np.float32)
        cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i].tolist()}
                              for i in range(400)])
        pid = cl.get_space("db", "s")["partitions"][0]["id"]
        src = c.ps_nodes[0].node_id
        target = c.add_ps()
        _wait_registered(c.master_addr, target.node_id)

        job = cl.migrate_partition(pid, to_node=target.node_id,
                                   timeout_s=6.0)
        target.stop(flush=False)
        c.ps_nodes.remove(target)

        # the source serves searches while the job is dying
        out = cl.search("db", "s", [{"field": "v", "feature": vecs[0]}],
                        limit=3)
        assert out[0]

        done = _wait_job(cl, job["job_id"])
        assert done["status"] in ("done", "error")
        part = next(p for p in cl.get_space("db", "s")["partitions"]
                    if p["id"] == pid)
        if done["status"] == "error":
            # membership untouched: the learner never joined the quorum
            assert part["replicas"] == [src]
            assert part["leader"] == src
        else:  # won the race before the kill: replica moved wholesale
            assert part["replicas"] == [target.node_id]
        # zero data loss either way (an errored job must leave the
        # source's copy fully intact and routed)
        docs = cl.query("db", "s", limit=500, fields=[])
        assert len(docs) == 400
        out = cl.search("db", "s", [{"field": "v", "feature": vecs[7]}],
                        limit=3)
        assert out[0]
    finally:
        c.stop()


def test_crash_ps_hosting_children_mid_split(tmp_path, rng):
    """The PS hosting the split children crashes during the copy: the
    job errors, the children are garbage-collected, the parent is
    intact and still routed — and a retry (against the surviving PS)
    succeeds."""
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1)
    c.start()
    victim = None
    try:
        cl = VearchClient(c.router_addr, master_addr=c.master_addr)
        _mk_space(cl)
        vecs = rng.standard_normal((1500, D)).astype(np.float32)
        for lo in range(0, 1500, 500):
            cl.upsert("db", "s", [
                {"_id": f"d{i}", "v": vecs[i].tolist()}
                for i in range(lo, lo + 500)])
        space0 = cl.get_space("db", "s")
        parent = space0["partitions"][0]["id"]

        # join an empty PS: least-loaded placement puts both children
        # there, making it the perfect crash victim
        victim = c.add_ps()
        _wait_registered(c.master_addr, victim.node_id)

        job = cl.split_partition("db", "s", parent, timeout_s=60.0)
        time.sleep(0.05)  # let the copy start
        victim.stop(flush=False)
        c.ps_nodes.remove(victim)
        victim_id = victim.node_id

        done = _wait_job(cl, job["job_id"])
        assert done["status"] == "error", done

        # parent intact and still the only routed partition
        space1 = cl.get_space("db", "s")
        assert [p["id"] for p in space1["partitions"]] == [parent]
        assert len(cl.query("db", "s", limit=1600, fields=[])) == 1500
        # children GC'd from the metadata: no server claims them
        servers = rpc.call(c.master_addr, "GET", "/servers")["servers"]
        for s in servers:
            for cid in done["detail"].get("children", []):
                assert cid not in s["partition_ids"], (
                    f"child {cid} leaked on node {s['node_id']}")

        # wait for the victim's lease to expire (heartbeat TTL) so the
        # retry places children on the surviving node only
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            servers = rpc.call(c.master_addr, "GET",
                               "/servers")["servers"]
            if not any(s["node_id"] == victim_id for s in servers):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("victim PS lease never expired")

        job = cl.split_partition("db", "s", parent, timeout_s=120.0)
        done = cl.wait_elastic_job(job["job_id"], timeout_s=120.0)
        assert done["status"] == "done"
        space2 = cl.get_space("db", "s")
        kids = [p["id"] for p in space2["partitions"]]
        assert len(kids) == 2 and parent not in kids
        assert len(cl.query("db", "s", limit=1600, fields=[])) == 1500
        out = cl.search("db", "s", [{"field": "v", "feature": vecs[3]}],
                        limit=3)
        assert out[0]
    finally:
        c.stop()

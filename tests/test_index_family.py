"""Per-index-type behavior tests for the long-tail index family
(reference: test/test_vector_index_{hnsw,ivfrabitq}.py,
test_vector_index_binary_ivf coverage)."""

import numpy as np
import pytest

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType,
    FieldSchema,
    IndexParams,
    MetricType,
    TableSchema,
)
from vearch_tpu.index.registry import registered_types


def test_registry_has_full_family():
    types = registered_types()
    for t in ("FLAT", "IVFFLAT", "IVFPQ", "HNSW", "BINARYIVF", "IVFRABITQ"):
        assert t in types, types


def _mk_engine(index_type, d=32, metric=MetricType.L2, params=None):
    schema = TableSchema(
        name="fam",
        fields=[FieldSchema("emb", DataType.VECTOR, dimension=d,
                            index=IndexParams(index_type, metric, params or {}))],
    )
    return Engine(schema)


def test_hnsw_search_and_ef_knob(rng):
    eng = _mk_engine("HNSW", params={"efSearch": 32})
    vecs = rng.standard_normal((500, 32)).astype(np.float32)
    eng.upsert([{"_id": f"d{i}", "emb": vecs[i]} for i in range(500)])
    res = eng.search(SearchRequest(vectors={"emb": vecs[:5]}, k=3))
    assert [r.items[0].key for r in res] == [f"d{i}" for i in range(5)]
    # scores are exact after rerank
    assert res[0].items[0].score == pytest.approx(0.0, abs=1e-3)
    # per-request efSearch override works
    res = eng.search(SearchRequest(vectors={"emb": vecs[:1]}, k=3,
                                   index_params={"efSearch": 500}))
    assert res[0].items[0].key == "d0"


def test_hnsw_cosine(rng):
    eng = _mk_engine("HNSW", metric=MetricType.COSINE)
    vecs = rng.standard_normal((300, 32)).astype(np.float32)
    eng.upsert([{"_id": f"d{i}", "emb": vecs[i]} for i in range(300)])
    res = eng.search(SearchRequest(vectors={"emb": vecs[7] * 3.0}, k=1))
    assert res[0].items[0].key == "d7"  # scale-invariant
    assert res[0].items[0].score == pytest.approx(1.0, abs=1e-2)


def test_hnsw_delete_and_update(rng):
    eng = _mk_engine("HNSW")
    vecs = rng.standard_normal((100, 32)).astype(np.float32)
    eng.upsert([{"_id": f"d{i}", "emb": vecs[i]} for i in range(100)])
    eng.delete(["d5"])
    res = eng.search(SearchRequest(vectors={"emb": vecs[5]}, k=5))
    assert all(it.key != "d5" for it in res[0].items)


def test_binaryivf_hamming(rng):
    d = 64  # bits
    eng = _mk_engine("BINARYIVF", d=d,
                     params={"ncentroids": 8, "nprobe": 8,
                             "training_threshold": 100})
    bits = rng.integers(0, 2, (400, d)).astype(np.uint8)
    packed = np.packbits(bits, axis=1)  # [400, 8] bytes
    eng.upsert([{"_id": f"d{i}", "emb": packed[i]} for i in range(400)])
    eng.wait_for_index()
    eng.build_index()
    res = eng.search(SearchRequest(vectors={"emb": packed[3]}, k=3))
    assert res[0].items[0].key == "d3"
    assert res[0].items[0].score == 0.0  # exact self-match: hamming 0
    # reported score == true hamming distance for other hits
    for it in res[0].items[1:]:
        i = int(it.key[1:])
        assert it.score == float((bits[3] != bits[i]).sum())


def test_binaryivf_wire_dim_validation():
    from vearch_tpu.engine.types import FieldSchema, IndexParams

    f = FieldSchema("emb", DataType.VECTOR, dimension=64,
                    index=IndexParams("BINARYIVF"))
    assert f.wire_dim == 8
    f2 = FieldSchema("emb", DataType.VECTOR, dimension=64,
                     index=IndexParams("FLAT"))
    assert f2.wire_dim == 64


def test_ivfrabitq_recall_with_rerank(rng):
    centers = rng.standard_normal((40, 32)).astype(np.float32) * 4
    which = rng.integers(0, 40, 4000)
    vecs = centers[which] + 0.5 * rng.standard_normal((4000, 32)).astype(np.float32)
    eng = _mk_engine("IVFRABITQ",
                     params={"ncentroids": 32, "training_threshold": 500})
    eng.upsert([{"_id": f"d{i}", "emb": vecs[i]} for i in range(4000)])
    eng.wait_for_index()
    eng.build_index()
    queries = vecs[rng.choice(4000, 30, replace=False)]
    ref = np.argsort(((queries[:, None] - vecs[None]) ** 2).sum(-1), axis=1)[:, :10]
    res = eng.search(SearchRequest(vectors={"emb": queries}, k=10))
    hits = sum(
        len({int(it.key[1:]) for it in r.items} & set(ref[qi].tolist()))
        for qi, r in enumerate(res)
    )
    assert hits / (30 * 10) >= 0.8  # 1-bit quant + exact rerank


def test_ivfrabitq_three_stage_recall_matches_int8_only(rng):
    """Acceptance gate: end-to-end recall@10 after the exact rerank is
    within 0.01 of the int8-only chain (stage0=off) at the same r1 —
    the 1-bit stage-0 filter buys its 8x density without giving up
    result quality."""
    centers = rng.standard_normal((40, 32)).astype(np.float32) * 4
    which = rng.integers(0, 40, 4000)
    vecs = centers[which] + 0.5 * rng.standard_normal(
        (4000, 32)).astype(np.float32)
    eng = _mk_engine("IVFRABITQ",
                     params={"ncentroids": 32, "training_threshold": 500})
    eng.upsert([{"_id": f"d{i}", "emb": vecs[i]} for i in range(4000)])
    eng.wait_for_index()
    eng.build_index()
    queries = vecs[rng.choice(4000, 30, replace=False)]
    ref = np.argsort(
        ((queries[:, None] - vecs[None]) ** 2).sum(-1), axis=1)[:, :10]

    def recall(sp):
        res = eng.search(SearchRequest(vectors={"emb": queries}, k=10,
                                       index_params=sp))
        hits = sum(
            len({int(it.key[1:]) for it in r.items}
                & set(ref[qi].tolist()))
            for qi, r in enumerate(res)
        )
        return hits / (30 * 10)

    r1 = 256
    three = recall({"r0": 1024, "r1": r1})
    int8_only = recall({"stage0": "off", "rerank": r1})
    assert three >= int8_only - 0.01, (three, int8_only)


def test_ivfrabitq_dump_load(rng, tmp_path):
    vecs = np.random.default_rng(0).standard_normal((1200, 32)).astype(np.float32)
    eng = _mk_engine("IVFRABITQ", params={"ncentroids": 16,
                                          "training_threshold": 500})
    eng.upsert([{"_id": f"d{i}", "emb": vecs[i]} for i in range(1200)])
    eng.wait_for_index()
    eng.build_index()
    eng.dump(str(tmp_path / "rq"))
    eng2 = Engine.open(str(tmp_path / "rq"))
    res = eng2.search(SearchRequest(vectors={"emb": vecs[42:43]}, k=1))
    assert res[0].items[0].key == "d42"

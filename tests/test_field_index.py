"""Online scalar field-index add/remove over a live cluster (reference:
AddFieldIndexWithParams / RemoveFieldIndex, c_api/gamma_api.h:166,181;
master flow via gammacb/gamma.go:538,591). The filter path must switch
from columnar scan to index — and back — without downtime: filtered
queries keep returning correct results throughout."""

import time

import numpy as np
import pytest

from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient

D = 8


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    with StandaloneCluster(
        data_dir=str(tmp_path_factory.mktemp("fidx")), n_ps=2
    ) as c:
        yield c


@pytest.fixture(scope="module")
def client(cluster):
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "sp",
        "partition_num": 2,
        "replica_num": 1,
        "fields": [
            {"name": "color", "data_type": "string"},
            {"name": "price", "data_type": "float"},
            {"name": "emb", "data_type": "vector", "dimension": D,
             "index": {"index_type": "FLAT", "metric_type": "L2",
                       "params": {}}},
        ],
    })
    rng = np.random.default_rng(7)
    vecs = rng.standard_normal((200, D)).astype(np.float32)
    cl.upsert("db", "sp", [
        {"_id": f"d{i}", "color": ["red", "green", "blue"][i % 3],
         "price": float(i % 50), "emb": vecs[i]}
        for i in range(200)
    ])
    return cl


def _red_count(cl):
    docs = cl.query(
        "db", "sp",
        filters={"operator": "AND", "conditions": [
            {"operator": "=", "field": "color", "value": "red"}]},
        limit=500,
    )
    return len(docs)


def _indexed_engines(cluster, field):
    """Engines of db/sp partitions whose live scalar manager has `field`."""
    out = []
    for ps in cluster.ps_nodes:
        for eng in ps.engines.values():
            mgr = eng._scalar_manager
            if mgr is not None and mgr.has_index(field):
                out.append(eng)
    return out


def test_add_field_index_switches_scan_to_index(cluster, client):
    n_red = _red_count(client)
    assert n_red == 67  # ceil(200/3): scan baseline is correct

    # no engine has a color index yet
    assert _indexed_engines(cluster, "color") == []

    out = client.add_field_index("db", "sp", "color", "BITMAP")
    assert out["index_type"] == "BITMAP"
    assert len(out["acked"]) == 2 and out["failed"] == []

    # schema change persisted at the master
    sp = client.get_space("db", "sp")
    color = next(f for f in sp["schema"]["fields"] if f["name"] == "color")
    assert color["scalar_index"] == "BITMAP"

    # background build publishes on every replica; queries stay correct
    # the whole time (scan until publish, index after)
    deadline = time.time() + 10
    while time.time() < deadline:
        assert _red_count(client) == n_red
        if len(_indexed_engines(cluster, "color")) == 2:
            break
        time.sleep(0.05)
    assert len(_indexed_engines(cluster, "color")) == 2
    assert _red_count(client) == n_red  # now served by the index

    # rows ingested AFTER the build flow into the published index
    client.upsert("db", "sp", [
        {"_id": "extra1", "color": "red", "price": 1.0,
         "emb": np.zeros(D, dtype=np.float32)},
    ])
    assert _red_count(client) == n_red + 1


def test_remove_field_index_falls_back_to_scan(cluster, client):
    n_red = _red_count(client)
    client.remove_field_index("db", "sp", "color")
    assert _indexed_engines(cluster, "color") == []
    sp = client.get_space("db", "sp")
    color = next(f for f in sp["schema"]["fields"] if f["name"] == "color")
    assert color["scalar_index"] == "NONE"
    assert _red_count(client) == n_red  # scan fallback, zero downtime


def test_field_index_validation(client):
    from vearch_tpu.cluster.rpc import RpcError

    with pytest.raises(RpcError):  # vector fields cannot take scalar indexes
        client.add_field_index("db", "sp", "emb")
    with pytest.raises(RpcError):  # unknown field
        client.add_field_index("db", "sp", "nope")
    with pytest.raises(RpcError):  # unknown index type
        client.add_field_index("db", "sp", "price", "BTREE")


def test_heartbeat_reconciles_missed_fanout(tmp_path):
    """A replica that missed the /field_index fan-out (transient RPC
    failure / restart with a stale local schema) must converge: the
    master's expectations ride every heartbeat response and the PS
    reconciles its engines against them."""
    from vearch_tpu.cluster.master import MasterServer
    from vearch_tpu.cluster.ps import PSServer
    from vearch_tpu.cluster.router import RouterServer
    from vearch_tpu.engine.types import ScalarIndexType

    master = MasterServer()
    master.start()
    ps = PSServer(data_dir=str(tmp_path / "ps"),
                  master_addr=master.addr, heartbeat_interval=0.3)
    ps.start()
    router = RouterServer(master_addr=master.addr)
    router.start()
    try:
        cl = VearchClient(router.addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "sp", "partition_num": 1, "replica_num": 1,
            "fields": [
                {"name": "color", "data_type": "string"},
                {"name": "emb", "data_type": "vector", "dimension": D,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}},
            ],
        })
        cl.upsert("db", "sp", [
            {"_id": f"d{i}", "color": "red",
             "emb": np.zeros(D, dtype=np.float32)} for i in range(10)
        ])
        cl.add_field_index("db", "sp", "color", "BITMAP",
                           background=False)
        eng = next(iter(ps.engines.values()))
        assert eng._scalar_manager.has_index("color")

        # simulate the missed-fan-out state: engine has neither the
        # index nor the schema flag, while the master's record says
        # BITMAP
        eng.remove_field_index("color")
        assert not eng._scalar_manager.has_index("color")
        assert eng.schema.field("color").scalar_index \
            is ScalarIndexType.NONE

        deadline = time.time() + 8
        while time.time() < deadline:
            if (eng._scalar_manager.has_index("color")
                    and eng.schema.field("color").scalar_index
                    is ScalarIndexType.BITMAP):
                break
            time.sleep(0.1)
        assert eng._scalar_manager.has_index("color"), \
            "heartbeat reconcile did not rebuild the missed index"

        # the reverse direction: master says NONE, engine still has it
        cl.remove_field_index("db", "sp", "color")
        deadline = time.time() + 8
        while time.time() < deadline:
            if not eng._scalar_manager.has_index("color"):
                break
            time.sleep(0.1)
        assert not eng._scalar_manager.has_index("color")
    finally:
        router.stop()
        ps.stop()
        master.stop()


def test_remove_cancels_inflight_build():
    """A remove during a long background build must win: the stale build
    must not publish after the drop (reviewer-found publish race)."""
    import threading

    from vearch_tpu.engine.engine import Engine
    from vearch_tpu.engine.types import (
        DataType, FieldSchema, IndexParams, MetricType, ScalarIndexType,
        TableSchema,
    )

    schema = TableSchema("t", [
        FieldSchema("color", DataType.STRING),
        FieldSchema("v", DataType.VECTOR, dimension=4,
                    index=IndexParams("FLAT", MetricType.L2, {})),
    ])
    eng = Engine(schema)
    eng.upsert([{"_id": f"d{i}", "color": "red", "v": [0.0] * 4}
                for i in range(50)])

    # stall the build inside its lock-free bulk phase so the remove can
    # interleave before publish
    gate = threading.Event()
    orig_column = eng.table.column

    def slow_column(name):
        if name == "color":
            gate.wait(5)
        return orig_column(name)

    eng.table.column = slow_column
    eng.add_field_index("color", "BITMAP")  # background
    assert "color" in eng._field_builds
    eng.remove_field_index("color")  # cancels the marker
    gate.set()
    # the build thread finishes; its publish must have been refused
    deadline = time.time() + 5
    while time.time() < deadline and "color" in eng._field_builds:
        time.sleep(0.05)
    assert eng._scalar_manager is None or \
        not eng._scalar_manager.has_index("color")
    assert eng.schema.field("color").scalar_index is ScalarIndexType.NONE
    eng.table.column = orig_column

    # sync join of a failed build must raise, not report success
    def boom(name):
        raise RuntimeError("column store exploded")

    eng.table.column = boom
    eng.table.string_column = boom
    with pytest.raises(RuntimeError):
        eng.add_field_index("color", "INVERTED", background=False)


def test_enable_id_cache_round_trips(client):
    """`enable_id_cache` (reference: entity/space.go:88-94) round-trips
    the space API; the engine's key->docid map is in-process so the
    cache is structurally always-on — the flag is wire compat."""
    client.create_space("db", {
        "name": "idc", "partition_num": 1, "replica_num": 1,
        "enable_id_cache": False,
        "fields": [
            {"name": "emb", "data_type": "vector", "dimension": D,
             "index": {"index_type": "FLAT", "metric_type": "L2",
                       "params": {}}},
        ],
    })
    sp = client.get_space("db", "idc")
    assert sp["enable_id_cache"] is False
    sp2 = client.get_space("db", "sp")  # default true, omitted from dict
    assert sp2.get("enable_id_cache", True) is True


def test_numeric_inverted_index_supports_range(cluster, client):
    client.add_field_index("db", "sp", "price", "INVERTED",
                           background=False)
    assert len(_indexed_engines(cluster, "price")) == 2
    docs = client.query(
        "db", "sp",
        filters={"operator": "AND", "conditions": [
            {"operator": ">=", "field": "price", "value": 45.0}]},
        limit=500,
    )
    # prices cycle 0..49 over 200 docs: 4 full cycles x 5 values >= 45
    assert len(docs) == 20


def test_online_field_index_survives_dump_load(tmp_path):
    """An index added ONLINE must come back after dump + reopen: the
    published flag rides schema.json and the rebuild is presence-gated."""
    from vearch_tpu.engine.engine import Engine
    from vearch_tpu.engine.types import (
        DataType, FieldSchema, IndexParams, MetricType, ScalarIndexType,
        TableSchema,
    )

    schema = TableSchema("t", [
        FieldSchema("color", DataType.STRING),
        FieldSchema("v", DataType.VECTOR, dimension=4,
                    index=IndexParams("FLAT", MetricType.L2, {})),
    ])
    eng = Engine(schema, data_dir=str(tmp_path / "d"))
    eng.upsert([
        {"_id": f"a{i}", "color": "red", "v": [float(i)] * 4}
        for i in range(20)
    ] + [{"_id": "noc", "v": [9.0] * 4}])  # color never set
    eng.add_field_index("color", "BITMAP", background=False)
    eng.dump(str(tmp_path / "d"))

    eng2 = Engine.open(str(tmp_path / "d"))
    assert eng2.schema.field("color").scalar_index \
        is ScalarIndexType.BITMAP
    assert eng2._scalar_manager is not None \
        and eng2._scalar_manager.has_index("color")
    docs = eng2.query({"operator": "AND", "conditions": [
        {"operator": "=", "field": "color", "value": "red"}]}, limit=50)
    assert len(docs) == 20  # the presence-gated row 'noc' is excluded


def test_online_index_rides_snapshot_catchup(tmp_path):
    """A follower caught up via the chunked engine-snapshot stream must
    come back with the online-added scalar index live (the snapshot is
    the leader's dump: schema flag + presence-gated rebuild), and serve
    correct filtered reads."""
    import vearch_tpu.cluster.ps as ps_mod
    from vearch_tpu.cluster import rpc as rpc_mod
    from vearch_tpu.cluster.master import MasterServer
    from vearch_tpu.cluster.ps import PSServer
    from vearch_tpu.cluster.router import RouterServer

    master = MasterServer(heartbeat_ttl=3600.0)
    master.start()
    nodes = [PSServer(data_dir=str(tmp_path / f"ps{i}"),
                      master_addr=master.addr, heartbeat_interval=0.3,
                      flush_interval=3600.0, raft_tick=0.3)
             for i in range(2)]
    for n in nodes:
        n.start()
    router = RouterServer(master_addr=master.addr)
    router.start()
    old_keep = ps_mod.WAL_KEEP_ENTRIES
    ps_mod.WAL_KEEP_ENTRIES = 5
    try:
        cl = VearchClient(router.addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1, "replica_num": 2,
            "fields": [
                {"name": "color", "data_type": "string"},
                {"name": "emb", "data_type": "vector", "dimension": D,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}},
            ],
        })
        sp = cl.get_space("db", "s")["partitions"][0]
        pid, leader_id = sp["id"], sp["leader"]
        leader_ps = next(p for p in nodes if p.node_id == leader_id)
        follower = next(p for p in nodes if p.node_id != leader_id)
        rng = np.random.default_rng(6)
        vecs = rng.standard_normal((60, D)).astype(np.float32)
        cl.upsert("db", "s", [
            {"_id": f"d{i}", "color": ["red", "blue"][i % 2],
             "emb": vecs[i]} for i in range(20)
        ])
        cl.add_field_index("db", "s", "color", "BITMAP",
                           background=False)

        fdir = follower.data_dir
        fid = follower.node_id
        follower.stop(flush=False)
        rpc_mod.call(master.addr, "POST", "/partitions/change_member",
                     {"partition_id": pid, "node_id": fid,
                      "method": "remove"})
        for i in range(20, 60):
            cl.upsert("db", "s", [
                {"_id": f"d{i}", "color": ["red", "blue"][i % 2],
                 "emb": vecs[i]}])
        leader_ps.flush_partition(pid)

        f2 = PSServer(data_dir=fdir, master_addr=master.addr,
                      heartbeat_interval=0.3, raft_tick=0.3)
        f2.start()
        nodes.append(f2)
        rpc_mod.call(master.addr, "POST", "/partitions/change_member",
                     {"partition_id": pid, "node_id": fid,
                      "method": "add"})
        deadline = time.time() + 30
        while time.time() < deadline:
            if (pid in f2.engines
                    and f2.engines[pid].doc_count == 60):
                break
            time.sleep(0.3)
        eng = f2.engines[pid]
        assert eng.doc_count == 60, "snapshot catch-up did not converge"
        assert eng._scalar_manager is not None \
            and eng._scalar_manager.has_index("color"), \
            "online index lost across snapshot install"
        # filtered read served BY THE FOLLOWER is correct
        docs = eng.query({"operator": "AND", "conditions": [
            {"operator": "=", "field": "color", "value": "red"}]},
            limit=200)
        assert len(docs) == 30
    finally:
        ps_mod.WAL_KEEP_ENTRIES = old_keep
        router.stop()
        for n in nodes:
            try:
                n.stop(flush=False)
            except Exception:
                pass
        master.stop()

"""Cluster CI profile: runs cloud/smoke.py — the full 3-master/2-router/
3-PS + S3 topology as real subprocesses, with leader and PS kill -9
failure injection (reference: CI_cluster.yml:33-51 runs its suite
against the compose fabric)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_cluster_smoke_profile():
    # the profile spawns an 8-process cluster; on a box already loaded
    # by the rest of the suite, election/lease timing can flake — one
    # retry keeps the gate meaningful without being load-sensitive
    last = "timed out"
    for attempt in range(2):
        try:
            out = subprocess.run(
                [sys.executable, os.path.join(REPO, "cloud", "smoke.py")],
                capture_output=True, text=True, timeout=600,
                env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
            )
        except subprocess.TimeoutExpired:
            continue  # a stalled attempt is the flake class retried here
        if out.returncode == 0 and "CLUSTER SMOKE: ALL GREEN" in out.stdout:
            return
        last = f"{out.stdout}\n{out.stderr}"
    raise AssertionError(f"smoke failed:\n{last}")

"""Cluster CI profile: runs cloud/smoke.py — the full 3-master/2-router/
3-PS + S3 topology as real subprocesses, with leader and PS kill -9
failure injection (reference: CI_cluster.yml:33-51 runs its suite
against the compose fabric)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_cluster_smoke_profile():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "cloud", "smoke.py")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
    )
    assert out.returncode == 0, f"smoke failed:\n{out.stdout}\n{out.stderr}"
    assert "CLUSTER SMOKE: ALL GREEN" in out.stdout

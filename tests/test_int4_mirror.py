"""int4 mirror tier (TPU-native capacity knob: half the resident HBM
per row of the int8 full-scan mirror; no reference analogue — the
reference's capacity tier is DiskANN)."""

import jax.numpy as jnp
import numpy as np
import pytest

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType, FieldSchema, IndexParams, MetricType, TableSchema,
)
from vearch_tpu.index.int8_mirror import Int8Mirror, quantize_rows_int4
from vearch_tpu.ops.ivf import int4_scan_candidates, unpack_int4


def test_int4_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    rows = rng.standard_normal((64, 20)).astype(np.float32)
    packed, scale, vsq = quantize_rows_int4(rows)
    assert packed.shape == (64, 10) and packed.dtype == np.uint8
    un = np.asarray(unpack_int4(jnp.asarray(packed)), dtype=np.float32)
    deq = un * scale[:, None]
    # quantization error bounded by scale/2 per dim
    assert np.max(np.abs(deq - rows)) <= np.max(scale) / 2 + 1e-6
    np.testing.assert_allclose(vsq, (deq ** 2).sum(1), rtol=1e-3)


def test_int4_mirror_halves_bytes():
    m8 = Int8Mirror(64)
    m4 = Int8Mirror(64, storage="int4")
    rows = np.random.default_rng(1).standard_normal((1024, 64)).astype(
        np.float32
    )
    m8.append(rows)
    m4.append(rows)
    assert m4._h8.nbytes * 2 == m8._h8.nbytes


def test_int4_scan_self_match():
    rng = np.random.default_rng(2)
    n, d = 2048, 32
    base = rng.standard_normal((n, d)).astype(np.float32) * 3
    packed, scale, vsq = quantize_rows_int4(base)
    q = base[rng.choice(n, 8, replace=False)]
    valid = np.ones(n, bool)
    s, ids = int4_scan_candidates(
        jnp.asarray(q), jnp.asarray(packed), jnp.asarray(scale),
        jnp.asarray(vsq), jnp.asarray(valid), 10, MetricType.L2,
    )
    ids = np.asarray(ids)
    exact = np.argmin(
        ((q[:, None].astype(np.float64)
          - base[None].astype(np.float64)) ** 2).sum(-1), axis=1
    )
    assert (ids[:, 0] == exact).mean() >= 0.8, (ids[:, 0], exact)


def test_ivfpq_int4_mirror_recall():
    rng = np.random.default_rng(3)
    n, d = 20_000, 32
    centers = (rng.standard_normal((150, d)) * 3).astype(np.float32)
    base = centers[rng.integers(0, 150, n)] + \
        0.6 * rng.standard_normal((n, d)).astype(np.float32)
    schema = TableSchema("i4", [
        FieldSchema("v", DataType.VECTOR, dimension=d,
                    index=IndexParams("IVFPQ", MetricType.L2, {
                        "ncentroids": 128, "nsubvector": 8,
                        "train_iters": 5, "training_threshold": n,
                        "mirror_dtype": "int4",
                    })),
    ])
    eng = Engine(schema)
    for i in range(0, n, 10_000):
        eng.upsert([{"_id": str(j), "v": base[j]}
                    for j in range(i, i + 10_000)])
    eng.build_index()
    assert eng.indexes["v"]._mirror.storage == "int4"
    q = base[:48] + 0.05 * rng.standard_normal((48, d)).astype(np.float32)
    exact = np.argsort(
        ((q[:, None].astype(np.float64)
          - base[None].astype(np.float64)) ** 2).sum(-1), axis=1
    )[:, :10]
    res = eng.search(SearchRequest(vectors={"v": q}, k=10,
                                   include_fields=[],
                                   index_params={"rerank": 256}))
    got = [[int(it.key) for it in r.items] for r in res]
    r10 = float(np.mean([
        len(set(got[i]) & set(exact[i].tolist())) / 10 for i in range(48)
    ]))
    assert r10 >= 0.8, r10
    eng.close()


def test_int4_odd_dimension_rejected():
    with pytest.raises(ValueError, match="even dimension"):
        Int8Mirror(33, storage="int4")

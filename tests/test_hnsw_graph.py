"""Native HNSW graph (csrc/vearch_hnsw.cpp) + graph-mode HNSWIndex.

Covers the reference's hnswlib capability (index/impl/hnswlib/
gamma_index_hnswlib.cc) through the independent native implementation:
recall vs exact, filtered search, incremental add, save/load, metric
variants, and the index-type integration (graph mode on disk stores,
dump/load via the engine)."""

import numpy as np
import pytest

from vearch_tpu.native import hnsw_graph

pytestmark = pytest.mark.skipif(
    not hnsw_graph.available(), reason="no native toolchain"
)


def _data(n=8000, d=32, seed=0):
    rng = np.random.default_rng(seed)
    centers = (rng.standard_normal((16, d)) * 3).astype(np.float32)
    base = centers[rng.integers(0, 16, n)] + 0.5 * rng.standard_normal(
        (n, d)
    ).astype(np.float32)
    queries = base[rng.choice(n, 32, replace=False)] + 0.1 * (
        rng.standard_normal((32, d)).astype(np.float32)
    )
    return base.astype(np.float32), queries.astype(np.float32)


def _gt_l2(base, queries, k=10):
    d2 = (
        (queries**2).sum(1)[:, None]
        - 2 * queries @ base.T
        + (base**2).sum(1)[None, :]
    )
    return np.argsort(d2, axis=1)[:, :k]


def _recall(ids, gt):
    return sum(
        len(set(ids[i].tolist()) & set(gt[i].tolist()))
        for i in range(gt.shape[0])
    ) / gt.size


class TestGraph:
    def test_recall_l2(self):
        base, queries = _data()
        g = hnsw_graph.HnswGraph(base.shape[1], m=16, ef_construction=200)
        g.add(base)
        assert g.count == base.shape[0]
        gt = _gt_l2(base, queries)
        scores, ids = g.search(queries, 10, ef=128)
        assert _recall(ids, gt) >= 0.9

    def test_scores_are_exact_l2(self):
        base, queries = _data(n=2000)
        g = hnsw_graph.HnswGraph(base.shape[1])
        g.add(base)
        scores, ids = g.search(queries[:4], 5, ef=64)
        for qi in range(4):
            for j in range(5):
                if ids[qi, j] < 0:
                    continue
                d2 = float(((queries[qi] - base[ids[qi, j]]) ** 2).sum())
                assert scores[qi, j] == pytest.approx(-d2, rel=1e-4)

    def test_filtered_search(self):
        base, queries = _data(n=3000)
        g = hnsw_graph.HnswGraph(base.shape[1])
        g.add(base)
        gt = _gt_l2(base, queries, k=1)
        valid = np.ones(base.shape[0], bool)
        valid[gt[:, 0]] = False
        _, ids = g.search(queries, 10, ef=128, valid_mask=valid)
        assert not (set(np.ravel(ids).tolist()) & set(gt[:, 0].tolist()))

    def test_dense_deletes_still_fill_k(self):
        # the index contract: masked search must yield k valid results
        # even when most docs are filtered and ef is small
        base, queries = _data(n=2000)
        g = hnsw_graph.HnswGraph(base.shape[1])
        g.add(base)
        valid = np.zeros(base.shape[0], bool)
        valid[::4] = True  # 75% deleted
        _, ids = g.search(queries, 10, ef=16, valid_mask=valid)
        assert (ids >= 0).all(), "under-filled results under dense deletes"
        assert (ids % 4 == 0).all()

    def test_corrupt_file_rejected(self, tmp_path):
        base, _ = _data(n=500)
        g = hnsw_graph.HnswGraph(base.shape[1])
        g.add(base)
        path = str(tmp_path / "g.hnsw")
        g.save(path)
        blob = bytearray(open(path, "rb").read())
        blob[40] ^= 0xFF  # flip a byte inside the header/levels region
        open(str(tmp_path / "bad.hnsw"), "wb").write(bytes(blob))
        with pytest.raises(ValueError):
            hnsw_graph.HnswGraph.load(
                str(tmp_path / "bad.hnsw"), base.shape[1]
            )

    def test_incremental_add_matches_bulk(self):
        base, queries = _data(n=4000)
        g = hnsw_graph.HnswGraph(base.shape[1])
        for lo in range(0, 4000, 500):
            first = g.add(base[lo : lo + 500])
            assert first == lo  # ids stay sequential == docids
        gt = _gt_l2(base, queries)
        _, ids = g.search(queries, 10, ef=128)
        assert _recall(ids, gt) >= 0.9

    def test_save_load_roundtrip(self, tmp_path):
        base, queries = _data(n=2000)
        g = hnsw_graph.HnswGraph(base.shape[1], m=12)
        g.add(base)
        path = str(tmp_path / "g.hnsw")
        g.save(path)
        g2 = hnsw_graph.HnswGraph.load(path, base.shape[1], m=12)
        assert g2.count == 2000
        s1, i1 = g.search(queries, 10, ef=64)
        s2, i2 = g2.search(queries, 10, ef=64)
        np.testing.assert_array_equal(i1, i2)

    def test_inner_product_metric(self):
        base, queries = _data(n=2000)
        bn = base / np.linalg.norm(base, axis=1, keepdims=True)
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        g = hnsw_graph.HnswGraph(base.shape[1], ip=True)
        g.add(bn)
        gt = np.argsort(-(qn @ bn.T), axis=1)[:, :10]
        _, ids = g.search(qn, 10, ef=128)
        assert _recall(ids, gt) >= 0.9

    def test_empty_and_tiny(self):
        g = hnsw_graph.HnswGraph(8)
        s, i = g.search(np.zeros((2, 8), np.float32), 3, ef=16)
        assert (i == -1).all()
        g.add(np.ones((1, 8), np.float32))
        s, i = g.search(np.ones((1, 8), np.float32), 3, ef=16)
        assert i[0, 0] == 0 and (i[0, 1:] == -1).all()


class TestHnswIndexGraphMode:
    def _index(self, base, tmp_path, **extra):
        from vearch_tpu.engine.disk_vector import DiskRawVectorStore
        from vearch_tpu.engine.types import IndexParams
        from vearch_tpu.index.registry import create_index

        store = DiskRawVectorStore(base.shape[1], str(tmp_path / "s"))
        store.add(base)
        idx = create_index(
            IndexParams("HNSW", params={"efSearch": 128, **extra}), store
        )
        idx.absorb(store.count)
        return store, idx

    def test_auto_graph_on_disk_store(self, tmp_path):
        base, queries = _data(n=3000)
        _, idx = self._index(base, tmp_path)
        assert idx.use_graph and idx._graph is not None
        gt = _gt_l2(base, queries)
        _, ids = idx.search(queries, 10, None)
        assert _recall(ids, gt) >= 0.9

    def test_dump_load_state(self, tmp_path):
        base, queries = _data(n=2000)
        store, idx = self._index(base, tmp_path)
        state = idx.dump_state()
        assert "graph_blob" in state
        store.flush_disk()  # reopen below must see the durable rows

        from vearch_tpu.engine.disk_vector import DiskRawVectorStore
        from vearch_tpu.engine.types import IndexParams
        from vearch_tpu.index.registry import create_index

        store2 = DiskRawVectorStore(base.shape[1], str(tmp_path / "s"))
        idx2 = create_index(
            IndexParams("HNSW", params={"efSearch": 128}), store2
        )
        idx2.load_state(state)
        assert idx2._graph.count == 2000
        gt = _gt_l2(base, queries)
        _, ids = idx2.search(queries, 10, None)
        assert _recall(ids, gt) >= 0.9

    def test_forced_graph_on_memory_store(self, tmp_path):
        from vearch_tpu.engine.raw_vector import RawVectorStore
        from vearch_tpu.engine.types import IndexParams
        from vearch_tpu.index.registry import create_index

        base, queries = _data(n=2000)
        store = RawVectorStore(base.shape[1])
        store.add(base)
        idx = create_index(
            IndexParams("HNSW", params={"graph": True, "efSearch": 128}),
            store,
        )
        idx.absorb(store.count)
        assert idx.use_graph
        gt = _gt_l2(base, queries)
        _, ids = idx.search(queries, 10, None)
        assert _recall(ids, gt) >= 0.9

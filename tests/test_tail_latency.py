"""Tail-latency chaos gates: hedged scatter, admission shedding, SDK backoff.

Three acceptance properties from the tail-latency PR, each stated as a
chaos experiment against a real in-process cluster:

1. STRAGGLER DOES NOT MOVE THE MERGED TAIL — one replica delayed to
   ~10-40x the median must not drag the router-merged p99 with it:
   the adaptive hedge (delay derived from the router's own streaming
   quantile sketch) fires a second attempt at a different replica and
   the fast answer wins, the slow attempt is cancelled via the kill
   machinery.
2. HEDGE VOLUME STAYS WITHIN BUDGET — the token bucket bounds hedges
   to ~hedge_budget_pct of primary traffic (plus the initial burst
   allowance), so a persistent straggler cannot double cluster load.
3. SATURATED PS SHEDS, HEALTHY PARTITIONS SERVE — a PS at its
   admission bound answers 429 + Retry-After in O(ms) without device
   work while partitions on other nodes keep serving, and the SDK
   honors Retry-After with capped, jittered backoff that NEVER retries
   a terminal kill (499).
"""

from __future__ import annotations

import math
import threading
import time
import urllib.request

import numpy as np
import pytest

from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.master import MasterServer
from vearch_tpu.cluster.ps import PSServer
from vearch_tpu.cluster.router import RouterServer
from vearch_tpu.sdk.client import VearchClient

D = 8


def _pctl(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    i = min(len(ys) - 1, max(0, int(math.ceil(q * len(ys))) - 1))
    return ys[i]


def _scrape(addr: str) -> str:
    with urllib.request.urlopen(f"http://{addr}/metrics",
                                timeout=5.0) as r:
        return r.read().decode()


@pytest.fixture
def three_ps(tmp_path):
    """Master + 3 PS (fast heartbeats so load digests are fresh);
    routers are created per-test because the hedge knobs differ."""
    master = MasterServer(heartbeat_ttl=3.0)
    master.start()
    ps_nodes = []
    for i in range(3):
        ps = PSServer(data_dir=str(tmp_path / f"ps{i}"),
                      master_addr=master.addr, heartbeat_interval=0.3)
        ps.start()
        ps_nodes.append(ps)
    routers: list[RouterServer] = []
    yield master, ps_nodes, routers
    for rt in routers:
        rt.stop()
    for ps in ps_nodes:
        try:
            ps.stop()
        except Exception:
            pass
    master.stop()


@pytest.fixture
def shed_cluster(tmp_path):
    """Master + 2 PS with a ONE-permit search gate: admission counts
    requests *waiting* for a gate permit (in-flight work already holds
    one), so shedding is only observable once the gate is saturated —
    a single permit makes that deterministic."""
    master = MasterServer(heartbeat_ttl=3.0)
    master.start()
    ps_nodes = []
    for i in range(2):
        ps = PSServer(data_dir=str(tmp_path / f"sps{i}"),
                      master_addr=master.addr, heartbeat_interval=0.3,
                      max_concurrent_searches=1)
        ps.start()
        ps_nodes.append(ps)
    router = RouterServer(master_addr=master.addr)
    router.start()
    yield master, ps_nodes, router
    router.stop()
    for ps in ps_nodes:
        try:
            ps.stop()
        except Exception:
            pass
    master.stop()


def _mk_space(cl: VearchClient, rng, replica_num: int = 3,
              name: str = "s") -> np.ndarray:
    cl.create_space("db", {
        "name": name, "partition_num": 1, "replica_num": replica_num,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })
    vecs = rng.standard_normal((60, D)).astype(np.float32)
    cl.upsert("db", name, [{"_id": f"d{i}", "v": vecs[i]}
                           for i in range(60)])
    return vecs


def _timed_search(router_addr: str, rng, space: str = "s") -> float:
    """One router search with a UNIQUE query vector (defeats both the
    router merged-result cache and the PS result cache — every call
    must really scatter). Returns elapsed seconds."""
    q = rng.standard_normal(D).astype(np.float32)
    t0 = time.monotonic()
    out = rpc.call(router_addr, "POST", "/document/search", {
        "db_name": "db", "space_name": space,
        "vectors": [{"field": "v", "feature": q.tolist()}],
        "limit": 3,
    })
    dt = time.monotonic() - t0
    assert out["documents"]
    return dt


def _leader_ps(cl: VearchClient, ps_nodes, space: str = "s"):
    part = cl.get_space("db", space)["partitions"][0]
    pid, leader_id = part["id"], part["leader"]
    ps = next(p for p in ps_nodes if p.node_id == leader_id)
    assert pid in ps.engines
    return ps, pid


def test_straggler_does_not_move_merged_p99(three_ps, rng):
    master, ps_nodes, routers = three_ps
    # budget 100% here: this test gates the LATENCY property in
    # isolation; the budget bound is gated separately below
    router = RouterServer(master_addr=master.addr, hedge_quantile=0.5,
                          hedge_budget_pct=100.0, hedge_min_delay_ms=2.0)
    router.start()
    routers.append(router)
    cl = VearchClient(router.addr)
    cl.create_database("db")
    _mk_space(cl, rng, replica_num=3)

    # warm the (pid, scatter) sketch past hedge_min_samples and take
    # the no-straggler baseline from the same samples
    base = [_timed_search(router.addr, rng) for _ in range(30)]
    p99_base = _pctl(base, 0.99)

    # delay the partition LEADER (the default read target) to >>10x the
    # observed median — without hedging every search would eat this
    ps, pid = _leader_ps(cl, ps_nodes)
    delay_s = 0.4
    assert delay_s > 10 * _pctl(base, 0.5)
    rpc.call(ps.addr, "POST", "/ps/engine/config", {
        "partition_id": pid,
        "config": {"debug_search_delay_ms": int(delay_s * 1e3)},
    })
    try:
        lat = [_timed_search(router.addr, rng) for _ in range(20)]
    finally:
        rpc.call(ps.addr, "POST", "/ps/engine/config", {
            "partition_id": pid, "config": {"debug_search_delay_ms": 0},
        })

    stats = rpc.call(router.addr, "GET", "/router/stats")
    hedges = stats["hedges"]
    # the hedge actually fired and actually won
    assert hedges["fired"] > 0 and hedges["won"] > 0, hedges
    # NO search waited out the injected straggler delay...
    assert max(lat) < delay_s, (
        f"a search ate the full straggler delay: max={max(lat):.3f}s "
        f"vs injected {delay_s}s (hedges={hedges})"
    )
    # ...and the merged p99 stayed within 2x the no-straggler baseline
    # (+100ms absolute slack for CI scheduler noise — still 4x under
    # the injected delay, so the property being gated is unambiguous)
    assert _pctl(lat, 0.99) <= 2.0 * p99_base + 0.1, (
        f"hedged p99 {_pctl(lat, 0.99):.3f}s vs baseline p99 "
        f"{p99_base:.3f}s (hedges={hedges})"
    )
    # decisions are observable: counters exported, not just in stats
    page = _scrape(router.addr)
    assert 'vearch_router_hedges_total{event="won"}' in page


def test_hedge_volume_stays_within_budget(three_ps, rng):
    master, ps_nodes, routers = three_ps
    budget_pct = 10.0
    router = RouterServer(master_addr=master.addr, hedge_quantile=0.5,
                          hedge_budget_pct=budget_pct,
                          hedge_min_delay_ms=2.0)
    router.start()
    routers.append(router)
    cl = VearchClient(router.addr)
    cl.create_database("db")
    _mk_space(cl, rng, replica_num=3)

    warm = 30
    for _ in range(warm):
        _timed_search(router.addr, rng)
    ps, pid = _leader_ps(cl, ps_nodes)
    rpc.call(ps.addr, "POST", "/ps/engine/config", {
        "partition_id": pid, "config": {"debug_search_delay_ms": 300},
    })
    n = 24
    try:
        for _ in range(n):
            _timed_search(router.addr, rng)
    finally:
        rpc.call(ps.addr, "POST", "/ps/engine/config", {
            "partition_id": pid, "config": {"debug_search_delay_ms": 0},
        })
    stats = rpc.call(router.addr, "GET", "/router/stats")
    hedges = stats["hedges"]
    # every straggler-phase search WANTED to hedge; the bucket must
    # have denied the excess: fired <= initial burst (token cap 10)
    # + budget_pct of all primaries, small slack for the last credit
    cap = 10.0
    allowed = cap + (budget_pct / 100.0) * (warm + n) + 1
    assert hedges["fired"] <= allowed, hedges
    assert hedges["budget_denied"] > 0, (
        f"expected the token bucket to deny some hedges: {hedges}"
    )


def test_saturated_ps_sheds_while_healthy_partitions_serve(
        shed_cluster, rng):
    master, ps_nodes, router = shed_cluster
    cl = VearchClient(router.addr)
    cl.create_database("db")
    # two single-replica spaces; balanced placement puts them on
    # different PS nodes so one can saturate while the other serves
    _mk_space(cl, rng, replica_num=1, name="a")
    _mk_space(cl, rng, replica_num=1, name="b")
    ps_a, pid_a = _leader_ps(cl, ps_nodes, "a")
    ps_b, _ = _leader_ps(cl, ps_nodes, "b")
    assert ps_a.node_id != ps_b.node_id, "placement co-located the spaces"

    # saturate ps_a: one search holds the single gate permit (pinned
    # in-flight by the injected delay), one fills the single admission
    # slot waiting for it; the next request must be shed, not queued
    rpc.call(ps_a.addr, "POST", "/ps/engine/config", {
        "partition_id": pid_a,
        "config": {"admission_queue_limit": 1,
                   "debug_search_delay_ms": 3000},
    })
    occupants: list[Exception] = []

    def occupy():
        try:
            _timed_search(router.addr, rng, "a")
        except Exception as e:  # pragma: no cover - surfaced below
            occupants.append(e)

    threads = [threading.Thread(target=occupy) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while ps_a._admission.waiting < 1:
            assert time.monotonic() < deadline, "occupant never queued"
            time.sleep(0.01)

        # shed is FAST (no 1.5s wait), carries Retry-After, and the
        # router passes 429 through instead of retrying it as failover
        t0 = time.monotonic()
        with pytest.raises(rpc.RpcError) as ei:
            _timed_search(router.addr, rng, "a")
        shed_dt = time.monotonic() - t0
        assert ei.value.code == 429
        assert "shedding" in str(ei.value)
        assert ei.value.retry_after and ei.value.retry_after > 0
        assert shed_dt < 1.0, f"shed took {shed_dt:.2f}s — it queued"

        # ...while the healthy node keeps serving its partition
        assert _timed_search(router.addr, rng, "b") < 1.0

        # the SDK honors Retry-After: capped retries, then the 429
        # surfaces (saturation outlasts the retry window)
        sdk = VearchClient(router.addr)
        sdk.max_retries_429 = 2
        shed0 = ps_a._admission.snapshot()["shed_total"]
        q = rng.standard_normal(D).astype(np.float32)
        with pytest.raises(rpc.RpcError) as ei:
            sdk.search("db", "a", [{"field": "v", "feature": q}], limit=3)
        assert ei.value.code == 429
        assert ps_a._admission.snapshot()["shed_total"] == shed0 + 3, (
            "initial attempt + 2 retries must each have been shed"
        )
    finally:
        for t in threads:
            t.join(timeout=10.0)
        rpc.call(ps_a.addr, "POST", "/ps/engine/config", {
            "partition_id": pid_a,
            "config": {"admission_queue_limit": 0,
                       "debug_search_delay_ms": 0},
        })
    assert not occupants, occupants
    # sheds are counted per-op (and per-space) on the PS metrics page
    assert 'vearch_ps_admission_shed_total{op="search",space=' in _scrape(
        ps_a.addr)
    # recovered: the formerly saturated space serves again
    assert _timed_search(router.addr, rng, "a") < 1.0


# -- SDK backoff unit gates (no cluster) -------------------------------------


def test_sdk_backoff_is_capped_and_never_retries_terminal_kill(monkeypatch):
    from vearch_tpu.sdk import client as client_mod

    cl = client_mod.VearchClient("127.0.0.1:1")
    calls: list[str] = []
    sleeps: list[float] = []
    monkeypatch.setattr(time, "sleep", sleeps.append)

    # server demands a 99s backoff: the client must clamp to its cap
    def always_shed(addr, method, path, body=None, **kw):
        calls.append(path)
        raise rpc.RpcError(429, "shedding", retry_after=99.0)

    monkeypatch.setattr(client_mod.rpc, "call", always_shed)
    with pytest.raises(rpc.RpcError) as ei:
        cl._doc_call("POST", "/document/search", {})
    assert ei.value.code == 429
    assert len(calls) == 1 + cl.max_retries_429
    assert sleeps and all(0 < s <= cl.backoff_cap_s for s in sleeps), sleeps

    # a single shed then success: one retry, jittered around retry_after
    calls.clear()
    sleeps.clear()
    state = {"n": 0}

    def shed_once(addr, method, path, body=None, **kw):
        calls.append(path)
        state["n"] += 1
        if state["n"] == 1:
            raise rpc.RpcError(429, "shedding", retry_after=0.2)
        return {"documents": []}

    monkeypatch.setattr(client_mod.rpc, "call", shed_once)
    assert cl._doc_call("POST", "/document/search", {}) == {"documents": []}
    assert len(calls) == 2
    assert len(sleeps) == 1 and 0.2 * 0.5 <= sleeps[0] <= 0.2 * 1.5

    # terminal kill (499) propagates immediately — retrying would
    # re-run the exact work the kill existed to shed
    calls.clear()
    sleeps.clear()

    def killed(addr, method, path, body=None, **kw):
        calls.append(path)
        raise rpc.RpcError(499, "request_killed: operator")

    monkeypatch.setattr(client_mod.rpc, "call", killed)
    with pytest.raises(rpc.RpcError) as ei:
        cl._doc_call("POST", "/document/search", {})
    assert ei.value.code == 499
    assert len(calls) == 1 and not sleeps

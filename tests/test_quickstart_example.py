"""The shipped quickstart example must actually run (observability
satellite: the profiled-search walkthrough is the first thing a new
user executes — a broken example is a broken front door)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "examples", "python", "quickstart.py")


def test_quickstart_runs_and_prints_profile():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, SCRIPT], capture_output=True,
                         text=True, timeout=240, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "top hit: doc-42" in out.stdout
    assert "profile (2 partitions" in out.stdout
    assert "dispatches    ['flat_scan']" in out.stdout
    # write-path explain + job + slowlog walkthroughs (PR 3)
    assert "write profile (" in out.stdout
    assert "wal_append" in out.stdout
    assert "build done in" in out.stdout
    assert "slow-query log (threshold 0.001 ms):" in out.stdout
    assert "(phases [" in out.stdout
    assert "quickstart OK" in out.stdout

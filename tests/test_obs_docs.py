"""Drift gate for docs/OBSERVABILITY.md (observability satellite):
documented metric/span names must exactly match source registrations."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "check_obs_docs.py")


def _load():
    spec = importlib.util.spec_from_file_location("check_obs_docs", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_docs_match_registrations():
    out = subprocess.run([sys.executable, SCRIPT],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "in sync" in out.stdout


def test_gate_catches_missing_doc_entry(tmp_path, monkeypatch):
    """Removing one documented metric makes the gate fail — it is a
    real check, not a tautology."""
    mod = _load()
    text = open(mod.DOC).read()
    assert "`vearch_raft_peer_lag`" in text
    broken = tmp_path / "OBSERVABILITY.md"
    broken.write_text(text.replace("`vearch_raft_peer_lag`", "`gone`"))
    monkeypatch.setattr(mod, "DOC", str(broken))
    assert mod.main() == 1


def test_gate_catches_stale_doc_entry(tmp_path, monkeypatch):
    """A documented metric with no registration behind it also fails."""
    mod = _load()
    text = open(mod.DOC).read()
    stale = tmp_path / "OBSERVABILITY.md"
    stale.write_text(text + "\n`vearch_raft_removed_total`\n")
    monkeypatch.setattr(mod, "DOC", str(stale))
    assert mod.main() == 1


def test_source_extraction_sees_known_names():
    mod = _load()
    metrics, spans, tags = mod.source_names()
    for name in ("vearch_raft_peer_lag", "vearch_raft_commit_latency_seconds",
                 "tracing_dropped_spans_total", "vearch_request_total",
                 "vearch_cluster_servers", "vearch_router_cache_events_total",
                 "vearch_ps_search_cache_events_total"):
        assert name in metrics, name
    for name in ("router.search", "ps.search", "ps.gate_wait",
                 "microbatch.queue", "engine.search.*", "kernel.*",
                 "raft.*"):
        assert name in spans, name
    assert "cache" in tags


def test_gate_catches_undocumented_span_tag(tmp_path, monkeypatch):
    """An undocumented post-creation span tag fails the gate too."""
    mod = _load()
    text = open(mod.DOC).read()
    stripped = tmp_path / "OBSERVABILITY.md"
    stripped.write_text(text.replace("`cache`", "cache"))
    monkeypatch.setattr(mod, "DOC", str(stripped))
    assert mod.main() == 1

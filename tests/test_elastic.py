"""Elastic data-plane acceptance (tier-1): online partition split under
concurrent traffic, and snapshot-streamed replica migration + drain to
a freshly joined PS — the two end-to-end contracts of
docs/ELASTICITY.md, asserted through the public surfaces only (SDK,
router, master REST, /metrics)."""

import threading
import urllib.request

import numpy as np

from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient

D = 8


def _mk_space(cl, partition_num=1, replica_num=1):
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": partition_num,
        "replica_num": replica_num,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })


def _all_ids(cl, expect_at_most):
    docs = cl.query("db", "s", limit=expect_at_most + 50, fields=[])
    return [d["_id"] for d in docs]


def test_online_split_under_concurrent_traffic(tmp_path, rng):
    """Split a partition while writers and searchers hammer it: zero
    lost docs, zero duplicated docs, read-your-writes holds across the
    cutover, and the router serves the children afterwards."""
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=2)
    c.start()
    try:
        cl = VearchClient(c.router_addr, master_addr=c.master_addr)
        _mk_space(cl)
        vecs = rng.standard_normal((1200, D)).astype(np.float32)
        seed_ids = [f"seed{i}" for i in range(300)]
        cl.upsert("db", "s", [{"_id": k, "v": vecs[i].tolist()}
                              for i, k in enumerate(seed_ids)])
        space0 = cl.get_space("db", "s")
        parent = space0["partitions"][0]["id"]
        assert len(space0["partitions"]) == 1

        acked: list[str] = []
        errors: list[Exception] = []
        stop = threading.Event()

        def writer(tid: int):
            i = 0
            try:
                while not stop.is_set():
                    ids = [f"w{tid}_{i + j}" for j in range(10)]
                    cl.upsert("db", "s", [
                        {"_id": k, "v": vecs[(300 + i + j) % 1200].tolist()}
                        for j, k in enumerate(ids)
                    ])
                    acked.extend(ids)  # list.append is atomic; ids unique
                    i += 10
            except Exception as e:
                errors.append(e)

        def searcher():
            try:
                while not stop.is_set():
                    out = cl.search(
                        "db", "s", [{"field": "v", "feature": vecs[0]}],
                        limit=3)
                    assert len(out) == 1 and out[0]
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,), daemon=True)
                   for t in range(2)]
        threads += [threading.Thread(target=searcher, daemon=True)]
        for t in threads:
            t.start()
        try:
            job = cl.split_partition("db", "s", parent, timeout_s=120.0)
            done = cl.wait_elastic_job(job["job_id"], timeout_s=120.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not errors, errors
        assert done["status"] == "done" and done["op"] == "split"

        # the router now serves two children; the parent is gone
        space1 = cl.get_space("db", "s")
        child_ids = [p["id"] for p in space1["partitions"]]
        assert len(child_ids) == 2 and parent not in child_ids
        assert space1.get("map_version", 0) > space0.get("map_version", 0)

        # zero lost, zero duplicated: the union of everything acked is
        # exactly what a full scan returns (sorted-id pagination would
        # surface a duplicate as an extra row)
        expected = sorted(set(seed_ids) | set(acked))
        assert len(expected) == len(seed_ids) + len(acked)
        got = _all_ids(cl, len(expected))
        assert sorted(got) == expected, (
            f"{len(expected)} acked vs {len(got)} served"
        )
        # read-your-writes by id across the cutover
        if acked:
            probe = acked[-20:]
            found = cl.query("db", "s", document_ids=probe, fields=[])
            assert sorted(d["_id"] for d in found) == sorted(probe)
        # searches land on the children
        out = cl.search("db", "s", [{"field": "v", "feature": vecs[0]}],
                        limit=5)
        assert out[0]

        # observability: the PS-side job is readable at /ps/jobs, the
        # master rolled the split into /cluster/health, and the
        # counter moved
        ps_jobs = [
            j
            for ps in c.ps_nodes
            for j in rpc.call(ps.addr, "GET", "/ps/jobs")["jobs"]
            if j.get("op") == "split"
        ]
        assert ps_jobs and any(j["partition_id"] == parent
                               for j in ps_jobs)
        health = rpc.call(c.master_addr, "GET", "/cluster/health")
        for key in ("splits_running", "splits_failed",
                    "migrations_running", "elastic_jobs_running",
                    "elastic_jobs_failed"):
            assert key in health
        # the split rollup is heartbeat-fed: it drains within a beat
        # of the parent's retirement
        import time as _time
        for _ in range(50):
            if rpc.call(c.master_addr, "GET",
                        "/cluster/health")["splits_running"] == 0:
                break
            _time.sleep(0.1)
        else:
            raise AssertionError("splits_running never drained")
        text = urllib.request.urlopen(
            f"http://{c.master_addr}/metrics").read().decode()
        assert 'vearch_partition_splits_total{status="done"} 1' in text
    finally:
        c.stop()


def test_online_split_replicated_partition_under_traffic(tmp_path, rng):
    """Split a replica_num=2 partition while writers and searchers
    hammer it: zero lost docs, zero duplicated docs, both children
    keep the replica factor, and EVERY replica of every child serves
    the full doc set (direct per-PS query, not just via the router)."""
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=2)
    c.start()
    try:
        cl = VearchClient(c.router_addr, master_addr=c.master_addr)
        _mk_space(cl, replica_num=2)
        vecs = rng.standard_normal((1200, D)).astype(np.float32)
        seed_ids = [f"seed{i}" for i in range(300)]
        cl.upsert("db", "s", [{"_id": k, "v": vecs[i].tolist()}
                              for i, k in enumerate(seed_ids)])
        space0 = cl.get_space("db", "s")
        parent = space0["partitions"][0]["id"]
        assert len(space0["partitions"][0]["replicas"]) == 2

        acked: list[str] = []
        errors: list[Exception] = []
        stop = threading.Event()

        def writer(tid: int):
            i = 0
            try:
                while not stop.is_set():
                    ids = [f"w{tid}_{i + j}" for j in range(10)]
                    cl.upsert("db", "s", [
                        {"_id": k, "v": vecs[(300 + i + j) % 1200].tolist()}
                        for j, k in enumerate(ids)
                    ])
                    acked.extend(ids)
                    i += 10
            except Exception as e:
                errors.append(e)

        def searcher():
            try:
                while not stop.is_set():
                    out = cl.search(
                        "db", "s", [{"field": "v", "feature": vecs[0]}],
                        limit=3)
                    assert len(out) == 1 and out[0]
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,), daemon=True)
                   for t in range(2)]
        threads += [threading.Thread(target=searcher, daemon=True)]
        for t in threads:
            t.start()
        try:
            job = cl.split_partition("db", "s", parent, timeout_s=120.0)
            done = cl.wait_elastic_job(job["job_id"], timeout_s=120.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not errors, [repr(e) for e in errors[:3]]
        assert done["status"] == "done" and done["op"] == "split"

        # both children exist and kept the replica factor
        space1 = cl.get_space("db", "s")
        children = space1["partitions"]
        assert len(children) == 2
        assert parent not in [p["id"] for p in children]
        for p in children:
            assert len(set(p["replicas"])) == 2, p

        # zero lost, zero duplicated through the router
        expected = sorted(set(seed_ids) | set(acked))
        assert len(expected) == len(seed_ids) + len(acked)
        got = _all_ids(cl, len(expected))
        assert sorted(got) == expected, (
            f"{len(expected)} acked vs {len(got)} served"
        )

        # every replica of every child serves its shard: query each
        # hosting PS directly (follower replication is async — poll the
        # whole picture until replicas agree and the union is complete)
        import time as _time
        addr_of = {ps.node_id: ps.addr for ps in c.ps_nodes}

        def _replica_sets():
            out = []
            for p in children:
                out.append([{
                    d["_id"] for d in rpc.call(
                        addr_of[node], "POST", "/ps/doc/query",
                        {"partition_id": p["id"],
                         "limit": len(expected) + 50, "fields": []},
                    )["documents"]
                } for node in p["replicas"]])
            return out

        deadline = _time.monotonic() + 60
        while True:
            per_child = _replica_sets()
            converged = all(
                all(s == sets[0] for s in sets[1:]) for sets in per_child
            ) and sorted(set().union(*(s[0] for s in per_child))) == expected
            if converged or _time.monotonic() > deadline:
                break
            _time.sleep(0.2)
        for p, sets in zip(children, per_child):
            assert all(s == sets[0] for s in sets[1:]), (
                p["id"], [len(s) for s in sets])
        # the two children partition the doc set: disjoint, complete
        a, b = per_child[0][0], per_child[1][0]
        assert not (a & b)
        assert sorted(a | b) == expected
    finally:
        c.stop()


def test_migrate_to_fresh_ps_then_drain_source(tmp_path, rng):
    """Join a brand-new PS, stream a replica onto it, then drain the
    original PS empty — with a searcher asserting zero failed queries
    throughout, and progress visible via /cluster/jobs, /cluster/health
    and the migration counter."""
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1)
    c.start()
    try:
        cl = VearchClient(c.router_addr, master_addr=c.master_addr)
        _mk_space(cl, partition_num=2)
        vecs = rng.standard_normal((200, D)).astype(np.float32)
        cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i].tolist()}
                              for i in range(200)])
        src = c.ps_nodes[0].node_id
        fresh = c.add_ps()
        # the master must see the new node before it can be a target
        deadline = 50
        import time as _time
        for _ in range(deadline * 10):
            servers = rpc.call(c.master_addr, "GET", "/servers")["servers"]
            if any(s["node_id"] == fresh.node_id for s in servers):
                break
            _time.sleep(0.1)
        else:
            raise AssertionError("fresh PS never registered")

        errors: list[Exception] = []
        stop = threading.Event()

        def searcher():
            try:
                while not stop.is_set():
                    out = cl.search(
                        "db", "s", [{"field": "v", "feature": vecs[0]}],
                        limit=3)
                    assert len(out) == 1 and out[0]
            except Exception as e:
                errors.append(e)

        t = threading.Thread(target=searcher, daemon=True)
        t.start()
        try:
            pid = cl.get_space("db", "s")["partitions"][0]["id"]
            job = cl.migrate_partition(pid, to_node=fresh.node_id,
                                       timeout_s=120.0)
            done = cl.wait_elastic_job(job["job_id"], timeout_s=120.0)
            assert done["status"] == "done" and done["op"] == "migrate"
            assert done["detail"]["to_node"] == fresh.node_id

            # the moved partition now lives on the fresh node only
            part = next(p for p in cl.get_space("db", "s")["partitions"]
                        if p["id"] == pid)
            assert part["replicas"] == [fresh.node_id]
            assert part["leader"] == fresh.node_id

            # drain the source PS empty (its remaining partition moves)
            out = cl.drain(src, apply=True)
            assert out.get("job_id"), out
            cl.wait_elastic_job(out["job_id"], timeout_s=120.0)
        finally:
            stop.set()
            t.join(timeout=60)
        assert not errors, [repr(e) for e in errors[:3]]

        servers = rpc.call(c.master_addr, "GET", "/servers")["servers"]
        drained = next(s for s in servers if s["node_id"] == src)
        assert drained["partition_ids"] == [], "source PS not empty"
        # data survived both hops
        assert len(_all_ids(cl, 200)) == 200
        out = cl.search("db", "s", [{"field": "v", "feature": vecs[5]}],
                        limit=3)
        assert out[0]

        # observability: job registry lists both jobs, health has the
        # rollup, and the counter moved once per completed move
        jobs = cl.elastic_jobs()
        assert {j["op"] for j in jobs} >= {"migrate", "drain"}
        assert all(j["status"] == "done" for j in jobs)
        health = rpc.call(c.master_addr, "GET", "/cluster/health")
        assert health["migrations_running"] == 0
        assert health["elastic_jobs_failed"] == 0
        text = urllib.request.urlopen(
            f"http://{c.master_addr}/metrics").read().decode()
        assert 'vearch_replica_migrations_total{status="done"} 2' in text
        assert "vearch_cluster_imbalance_score" in text
    finally:
        c.stop()


def test_plan_and_rebalance_endpoints(tmp_path, rng):
    """/cluster/plan and a no-op rebalance round-trip on a healthy
    cluster; rebalance without `apply` never mutates anything."""
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1)
    c.start()
    try:
        cl = VearchClient(c.router_addr, master_addr=c.master_addr)
        _mk_space(cl)
        plan = cl.cluster_plan()
        for key in ("imbalance", "node_loads", "moves", "splits"):
            assert key in plan
        out = cl.rebalance(apply=False)
        assert out["applied"] is False
        before = cl.get_space("db", "s")
        out = cl.rebalance(apply=True)  # balanced: nothing to do
        assert "job_id" not in out
        assert cl.get_space("db", "s") == before
    finally:
        c.stop()

"""Raft replication observability: per-peer lag gauges, commit/apply
latency, heartbeat-driven convergence, and the /metrics surface on a PS
during an induced follower stall (observability tentpole + the
adversarial-schedule liveness fix).

Reference: the monitor package graphs raft write latency per partition
(internal/monitor/monitor_service.go:77); per-peer next/match state is
what the reference's `_cluster/health?detail=true` exposes per replica.
"""

import json
import time

import numpy as np
import pytest

from vearch_tpu.cluster.master import MasterServer
from vearch_tpu.cluster.ps import PSServer
from vearch_tpu.cluster.raft import RaftNode
from vearch_tpu.cluster.router import RouterServer
from vearch_tpu.cluster.rpc import RpcError
from vearch_tpu.sdk.client import VearchClient

from tests.test_metrics_gauges import gauge_value, scrape

D = 8


# -- direct-node harness (idiom: test_advice_r5._mk_node) --------------------

def _mk_node(tmp_path, nid, members, registry, stalled, **kw):
    state = {"ops": []}

    def apply_fn(op):
        state["ops"].append(op)
        return True

    def snapshot_fn():
        with node._apply_lock:
            return json.dumps(state["ops"]).encode(), node.applied

    def install_fn(data, _idx):
        state["ops"][:] = json.loads(data.decode())

    def send_fn(peer, path, body):
        if peer in stalled:
            raise RpcError(503, f"node {peer} stalled")
        target = registry[peer]
        if path.endswith("/append"):
            return target.handle_append(body)
        if path.endswith("/snapshot"):
            return target.handle_install_snapshot(body)
        raise AssertionError(f"unexpected route {path}")

    node = RaftNode(
        pid=1, node_id=nid, wal_dir=str(tmp_path / f"n{nid}"),
        apply_fn=apply_fn, send_fn=send_fn, members=members,
        is_leader=False, snapshot_fn=snapshot_fn, install_fn=install_fn,
        quorum_timeout=5.0, **kw,
    )
    node._test_state = state
    registry[nid] = node
    return node


def test_lag_gauge_rises_and_tick_alone_converges(tmp_path):
    """The liveness-flake root cause, proven with the new lag gauge: a
    follower unreachable during proposes accumulates visible lag, and
    the leader's heartbeat tick ALONE — no new proposals — re-probes it
    to full convergence (entries AND commit index, so the follower
    actually applies). Before the fix, a retry arriving while another
    sync held the peer lock was silently dropped, and a sync exited as
    soon as the peer held every entry even if its commit index (and
    therefore its applied state) was stale."""
    registry: dict[int, RaftNode] = {}
    stalled: set[int] = set()
    a = _mk_node(tmp_path, 1, [1, 2, 3], registry, stalled)
    b = _mk_node(tmp_path, 2, [1, 2, 3], registry, stalled)
    c = _mk_node(tmp_path, 3, [1, 2, 3], registry, stalled)
    try:
        a.become_leader(1, [1, 2, 3])
        a.propose([{"seq": 0}])
        assert a.replication_lag() == {2: 0, 3: 0}

        stalled.add(3)
        for i in range(1, 4):
            a.propose([{"seq": i}])  # quorum via b; c misses everything
        lag = a.replication_lag()
        assert lag[2] == 0 and lag[3] == 3, lag
        peers = a.state()["peers"]
        assert peers["3"]["lag"] == 3
        assert peers["3"]["match"] == 1
        # the stalled peer's ack age is stale relative to the healthy one
        assert peers["3"]["ack_age"] > peers["2"]["ack_age"]
        assert a.heartbeat_age() == pytest.approx(
            peers["3"]["ack_age"], abs=0.5)
        assert c.applied == 1 and c._test_state["ops"] == [{"seq": 0}]

        # heal; heartbeat ticks only — convergence must not need a write
        stalled.discard(3)
        deadline = time.time() + 10.0
        while time.time() < deadline:
            a.tick()
            if a.replication_lag()[3] == 0 and c.applied == a.commit:
                break
            time.sleep(0.05)
        assert a.replication_lag()[3] == 0
        assert c.commit == a.commit and c.applied == a.commit
        assert c._test_state["ops"] == a._test_state["ops"]
        assert a.state()["peers"]["3"]["lag"] == 0
    finally:
        for n in registry.values():
            n.close()


def test_observer_events_fire_outside_protocol(tmp_path):
    """The observer sink sees commit/apply latency and leadership
    transitions, and a raising observer never breaks the protocol."""
    registry: dict[int, RaftNode] = {}
    stalled: set[int] = set()
    events: list[tuple[str, dict]] = []

    def observer(event, info):
        events.append((event, dict(info)))
        raise RuntimeError("observer bug")  # must be swallowed

    a = _mk_node(tmp_path, 1, [1, 2], registry, stalled, observer=observer)
    b = _mk_node(tmp_path, 2, [1, 2], registry, stalled)
    try:
        a.become_leader(1, [1, 2])
        a.propose([{"seq": 1}, {"seq": 2}])
        kinds = [e for e, _ in events]
        assert "become_leader" in kinds
        commits = [i for e, i in events if e == "commit"]
        assert len(commits) == 1
        assert commits[0]["entries"] == 2 and commits[0]["seconds"] >= 0
        applies = [i for e, i in events if e == "apply"]
        assert [i["index"] for i in applies] == [1, 2]
        assert all(i["seconds"] >= 0 for i in applies)
        # despite the raising observer, the write went through
        assert a._test_state["ops"] == b._test_state["ops"] == [
            {"seq": 1}, {"seq": 2}]
        assert a.state()["elections_started"] == 0  # decreed, not voted
    finally:
        a.close()
        b.close()


# -- cluster surface: /metrics moves during an induced follower stall --------

def test_ps_metrics_move_during_follower_stall(tmp_path, rng):
    """Acceptance: the leader PS's /metrics exposes raft peer-lag and
    commit-latency series, and an induced follower stall makes them
    move — lag > 0 for the dead node, commit-latency histogram count
    grows with each write, heartbeat age present. heartbeat_ttl is kept
    long so the master does not reconfigure the group mid-assertion."""
    master = MasterServer(heartbeat_ttl=30.0)
    master.start()
    ps_nodes = []
    for i in range(3):
        ps = PSServer(data_dir=str(tmp_path / f"ps{i}"),
                      master_addr=master.addr, heartbeat_interval=0.3)
        ps.start()
        ps_nodes.append(ps)
    router = RouterServer(master_addr=master.addr)
    router.start()
    try:
        cl = VearchClient(router.addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1, "replica_num": 3,
            "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                        "index": {"index_type": "FLAT", "metric_type": "L2",
                                  "params": {}}}],
        })
        vecs = rng.standard_normal((30, D)).astype(np.float32)
        cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                              for i in range(10)])

        part = cl.get_space("db", "s")["partitions"][0]
        pid = part["id"]
        leader_ps = next(p for p in ps_nodes
                         if p.node_id == part["leader"])
        follower = next(p for p in ps_nodes
                        if p.node_id != part["leader"])

        text = scrape(leader_ps.addr)
        assert gauge_value(text, "vearch_raft_is_leader",
                           partition=pid) == 1.0
        assert (gauge_value(text, "vearch_raft_commit_index",
                            partition=pid) or 0) > 0
        # healthy group: every peer at lag 0
        for p in ps_nodes:
            if p.node_id != leader_ps.node_id:
                assert gauge_value(text, "vearch_raft_peer_lag",
                                   partition=pid, peer=p.node_id) == 0.0
        c0 = gauge_value(
            text, "vearch_raft_commit_latency_seconds_count",
            partition=pid) or 0.0
        assert c0 > 0  # the upserts above were timed
        # exporter-health counters ride the same scrape (satellite:
        # collector outage is observable, not silent)
        assert "tracing_dropped_spans_total" in text

        follower.stop()  # induced stall
        cl.upsert("db", "s", [{"_id": f"s{i}", "v": vecs[10 + i]}
                              for i in range(10)])

        text = scrape(leader_ps.addr)
        lag = gauge_value(text, "vearch_raft_peer_lag",
                          partition=pid, peer=follower.node_id)
        assert lag is not None and lag > 0, "stalled peer shows no lag"
        nxt = gauge_value(text, "vearch_raft_peer_next_index",
                          partition=pid, peer=follower.node_id)
        assert nxt is not None
        c1 = gauge_value(
            text, "vearch_raft_commit_latency_seconds_count",
            partition=pid) or 0.0
        assert c1 > c0  # writes during the stall were still timed
        age = gauge_value(text, "vearch_raft_heartbeat_age_seconds",
                          partition=pid)
        assert age is not None and age >= 0.0
    finally:
        router.stop()
        for ps in ps_nodes:
            try:
                ps.stop()
            except Exception:
                pass
        master.stop()

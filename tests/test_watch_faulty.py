"""Watch-driven router caches + faulty-node tracking (reference:
internal/client/master_cache.go — etcd watch streams keep the client's
space/server caches fresh; a faulty-server list routes reads around
nodes whose RPCs just failed)."""

import threading
import time

import numpy as np
import pytest

from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient

D = 8


@pytest.fixture()
def cluster(tmp_path):
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=2)
    c.start()
    yield c
    c.stop()


def _mk_space(cl, name, replica_num=1, dim=D):
    cl.create_space("db", {
        "name": name, "partition_num": 2, "replica_num": replica_num,
        "fields": [{"name": "v", "data_type": "vector", "dimension": dim,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })


def test_watch_longpoll_fires_on_mutation(cluster):
    """GET /watch blocks, then returns within the poll window once a
    metadata key changes — not after a TTL."""
    rev0 = rpc.call(cluster.master_addr, "GET", "/watch",
                    {"rev": 0, "timeout": 0.0})["rev"]
    out = {}

    def poll():
        out["res"] = rpc.call(cluster.master_addr, "GET", "/watch",
                              {"rev": rev0, "timeout": 10.0})

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.3)
    cl = VearchClient(cluster.router_addr)
    t0 = time.time()
    cl.create_database("db")
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert out["res"]["rev"] > rev0
    assert any(k.startswith("/db/") for k in out["res"]["keys"]), out
    assert time.time() - t0 < 3.0  # long-poll, not TTL wait


def test_watch_reset_when_past_ring(cluster):
    """A watcher older than the event ring is told to resync."""
    master = cluster.master
    for i in range(600):  # overflow the 512-event ring
        master.store.put(f"/config/junk/{i % 7}", {"i": i})
    out = rpc.call(cluster.master_addr, "GET", "/watch",
                   {"rev": 1, "timeout": 0.0})
    assert out.get("reset") is True


def test_router_cache_invalidated_by_watch_not_ttl(cluster):
    """With the TTL effectively infinite, a space drop+recreate is
    visible to the router via the watch within one poll round."""
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    _mk_space(cl, "s", dim=D)
    rng = np.random.default_rng(0)
    vecs = rng.standard_normal((50, D)).astype(np.float32)
    cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                          for i in range(50)])
    assert cl.search("db", "s", [{"field": "v", "feature": vecs[3]}],
                     limit=1)[0][0]["_id"] == "d3"

    cluster.router.space_cache_ttl = 1e9  # TTL can no longer help

    # recreate with a different dimension: stale partition routing or a
    # stale schema would fail the new-dim search
    cl.drop_space("db", "s")
    _mk_space(cl, "s", dim=D * 2)
    big = rng.standard_normal((10, D * 2)).astype(np.float32)
    deadline = time.time() + 10.0
    last = None
    while time.time() < deadline:
        try:
            cl.upsert("db", "s", [{"_id": f"n{i}", "v": big[i]}
                                  for i in range(10)])
            res = cl.search("db", "s",
                            [{"field": "v", "feature": big[4]}], limit=1)
            assert res[0][0]["_id"] == "n4"
            break
        except rpc.RpcError as e:  # watch not applied yet
            last = e
            time.sleep(0.2)
    else:
        raise AssertionError(f"watch never refreshed the cache: {last}")
    stats = rpc.call(cluster.router_addr, "GET", "/router/stats", None)
    assert stats["watch_rev"] > 0


def test_faulty_node_skipped_by_read_balancing(cluster):
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    _mk_space(cl, "r", replica_num=2)
    rng = np.random.default_rng(1)
    vecs = rng.standard_normal((40, D)).astype(np.float32)
    cl.upsert("db", "r", [{"_id": f"d{i}", "v": vecs[i]}
                          for i in range(40)])

    victim = cluster.ps_nodes[1]
    victim_id = victim.node_id
    victim.stop()
    router = cluster.router
    router._faulty.clear()
    # every read succeeds despite the dead replica, and random picks
    # eventually touch the dead node (p ~1/2 per partition call), whose
    # failure must land it in the faulty map. Missing 40 coin flips has
    # probability ~2^-40 — this genuinely tests the marking path.
    marked = False
    for i in range(40):
        res = cl.search("db", "r",
                        [{"field": "v", "feature": vecs[i % 40]}],
                        limit=1, load_balance="random")
        assert res[0][0]["_id"] == f"d{i % 40}"
        if victim_id in router._faulty:
            marked = True
            break
    assert marked, "dead node was never penalised by a failed RPC"
    stats = rpc.call(cluster.router_addr, "GET", "/router/stats", None)
    assert str(victim_id) in stats["faulty_nodes"], stats
    # deterministic check at the unit level: mark + skip
    router._faulty[victim_id] = time.time() + 5.0
    space = router._space("db", "r")
    for p in space.partitions:
        if victim_id in p.replicas and len(p.replicas) > 1:
            for _ in range(10):
                node, _addr = router._partition_target(space, p.id,
                                                      "random")
                assert node != victim_id


def test_alias_resolved_cache_evicted_on_space_change(cluster):
    """Space-cache entries created through an alias are evicted when the
    CANONICAL space changes (watch keys name the real space, not the
    alias the router cached under)."""
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    _mk_space(cl, "real", dim=D)
    rpc.call(cluster.router_addr, "POST", "/alias/a1/dbs/db/spaces/real",
             None)
    rng = np.random.default_rng(2)
    vecs = rng.standard_normal((20, D)).astype(np.float32)
    cl.upsert("db", "real", [{"_id": f"d{i}", "v": vecs[i]}
                             for i in range(20)])
    # warm the cache through the alias
    assert cl.search("db", "a1", [{"field": "v", "feature": vecs[1]}],
                     limit=1)[0][0]["_id"] == "d1"
    cluster.router.space_cache_ttl = 1e9

    cl.drop_space("db", "real")
    _mk_space(cl, "real", dim=D * 2)
    rpc.call(cluster.router_addr, "POST", "/alias/a1/dbs/db/spaces/real",
             None)
    big = rng.standard_normal((6, D * 2)).astype(np.float32)
    deadline = time.time() + 10.0
    while True:
        try:
            cl.upsert("db", "real", [{"_id": f"n{i}", "v": big[i]}
                                     for i in range(6)])
            res = cl.search("db", "a1",
                            [{"field": "v", "feature": big[3]}], limit=1)
            assert res[0][0]["_id"] == "n3"
            break
        except rpc.RpcError:
            assert time.time() < deadline, "alias cache never refreshed"
            time.sleep(0.2)


def test_watch_response_carries_stable_epoch(cluster):
    """/watch responses name the master process instance: revs are
    per-process counters, so routers key full resyncs on epoch change
    instead of comparing rev magnitudes across processes."""
    a = rpc.call(cluster.master_addr, "GET", "/watch",
                 {"rev": 0, "timeout": 0.0})
    b = rpc.call(cluster.master_addr, "GET", "/watch",
                 {"rev": 0, "timeout": 0.0})
    assert a["epoch"] and a["epoch"] == b["epoch"]


def test_watch_epoch_change_forces_full_resync(cluster):
    """A /watch answer from a DIFFERENT master process (failover across
    the multi-master list, or restart) drops every router cache even
    when the new rev is numerically ahead of ours."""
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    _mk_space(cl, "s", dim=D)
    router = cluster.router
    router.space_cache_ttl = 1e9
    rng = np.random.default_rng(5)
    v = rng.standard_normal(D).astype(np.float32)
    cl.upsert("db", "s", [{"_id": "x", "v": v}])
    cl.search("db", "s", [{"field": "v", "feature": v}], limit=1)
    assert router._space_cache  # warmed

    orig = router._master_call

    def imposter(method, path, body=None):
        if path == "/watch":
            # pretend a fresh master with interleaved-forward numbering
            return {"rev": router._watch_rev + 100,
                    "epoch": "imposter-epoch", "keys": []}
        return orig(method, path, body)

    router._master_call = imposter
    try:
        deadline = time.time() + 5.0
        while router._watch_epoch != "imposter-epoch":
            assert time.time() < deadline, "epoch never adopted"
            time.sleep(0.05)
        time.sleep(0.1)  # let the resync that rides the adoption land
        assert not router._space_cache, "caches survived an epoch change"
    finally:
        router._master_call = orig

"""Hybrid scalar-filter + vector search tests (reference:
test/test_module_filter.py — range/term filters combined with knn)."""

import numpy as np
import pytest

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType,
    FieldSchema,
    IndexParams,
    MetricType,
    ScalarIndexType,
    TableSchema,
)
from vearch_tpu.scalar.filter import Condition, Filter

D = 16


def make_engine(rng, scalar_index=ScalarIndexType.NONE, n=200):
    schema = TableSchema(
        name="f",
        fields=[
            FieldSchema("price", DataType.FLOAT, scalar_index=scalar_index),
            FieldSchema("cat", DataType.STRING, scalar_index=scalar_index),
            FieldSchema("stock", DataType.INT),
            FieldSchema("emb", DataType.VECTOR, dimension=D,
                        index=IndexParams("FLAT", MetricType.L2)),
        ],
    )
    eng = Engine(schema)
    vecs = rng.standard_normal((n, D)).astype(np.float32)
    docs = [
        {"_id": f"d{i}", "price": float(i % 50), "cat": f"c{i % 5}",
         "stock": i % 10, "emb": vecs[i]}
        for i in range(n)
    ]
    eng.upsert(docs)
    return eng, vecs


FILTER_CASES = [
    ({"operator": "AND",
      "conditions": [{"field": "price", "operator": ">=", "value": 10},
                     {"field": "price", "operator": "<", "value": 20}]},
     lambda i: 10 <= (i % 50) < 20),
    ({"operator": "AND",
      "conditions": [{"field": "cat", "operator": "IN", "value": ["c1", "c3"]}]},
     lambda i: i % 5 in (1, 3)),
    ({"operator": "OR",
      "conditions": [{"field": "price", "operator": "=", "value": 7},
                     {"field": "cat", "operator": "=", "value": "c2"}]},
     lambda i: (i % 50) == 7 or i % 5 == 2),
    ({"operator": "AND",
      "conditions": [{"field": "cat", "operator": "NOT IN", "value": ["c0"]},
                     {"field": "stock", "operator": "!=", "value": 3}]},
     lambda i: i % 5 != 0 and i % 10 != 3),
]


@pytest.mark.parametrize("scalar_index", [
    ScalarIndexType.NONE, ScalarIndexType.INVERTED, ScalarIndexType.BITMAP,
])
@pytest.mark.parametrize("case", range(len(FILTER_CASES)))
def test_filtered_search_matches_predicate(rng, scalar_index, case):
    flt, pred = FILTER_CASES[case]
    eng, vecs = make_engine(rng, scalar_index)
    res = eng.search(SearchRequest(vectors={"emb": vecs[:4]}, k=200, filters=flt))
    expect = {f"d{i}" for i in range(200) if pred(i)}
    for r in res:
        got = {it.key for it in r.items}
        assert got == expect


def test_filter_self_query_top1(rng):
    eng, vecs = make_engine(rng)
    flt = {"operator": "AND",
           "conditions": [{"field": "cat", "operator": "IN", "value": ["c3"]}]}
    res = eng.search(SearchRequest(vectors={"emb": vecs[3]}, k=1, filters=flt))
    assert res[0].items[0].key == "d3"


def test_filter_excludes_top_hit(rng):
    eng, vecs = make_engine(rng)
    flt = {"operator": "AND",
           "conditions": [{"field": "cat", "operator": "NOT IN", "value": ["c3"]}]}
    res = eng.search(SearchRequest(vectors={"emb": vecs[3]}, k=5, filters=flt))
    assert all(it.key != "d3" for it in res[0].items)


def test_filter_with_deletes(rng):
    eng, vecs = make_engine(rng)
    eng.delete(["d7"])
    flt = {"operator": "AND",
           "conditions": [{"field": "price", "operator": "=", "value": 7.0}]}
    res = eng.search(SearchRequest(vectors={"emb": vecs[7]}, k=50, filters=flt))
    keys = {it.key for it in res[0].items}
    assert "d7" not in keys
    assert keys == {f"d{i}" for i in range(200) if i % 50 == 7 and i != 7}


def test_filter_on_ivf_index(rng):
    schema = TableSchema(
        name="fi",
        fields=[
            FieldSchema("price", DataType.FLOAT),
            FieldSchema("emb", DataType.VECTOR, dimension=D,
                        index=IndexParams("IVFFLAT", MetricType.L2,
                                          {"ncentroids": 16, "nprobe": 16})),
        ],
    )
    eng = Engine(schema)
    vecs = rng.standard_normal((2000, D)).astype(np.float32)
    eng.upsert([{"_id": f"d{i}", "price": float(i % 100), "emb": vecs[i]}
                for i in range(2000)])
    eng.build_index()
    flt = {"operator": "AND",
           "conditions": [{"field": "price", "operator": "<", "value": 50}]}
    res = eng.search(SearchRequest(vectors={"emb": vecs[:8]}, k=10, filters=flt))
    for qi, r in enumerate(res):
        assert all(int(it.key[1:]) % 100 < 50 for it in r.items)
        if qi % 100 < 50:  # query's own row passes the filter
            assert r.items[0].key == f"d{qi}"


def test_invalid_operator_rejected():
    with pytest.raises(ValueError, match="unsupported filter operator"):
        Condition("price", "LIKE", "x")
    with pytest.raises(ValueError, match="unsupported filter combinator"):
        Filter(operator="XOR")


def test_composite_index_equality_and(rng):
    """AND of equality conditions over a declared composite index
    (reference: table/composite_index.h, test_module_filter_composite)."""
    schema = TableSchema(
        name="comp",
        fields=[
            FieldSchema("brand", DataType.STRING),
            FieldSchema("color", DataType.STRING),
            FieldSchema("price", DataType.FLOAT),
            FieldSchema("emb", DataType.VECTOR, dimension=D,
                        index=IndexParams("FLAT", MetricType.L2)),
        ],
        composite_indexes=[["brand", "color"]],
    )
    eng = Engine(schema)
    vecs = rng.standard_normal((100, D)).astype(np.float32)
    eng.upsert([
        {"_id": f"d{i}", "brand": f"b{i % 4}", "color": f"c{i % 3}",
         "price": float(i), "emb": vecs[i]}
        for i in range(100)
    ])
    flt = {"operator": "AND",
           "conditions": [{"field": "brand", "operator": "=", "value": "b1"},
                          {"field": "color", "operator": "=", "value": "c2"}]}
    res = eng.search(SearchRequest(vectors={"emb": vecs[:1]}, k=100,
                                   filters=flt))
    expect = {f"d{i}" for i in range(100) if i % 4 == 1 and i % 3 == 2}
    assert {it.key for it in res[0].items} == expect

    # composite + extra range condition combine correctly
    flt["conditions"].append(
        {"field": "price", "operator": "<", "value": 50})
    res = eng.search(SearchRequest(vectors={"emb": vecs[:1]}, k=100,
                                   filters=flt))
    assert {it.key for it in res[0].items} == {
        k for k in expect if int(k[1:]) < 50
    }
    # the composite actually got used (sanity on the planner)
    ci = eng._scalar_manager.composite_for({"brand", "color"})
    assert ci is not None and ci._rows


def test_composite_prefix_and_range_scan(rng):
    """Composite-key semantics (reference: composite_index.h ordered
    multi-column keys): equality on a prefix of the member fields plus a
    range on the next member resolves in the composite, not per-field."""
    schema = TableSchema(
        name="comp3",
        fields=[
            FieldSchema("brand", DataType.STRING),
            FieldSchema("price", DataType.FLOAT),
            FieldSchema("emb", DataType.VECTOR, dimension=D,
                        index=IndexParams("FLAT", MetricType.L2)),
        ],
        composite_indexes=[["brand", "price"]],
    )
    eng = Engine(schema)
    vecs = rng.standard_normal((120, D)).astype(np.float32)
    eng.upsert([
        {"_id": f"d{i}", "brand": f"b{i % 3}", "price": float(i % 40),
         "emb": vecs[i]}
        for i in range(120)
    ])

    def hits(flt):
        res = eng.search(SearchRequest(vectors={"emb": vecs[:1]}, k=120,
                                       filters=flt))
        return {it.key for it in res[0].items}

    # prefix equality + range on the next member field
    flt = {"operator": "AND", "conditions": [
        {"field": "brand", "operator": "=", "value": "b1"},
        {"field": "price", "operator": "<", "value": 10},
    ]}
    assert hits(flt) == {f"d{i}" for i in range(120)
                         if i % 3 == 1 and (i % 40) < 10}
    # >= variant
    flt["conditions"][1] = {"field": "price", "operator": ">=", "value": 30}
    assert hits(flt) == {f"d{i}" for i in range(120)
                         if i % 3 == 1 and (i % 40) >= 30}
    # prefix-only equality (brand alone) also rides the composite
    flt = {"operator": "AND", "conditions": [
        {"field": "brand", "operator": "=", "value": "b2"},
    ]}
    assert hits(flt) == {f"d{i}" for i in range(120) if i % 3 == 2}
    # a band: composite consumes one bound, per-field handles the other
    flt = {"operator": "AND", "conditions": [
        {"field": "brand", "operator": "=", "value": "b0"},
        {"field": "price", "operator": ">", "value": 5},
        {"field": "price", "operator": "<=", "value": 15},
    ]}
    assert hits(flt) == {f"d{i}" for i in range(120)
                         if i % 3 == 0 and 5 < (i % 40) <= 15}


def test_composite_survives_dump_load(rng, tmp_path):
    schema = TableSchema(
        name="comp2",
        fields=[
            FieldSchema("a", DataType.STRING),
            FieldSchema("b", DataType.STRING),
            FieldSchema("emb", DataType.VECTOR, dimension=D,
                        index=IndexParams("FLAT", MetricType.L2)),
        ],
        composite_indexes=[["a", "b"]],
    )
    eng = Engine(schema)
    vecs = rng.standard_normal((20, D)).astype(np.float32)
    eng.upsert([{"_id": f"d{i}", "a": f"a{i % 2}", "b": f"b{i % 2}",
                 "emb": vecs[i]} for i in range(20)])
    eng.dump(str(tmp_path / "c"))
    eng2 = Engine.open(str(tmp_path / "c"))
    flt = {"operator": "AND",
           "conditions": [{"field": "a", "operator": "=", "value": "a1"},
                          {"field": "b", "operator": "=", "value": "b1"}]}
    res = eng2.search(SearchRequest(vectors={"emb": vecs[:1]}, k=20,
                                    filters=flt))
    assert {it.key for it in res[0].items} == \
        {f"d{i}" for i in range(20) if i % 2 == 1}


def test_scalar_index_survives_dump_load(rng, tmp_path):
    eng, vecs = make_engine(rng, ScalarIndexType.INVERTED)
    eng.dump(str(tmp_path / "s"))
    eng2 = Engine.open(str(tmp_path / "s"))
    flt = {"operator": "AND",
           "conditions": [{"field": "price", "operator": "=", "value": 5.0}]}
    res = eng2.search(SearchRequest(vectors={"emb": vecs[:1]}, k=200, filters=flt))
    assert {it.key for it in res[0].items} == \
        {f"d{i}" for i in range(200) if i % 50 == 5}

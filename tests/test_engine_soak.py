"""Randomized engine soak: interleaved upserts, partial updates,
deletes, online field-index flips, dumps and reopens — checking after
every step that a shadow model agrees with the engine (latest values by
id, filter counts, self-match search). The reference's long pytest suite
gets equivalent assurance from sheer breadth; this compresses it into a
property-style run."""

import numpy as np
import pytest

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType, FieldSchema, IndexParams, MetricType, TableSchema,
)

D = 8


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1234, 20260730])
def test_randomized_soak(tmp_path, seed):
    rng = np.random.default_rng(seed)
    schema = TableSchema("t", [
        FieldSchema("color", DataType.STRING),
        FieldSchema("price", DataType.INT),
        FieldSchema("v", DataType.VECTOR, dimension=D,
                    index=IndexParams("FLAT", MetricType.L2, {})),
    ])
    eng = Engine(schema, data_dir=str(tmp_path / "d"))
    shadow: dict[str, dict] = {}  # id -> {color, price(set?), vec}
    colors = ["red", "green", "blue"]

    def check():
        # filter count parity for a random color
        c = colors[int(rng.integers(0, 3))]
        want = sum(1 for d in shadow.values() if d.get("color") == c)
        got = eng.query({"operator": "AND", "conditions": [
            {"operator": "=", "field": "color", "value": c}]},
            limit=10_000, include_fields=[])
        assert len(got) == want, (c, len(got), want)
        # self-match for a random live doc
        if shadow:
            key = list(shadow)[int(rng.integers(0, len(shadow)))]
            res = eng.search(SearchRequest(
                vectors={"v": shadow[key]["vec"][None, :]}, k=3,
                include_fields=["color", "price"]))
            items = res[0].items
            assert items and items[0].key == key, (key, items[:2])
            if "color" in shadow[key]:
                assert items[0].fields["color"] == shadow[key]["color"]

    next_id = 0
    for step in range(300):
        op = rng.random()
        if op < 0.45 or not shadow:  # insert or full update
            n = int(rng.integers(1, 8))
            docs = []
            for _ in range(n):
                if shadow and rng.random() < 0.3:
                    key = list(shadow)[int(rng.integers(0, len(shadow)))]
                else:
                    key = f"k{next_id}"
                    next_id += 1
                vec = rng.standard_normal(D).astype(np.float32)
                color = colors[int(rng.integers(0, 3))]
                price = int(rng.integers(0, 100))
                docs.append({"_id": key, "color": color, "price": price,
                             "v": vec})
                shadow[key] = {"color": color, "price": price, "vec": vec}
            eng.upsert(docs)
        elif op < 0.60:  # partial update (scalars only)
            key = list(shadow)[int(rng.integers(0, len(shadow)))]
            color = colors[int(rng.integers(0, 3))]
            eng.upsert([{"_id": key, "color": color}])
            shadow[key]["color"] = color
        elif op < 0.72:  # delete
            key = list(shadow)[int(rng.integers(0, len(shadow)))]
            assert eng.delete([key]) == 1
            del shadow[key]
        elif op < 0.82:  # flip the color index on/off
            if (eng._scalar_manager is not None
                    and eng._scalar_manager.has_index("color")):
                eng.remove_field_index("color")
            else:
                eng.add_field_index("color", "BITMAP", background=False)
        elif op < 0.90:  # dump + reopen
            eng.dump(str(tmp_path / "d"))
            eng.close()
            eng = Engine.open(str(tmp_path / "d"))
        else:
            check()
    check()
    # final exhaustive id sweep
    for key, d in shadow.items():
        got = eng.get([key])
        assert got and got[0]["color"] == d.get("color"), key

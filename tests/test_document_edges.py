"""Document-op edge cases mirroring the reference suite (reference:
test/test_document_upsert.py update() with has_vector=False —
partial updates; test_document_search.py
test_vearch_document_search_with_score_filter — min/max score windows;
badcase classes — validation)."""

import numpy as np
import pytest

from vearch_tpu.cluster.rpc import RpcError
from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient
import vearch_tpu.cluster.rpc as rpc

D = 8


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    with StandaloneCluster(
        data_dir=str(tmp_path_factory.mktemp("edges")), n_ps=2
    ) as c:
        yield c


@pytest.fixture(scope="module")
def client(cluster):
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "sp", "partition_num": 2, "replica_num": 1,
        "fields": [
            {"name": "color", "data_type": "string"},
            {"name": "price", "data_type": "float"},
            {"name": "emb", "data_type": "vector", "dimension": D,
             "index": {"index_type": "FLAT", "metric_type": "L2",
                       "params": {}}},
        ],
    })
    return cl


@pytest.fixture(scope="module")
def vecs(client):
    rng = np.random.default_rng(5)
    v = rng.standard_normal((40, D)).astype(np.float32)
    client.upsert("db", "sp", [
        {"_id": f"d{i}", "color": "red", "price": float(i), "emb": v[i]}
        for i in range(40)
    ])
    return v


def _get(client, _id):
    docs = client.query("db", "sp", document_ids=[_id])
    return docs[0] if docs else None


def test_partial_update_without_vector(client, vecs):
    """Upsert with only scalars for an existing _id: scalars change, the
    stored vector survives (reference: add(has_vector=False))."""
    client.upsert("db", "sp", [{"_id": "d3", "color": "blue"}])
    doc = _get(client, "d3")
    assert doc["color"] == "blue"
    assert doc["price"] == 3.0  # omitted scalar carried forward
    # the vector is the ORIGINAL one: exact search still lands on d3
    hits = client.search("db", "sp",
                         [{"field": "emb", "feature": vecs[3].tolist()}],
                         limit=1)
    assert hits[0][0]["_id"] == "d3"


def test_partial_update_new_id_is_rejected(client):
    with pytest.raises(RpcError) as e:
        client.upsert("db", "sp", [{"_id": "ghost", "color": "x"}])
    assert e.value.code == 400
    assert _get(client, "ghost") is None


def test_partial_update_vector_only(client, vecs):
    """Upsert with only the vector: scalars carry forward."""
    newv = np.full(D, 9.0, dtype=np.float32)
    client.upsert("db", "sp", [{"_id": "d5", "emb": newv}])
    doc = _get(client, "d5")
    assert doc["color"] == "red" and doc["price"] == 5.0
    hits = client.search("db", "sp",
                         [{"field": "emb", "feature": newv.tolist()}],
                         limit=1)
    assert hits[0][0]["_id"] == "d5"


def test_search_score_window(client, vecs):
    """min_score/max_score bound the user-facing score (L2: distance²,
    lower = closer). max_score=0.01 keeps only the self-match."""
    out = rpc.call(client.addr, "POST", "/document/search", {
        "db_name": "db", "space_name": "sp",
        "vectors": [{"field": "emb", "feature": vecs[7].tolist(),
                     "max_score": 0.01}],
        "limit": 10,
    })
    rows = out["documents"][0]
    assert [r["_id"] for r in rows] == ["d7"]
    assert rows[0]["_score"] <= 0.01

    # a min_score floor excludes the self-match but keeps neighbors
    out = rpc.call(client.addr, "POST", "/document/search", {
        "db_name": "db", "space_name": "sp",
        "vectors": [{"field": "emb", "feature": vecs[7].tolist(),
                     "min_score": 0.01}],
        "limit": 10,
    })
    rows = out["documents"][0]
    assert rows and all(r["_id"] != "d7" for r in rows)
    assert all(r["_score"] >= 0.01 for r in rows)


def test_upsert_validation_badcases(client):
    # wrong vector length
    with pytest.raises(RpcError) as e:
        client.upsert("db", "sp", [
            {"_id": "bad", "color": "x", "price": 0.0,
             "emb": [0.0] * (D + 1)}])
    assert e.value.code == 400
    # unknown field
    with pytest.raises(RpcError) as e:
        client.upsert("db", "sp", [
            {"_id": "bad", "nope": 1, "emb": [0.0] * D}])
    assert e.value.code == 400
    # a failed batch must not write anything
    assert _get(client, "bad") is None


def test_null_vector_means_keep_stored(client, vecs):
    """A JSON null vector is the natural 'keep the stored one' idiom —
    it must behave exactly like omitting the field."""
    client.upsert("db", "sp", [{"_id": "d11", "emb": None,
                                "color": "violet"}])
    doc = _get(client, "d11")
    assert doc["color"] == "violet"
    hits = client.search("db", "sp",
                         [{"field": "emb", "feature": vecs[11].tolist()}],
                         limit=1)
    assert hits[0][0]["_id"] == "d11"  # stored vector intact


def test_same_id_twice_in_one_batch(client, vecs):
    """[full update, partial update] of the same _id in ONE batch: the
    partial inherits the NEW vector from earlier in the batch, not the
    pre-batch one (reviewer-found ordering bug)."""
    newv = np.full(D, -7.0, dtype=np.float32)
    client.upsert("db", "sp", [
        {"_id": "d13", "color": "gold", "price": 1.0, "emb": newv},
        {"_id": "d13", "color": "silver"},
    ])
    doc = _get(client, "d13")
    assert doc["color"] == "silver" and doc["price"] == 1.0
    hits = client.search("db", "sp",
                         [{"field": "emb", "feature": newv.tolist()}],
                         limit=1)
    assert hits[0][0]["_id"] == "d13"  # batch-internal vector won


def test_bad_scalar_type_rejected_without_corruption(client, vecs):
    """A scalar value the column cannot take must 400 BEFORE any
    mutation — a mid-batch failure would desync table rows from vector
    rows forever (reviewer-found invariant hazard)."""
    with pytest.raises(RpcError) as e:
        client.upsert("db", "sp", [
            {"_id": "t1", "color": "x", "price": "not-a-number",
             "emb": np.zeros(D, dtype=np.float32)}])
    assert e.value.code == 400
    assert _get(client, "t1") is None
    # the engine still works and rows still line up
    client.upsert("db", "sp", [
        {"_id": "t2", "color": "y", "price": 2.0,
         "emb": np.full(D, 4.0, dtype=np.float32)}])
    hits = client.search(
        "db", "sp",
        [{"field": "emb", "feature": np.full(D, 4.0, np.float32)}],
        limit=1)
    assert hits[0][0]["_id"] == "t2"


def test_partial_update_does_not_index_phantom_defaults(tmp_path):
    """A doc that never set an INT field stores the column default (0);
    a partial update must not resurrect that 0 as a filterable value
    (reviewer-found phantom-index bug)."""
    from vearch_tpu.engine.engine import Engine
    from vearch_tpu.engine.types import (
        DataType, FieldSchema, IndexParams, MetricType, ScalarIndexType,
        TableSchema,
    )

    schema = TableSchema("t", [
        FieldSchema("price", DataType.INT,
                    scalar_index=ScalarIndexType.INVERTED),
        FieldSchema("tag", DataType.STRING),
        FieldSchema("v", DataType.VECTOR, dimension=4,
                    index=IndexParams("FLAT", MetricType.L2, {})),
    ])
    eng = Engine(schema)
    eng.upsert([
        {"_id": "a", "v": [0.0] * 4},               # price never set
        {"_id": "b", "price": 0, "v": [1.0] * 4},   # price really 0
    ])
    eng.upsert([{"_id": "a", "tag": "x"}])  # partial update of a
    docs = eng.query({"operator": "AND", "conditions": [
        {"operator": "=", "field": "price", "value": 0}]}, limit=10)
    assert [d["_id"] for d in docs] == ["b"], docs


def test_partial_update_on_disk_store(tmp_path):
    """Vector inheritance reads the stored row off the mmap disk tier
    (DiskRawVectorStore.get) — partial updates must work for
    store_type: RocksDB/Disk spaces too."""
    from vearch_tpu.engine.disk_vector import DiskRawVectorStore
    from vearch_tpu.engine.engine import Engine, SearchRequest
    from vearch_tpu.engine.types import (
        DataType, FieldSchema, IndexParams, MetricType, TableSchema,
    )

    schema = TableSchema("t", [
        FieldSchema("tag", DataType.STRING),
        FieldSchema("v", DataType.VECTOR, dimension=16,
                    index=IndexParams("FLAT", MetricType.L2,
                                      {"store_type": "RocksDB"})),
    ])
    eng = Engine(schema, data_dir=str(tmp_path / "d"))
    assert isinstance(eng.vector_stores["v"], DiskRawVectorStore)
    rng = np.random.default_rng(21)
    vecs = rng.standard_normal((30, 16)).astype(np.float32)
    eng.upsert([{"_id": f"k{i}", "tag": "a", "v": vecs[i]}
                for i in range(30)])
    eng.upsert([{"_id": "k4", "tag": "b"}])  # scalars only
    res = eng.search(SearchRequest(vectors={"v": vecs[4:5]}, k=1))
    assert res[0].items[0].key == "k4"
    assert res[0].items[0].fields["tag"] == "b"


def test_microbatch_score_bounds_not_shared():
    """Concurrent bounded and unbounded searches must not co-batch into
    one request that drops the window (reviewer-found silent-wrong-
    results bug)."""
    import threading

    from vearch_tpu.engine.engine import Engine, SearchRequest
    from vearch_tpu.engine.types import (
        DataType, FieldSchema, IndexParams, MetricType, TableSchema,
    )

    schema = TableSchema("t", [
        FieldSchema("v", DataType.VECTOR, dimension=4,
                    index=IndexParams("FLAT", MetricType.L2, {})),
    ])
    eng = Engine(schema)
    rng = np.random.default_rng(0)
    base = rng.standard_normal((50, 4)).astype(np.float32)
    eng.upsert([{"_id": str(i), "v": base[i]} for i in range(50)])

    q = base[7:8]
    out: dict[str, list] = {}

    def run(name, bounds):
        out[name] = eng.search(SearchRequest(
            vectors={"v": q}, k=10, include_fields=[],
            score_bounds=bounds))

    ts = [
        threading.Thread(target=run, args=("bounded",
                                           {"v": (None, 1e-4)})),
        threading.Thread(target=run, args=("free", None)),
    ]
    [t.start() for t in ts]
    [t.join() for t in ts]
    bounded = out["bounded"][0].items
    free = out["free"][0].items
    assert [it.key for it in bounded] == ["7"]  # window enforced
    assert len(free) == 10  # unbounded untouched


def test_full_update_still_replaces_everything(client, vecs):
    client.upsert("db", "sp", [
        {"_id": "d9", "color": "green", "price": 99.0,
         "emb": np.ones(D, dtype=np.float32)}])
    doc = _get(client, "d9")
    assert doc["color"] == "green" and doc["price"] == 99.0
    hits = client.search(
        "db", "sp",
        [{"field": "emb", "feature": np.ones(D, dtype=np.float32)}],
        limit=1)
    assert hits[0][0]["_id"] == "d9"


def test_columnar_response_matches_json(client, vecs):
    """Opt-in columnar responses return exactly the JSON path's results
    (ids AND scores) for fields-free searches."""
    q = [{"field": "emb", "feature": vecs[:8]}]
    plain = client.search("db", "sp", q, limit=5, fields=[])
    col = client.search("db", "sp", q, limit=5, fields=[], columnar=True)
    assert len(col) == 8
    for a, b in zip(plain, col):
        assert [r["_id"] for r in a] == [r["_id"] for r in b]
        for ra, rb in zip(a, b):
            assert abs(ra["_score"] - rb["_score"]) < 1e-4

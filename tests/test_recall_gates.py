"""Per-index recall gates against exact ground truth (reference:
test/test_recall_baseline.py:301-303 — recall@100 >= 0.9, @10 >= 0.8,
@1 >= 0.5, gated per index type on real datasets vs an in-process faiss
oracle; this image has zero egress, so the dataset is the same
clustered-Gaussian SIFT-like generator bench.py uses and the oracle is
an exact numpy scan — the gate thresholds are the reference's own)."""

import numpy as np
import pytest

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType, FieldSchema, IndexParams, MetricType, TableSchema,
)

N, D, NQ = 30_000, 64, 64

R_AT_100 = 0.9
R_AT_10 = 0.8
R_AT_1 = 0.5


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    nc = 300
    centers = (rng.standard_normal((nc, D)) * 3).astype(np.float32)
    which = rng.integers(0, nc, N)
    base = centers[which] + 0.7 * rng.standard_normal((N, D)).astype(
        np.float32
    )
    q_idx = rng.choice(N, NQ, replace=False)
    queries = base[q_idx] + 0.1 * rng.standard_normal((NQ, D)).astype(
        np.float32
    )
    # exact L2 ground truth (the oracle): full f64 scan
    d2 = (
        np.sum(queries.astype(np.float64) ** 2, axis=1)[:, None]
        - 2.0 * queries.astype(np.float64) @ base.astype(np.float64).T
        + np.sum(base.astype(np.float64) ** 2, axis=1)[None, :]
    )
    gt = np.argsort(d2, axis=1)[:, :100]
    return base, queries, gt


def build_engine(index_params: IndexParams, base: np.ndarray) -> Engine:
    schema = TableSchema("r", [
        FieldSchema("v", DataType.VECTOR, dimension=D, index=index_params),
    ])
    eng = Engine(schema)
    step = 10_000
    for i in range(0, N, step):
        eng.upsert([{"_id": str(j), "v": base[j]}
                    for j in range(i, i + step)])
    eng.build_index()
    return eng


def recalls(eng: Engine, queries, gt, index_params=None):
    req = SearchRequest(vectors={"v": queries}, k=100, include_fields=[],
                        index_params=index_params or {})
    res = eng.search(req)
    got = [[int(it.key) for it in r.items] for r in res]
    out = {}
    for k in (1, 10, 100):
        out[k] = float(np.mean([
            len(set(got[q][:k]) & set(gt[q][:k].tolist())) / k
            for q in range(len(got))
        ]))
    return out


def assert_gates(r, name):
    assert r[100] >= R_AT_100, f"{name} recall@100 {r[100]:.3f} < {R_AT_100}"
    assert r[10] >= R_AT_10, f"{name} recall@10 {r[10]:.3f} < {R_AT_10}"
    assert r[1] >= R_AT_1, f"{name} recall@1 {r[1]:.3f} < {R_AT_1}"


def test_recall_flat(dataset):
    base, queries, gt = dataset
    eng = build_engine(IndexParams("FLAT", MetricType.L2, {}), base)
    r = recalls(eng, queries, gt)
    # exact index: hold it to far above the generic gates
    assert r[1] >= 0.99 and r[10] >= 0.99, r


def test_recall_ivfflat(dataset):
    base, queries, gt = dataset
    eng = build_engine(IndexParams("IVFFLAT", MetricType.L2, {
        "ncentroids": 128, "nprobe": 24, "train_iters": 6,
        "training_threshold": N,
    }), base)
    assert_gates(recalls(eng, queries, gt), "IVFFLAT")


def test_recall_ivfpq_full_scan(dataset):
    base, queries, gt = dataset
    eng = build_engine(IndexParams("IVFPQ", MetricType.L2, {
        "ncentroids": 128, "nsubvector": 16, "train_iters": 6,
        "training_threshold": N,
    }), base)
    assert_gates(
        recalls(eng, queries, gt, {"rerank": 256}), "IVFPQ/full"
    )


def test_recall_ivfpq_probe_mode(dataset):
    base, queries, gt = dataset
    eng = build_engine(IndexParams("IVFPQ", MetricType.L2, {
        "ncentroids": 128, "nsubvector": 16, "train_iters": 6,
        "training_threshold": N, "scan_mode": "probe", "nprobe": 24,
    }), base)
    assert_gates(
        recalls(eng, queries, gt, {"rerank": 256}), "IVFPQ/probe"
    )


def test_recall_hnsw_surface(dataset):
    base, queries, gt = dataset
    eng = build_engine(IndexParams("HNSW", MetricType.L2, {
        "nlinks": 32, "efSearch": 64, "training_threshold": N,
    }), base)
    assert_gates(recalls(eng, queries, gt), "HNSW")


def test_recall_ivfrabitq(dataset):
    base, queries, gt = dataset
    eng = build_engine(IndexParams("IVFRABITQ", MetricType.L2, {
        "ncentroids": 128, "train_iters": 6, "training_threshold": N,
    }), base)
    assert_gates(
        recalls(eng, queries, gt, {"rerank": 512}), "IVFRABITQ"
    )


def test_recall_binaryivf():
    """Hamming ground truth on packed binary vectors (reference:
    test_vector_index_binary_ivf parity)."""
    rng = np.random.default_rng(11)
    n, dbits, nq = 20_000, 256, 32
    # clustered bits (uniform random bits have no coarse-cluster
    # structure at all — IVF on them degenerates to random bucketing;
    # real binary descriptors cluster, like the reference's datasets)
    nc = 64
    centers = rng.integers(0, 2, (nc, dbits), dtype=np.uint8)
    which = rng.integers(0, nc, n)
    noise = (rng.random((n, dbits)) < 0.10).astype(np.uint8)
    bits = centers[which] ^ noise
    packed = np.packbits(bits, axis=1)
    q_idx = rng.choice(n, nq, replace=False)
    # queries: ground-truth rows with ~8% bit noise
    qbits = bits[q_idx].copy()
    flip = rng.random((nq, dbits)) < 0.08
    qbits ^= flip.astype(np.uint8)
    qpacked = np.packbits(qbits, axis=1)

    # hamming ground truth via xor on unpacked bits
    ham = (qbits[:, None, :] ^ bits[None, :, :]).sum(axis=2)
    gt = np.argsort(ham, axis=1, kind="stable")[:, :100]

    schema = TableSchema("b", [
        FieldSchema("v", DataType.VECTOR, dimension=dbits,
                    index=IndexParams("BINARYIVF", MetricType.L2, {
                        "ncentroids": 64, "nprobe": 16,
                        "training_threshold": n,
                    })),
    ])
    eng = Engine(schema)
    for i in range(0, n, 5000):
        eng.upsert([{"_id": str(j), "v": packed[j]}
                    for j in range(i, i + 5000)])
    eng.build_index()
    req = SearchRequest(vectors={"v": qpacked}, k=100, include_fields=[])
    res = eng.search(req)
    got = [[int(it.key) for it in r.items] for r in res]
    r10 = float(np.mean([
        len(set(got[q][:10]) & set(gt[q][:10].tolist())) / 10
        for q in range(nq)
    ]))
    r1 = float(np.mean([got[q][0] == gt[q][0] for q in range(nq)]))
    assert r10 >= R_AT_10, f"BINARYIVF recall@10 {r10:.3f}"
    assert r1 >= R_AT_1, f"BINARYIVF recall@1 {r1:.3f}"


def test_recall_ivfpq_opq(dataset):
    """OPQ rotation (reference: gamma_index_ivfpq.h opq_ option) meets
    the gates and does not lose recall vs plain PQ on the same data."""
    base, queries, gt = dataset
    params = {
        "ncentroids": 128, "nsubvector": 16, "train_iters": 6,
        "training_threshold": N,
    }
    plain = build_engine(IndexParams("IVFPQ", MetricType.L2, params), base)
    opq = build_engine(
        IndexParams("IVFPQ", MetricType.L2, {**params, "opq": True}), base
    )
    # rerank 128 (not the other tests' 64): this test builds TWO indexes
    # and compares them, doubling its exposure to XLA-CPU thread-order
    # float jitter in k-means; at 64 the gate flaked rarely. The gate
    # still certifies the quantizer (a broken codebook craters the
    # candidate set no matter the rerank depth).
    r_plain = recalls(plain, queries, gt, {"rerank": 128})
    r_opq = recalls(opq, queries, gt, {"rerank": 128})
    assert_gates(r_opq, "IVFPQ/OPQ")
    # OPQ refines the quantizer (measured: mirror MSE 0.2815 vs 0.2905
    # plain at these params) but per-build k-means variance swings
    # recall@10 by a few points either way — compare with slack
    assert r_opq[10] >= r_plain[10] - 0.05, (r_plain, r_opq)

    # dump/load round-trips the rotation
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        opq.dump(tmp)
        eng2 = Engine.open(tmp)
        r2 = recalls(eng2, queries, gt, {"rerank": 128})
        assert abs(r2[10] - r_opq[10]) < 0.05

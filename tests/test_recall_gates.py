"""Per-index recall gates against exact ground truth (reference:
test/test_recall_baseline.py:301-303 — recall@100 >= 0.9, @10 >= 0.8,
@1 >= 0.5, gated per index type on real datasets vs an in-process faiss
oracle; this image has zero egress, so the data comes from
tests/datasets.py: an easy isotropic clustered-Gaussian regime AND a
hard regime — power-law cluster masses, anisotropic covariance, OOD
queries — built to reproduce what makes SIFT/Glove/Nytimes hard. The
gate thresholds are the reference's own, enforced on BOTH regimes."""

import numpy as np
import pytest

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType, FieldSchema, IndexParams, MetricType, TableSchema,
)
from tests.datasets import make_easy, make_gist_like, make_hard

N, D, NQ = 30_000, 64, 64

R_AT_100 = 0.9
R_AT_10 = 0.8
R_AT_1 = 0.5

# {(index_name, regime): {k: recall}} — printed by test_zz_recall_matrix
RESULTS: dict[tuple[str, str], dict[int, float]] = {}


@pytest.fixture(scope="module")
def easy():
    return make_easy(N, D, NQ)


@pytest.fixture(scope="module")
def hard():
    return make_hard(N, D, NQ)


@pytest.fixture(scope="module")
def regimes(easy, hard):
    return {"easy": easy, "hard": hard}


def build_engine(index_params: IndexParams, base: np.ndarray) -> Engine:
    schema = TableSchema("r", [
        FieldSchema("v", DataType.VECTOR, dimension=base.shape[1],
                    index=index_params),
    ])
    eng = Engine(schema)
    step = 10_000
    for i in range(0, len(base), step):
        eng.upsert([{"_id": str(j), "v": base[j]}
                    for j in range(i, min(i + step, len(base)))])
    eng.build_index()
    return eng


def recalls(eng: Engine, queries, gt, index_params=None):
    req = SearchRequest(vectors={"v": queries}, k=100, include_fields=[],
                        index_params=index_params or {})
    res = eng.search(req)
    got = [[int(it.key) for it in r.items] for r in res]
    out = {}
    for k in (1, 10, 100):
        out[k] = float(np.mean([
            len(set(got[q][:k]) & set(gt[q][:k].tolist())) / k
            for q in range(len(got))
        ]))
    return out


def gate(eng, dataset, name, regime, index_params=None):
    base, queries, gt = dataset
    r = recalls(eng, queries, gt, index_params)
    RESULTS[(name, regime)] = r
    assert r[100] >= R_AT_100, \
        f"{name}/{regime} recall@100 {r[100]:.3f} < {R_AT_100}"
    assert r[10] >= R_AT_10, \
        f"{name}/{regime} recall@10 {r[10]:.3f} < {R_AT_10}"
    assert r[1] >= R_AT_1, \
        f"{name}/{regime} recall@1 {r[1]:.3f} < {R_AT_1}"
    return r


REGIMES = ("easy", "hard")


@pytest.mark.parametrize("regime", REGIMES)
def test_recall_flat(regimes, regime):
    base, queries, gt = regimes[regime]
    eng = build_engine(IndexParams("FLAT", MetricType.L2, {}), base)
    r = recalls(eng, queries, gt)
    RESULTS[("FLAT", regime)] = r
    # exact index: hold it to far above the generic gates on BOTH regimes
    assert r[1] >= 0.99 and r[10] >= 0.99, r


@pytest.mark.parametrize("regime", REGIMES)
def test_recall_ivfflat(regimes, regime):
    base, queries, gt = regimes[regime]
    # nprobe 32 (not the easy-set 24): power-law cluster masses put many
    # true neighborhoods in tail cells — the reference runs SIFT1M at
    # comparable probe fractions (nprobe/ncentroids)
    eng = build_engine(IndexParams("IVFFLAT", MetricType.L2, {
        "ncentroids": 128, "nprobe": 32, "train_iters": 6,
        "training_threshold": N,
    }), base)
    gate(eng, regimes[regime], "IVFFLAT", regime)


@pytest.mark.parametrize("regime", REGIMES)
def test_recall_ivfpq_full_scan(regimes, regime):
    base, queries, gt = regimes[regime]
    eng = build_engine(IndexParams("IVFPQ", MetricType.L2, {
        "ncentroids": 128, "nsubvector": 16, "train_iters": 6,
        "training_threshold": N,
    }), base)
    gate(eng, regimes[regime], "IVFPQ/full", regime, {"rerank": 256})


@pytest.mark.parametrize("regime", REGIMES)
def test_recall_ivfpq_probe_mode(regimes, regime):
    base, queries, gt = regimes[regime]
    eng = build_engine(IndexParams("IVFPQ", MetricType.L2, {
        "ncentroids": 128, "nsubvector": 16, "train_iters": 6,
        "training_threshold": N, "scan_mode": "probe", "nprobe": 32,
    }), base)
    gate(eng, regimes[regime], "IVFPQ/probe", regime, {"rerank": 256})


@pytest.mark.parametrize("regime", REGIMES)
def test_recall_hnsw_surface(regimes, regime):
    base, queries, gt = regimes[regime]
    eng = build_engine(IndexParams("HNSW", MetricType.L2, {
        "nlinks": 32, "efSearch": 128, "training_threshold": N,
    }), base)
    gate(eng, regimes[regime], "HNSW", regime)


@pytest.mark.parametrize("regime", REGIMES)
def test_recall_ivfrabitq(regimes, regime):
    base, queries, gt = regimes[regime]
    eng = build_engine(IndexParams("IVFRABITQ", MetricType.L2, {
        "ncentroids": 128, "train_iters": 6, "training_threshold": N,
    }), base)
    gate(eng, regimes[regime], "IVFRABITQ", regime, {"rerank": 768})


@pytest.mark.parametrize("regime", REGIMES)
def test_recall_scann(regimes, regime):
    base, queries, gt = regimes[regime]
    eng = build_engine(IndexParams("SCANN", MetricType.INNER_PRODUCT, {
        "ncentroids": 128, "nsubvector": 16, "train_iters": 6,
        "training_threshold": N, "nprobe": 32,
    }), base)
    # SCANN optimizes inner-product ranking; gate it on MIPS ground
    # truth, not the module's L2 oracle
    q = queries.astype(np.float64)
    ip = q @ base.astype(np.float64).T
    gt_ip = np.argsort(-ip, axis=1)[:, :100]
    gate(eng, (base, queries, gt_ip), "SCANN", regime, {"rerank": 256})


def test_recall_gist_like_ivfpq():
    """d=960 GIST-shaped config (low intrinsic dimension, strongly
    correlated subspaces — the regime where PQ subquantizers are
    stressed; BASELINE.json lists GIST1M as a reference target)."""
    base, queries, gt = make_gist_like()
    eng = build_engine(IndexParams("IVFPQ", MetricType.L2, {
        "ncentroids": 64, "nsubvector": 32, "train_iters": 6,
        "training_threshold": len(base),
    }), base)
    gate(eng, (base, queries, gt), "IVFPQ/gist960", "gist")


def test_recall_binaryivf():
    """Hamming ground truth on packed binary vectors (reference:
    test_vector_index_binary_ivf parity)."""
    rng = np.random.default_rng(11)
    n, dbits, nq = 20_000, 256, 32
    # clustered bits (uniform random bits have no coarse-cluster
    # structure at all — IVF on them degenerates to random bucketing;
    # real binary descriptors cluster, like the reference's datasets)
    nc = 64
    centers = rng.integers(0, 2, (nc, dbits), dtype=np.uint8)
    which = rng.integers(0, nc, n)
    noise = (rng.random((n, dbits)) < 0.10).astype(np.uint8)
    bits = centers[which] ^ noise
    packed = np.packbits(bits, axis=1)
    q_idx = rng.choice(n, nq, replace=False)
    # queries: ground-truth rows with ~8% bit noise
    qbits = bits[q_idx].copy()
    flip = rng.random((nq, dbits)) < 0.08
    qbits ^= flip.astype(np.uint8)
    qpacked = np.packbits(qbits, axis=1)

    # hamming ground truth via xor on unpacked bits
    ham = (qbits[:, None, :] ^ bits[None, :, :]).sum(axis=2)
    gt = np.argsort(ham, axis=1, kind="stable")[:, :100]

    schema = TableSchema("b", [
        FieldSchema("v", DataType.VECTOR, dimension=dbits,
                    index=IndexParams("BINARYIVF", MetricType.L2, {
                        "ncentroids": 64, "nprobe": 16,
                        "training_threshold": n,
                    })),
    ])
    eng = Engine(schema)
    for i in range(0, n, 5000):
        eng.upsert([{"_id": str(j), "v": packed[j]}
                    for j in range(i, i + 5000)])
    eng.build_index()
    req = SearchRequest(vectors={"v": qpacked}, k=100, include_fields=[])
    res = eng.search(req)
    got = [[int(it.key) for it in r.items] for r in res]
    r10 = float(np.mean([
        len(set(got[q][:10]) & set(gt[q][:10].tolist())) / 10
        for q in range(nq)
    ]))
    r1 = float(np.mean([got[q][0] == gt[q][0] for q in range(nq)]))
    RESULTS[("BINARYIVF", "binary")] = {1: r1, 10: r10, 100: float("nan")}
    assert r10 >= R_AT_10, f"BINARYIVF recall@10 {r10:.3f}"
    assert r1 >= R_AT_1, f"BINARYIVF recall@1 {r1:.3f}"


def test_recall_ivfpq_opq(regimes):
    """OPQ rotation (reference: gamma_index_ivfpq.h opq_ option) meets
    the gates and does not lose recall vs plain PQ on the same data."""
    base, queries, gt = regimes["easy"]
    params = {
        "ncentroids": 128, "nsubvector": 16, "train_iters": 6,
        "training_threshold": N,
    }
    plain = build_engine(IndexParams("IVFPQ", MetricType.L2, params), base)
    opq = build_engine(
        IndexParams("IVFPQ", MetricType.L2, {**params, "opq": True}), base
    )
    # rerank 128 (not the other tests' 64): this test builds TWO indexes
    # and compares them, doubling its exposure to XLA-CPU thread-order
    # float jitter in k-means; at 64 the gate flaked rarely. The gate
    # still certifies the quantizer (a broken codebook craters the
    # candidate set no matter the rerank depth).
    r_plain = recalls(plain, queries, gt, {"rerank": 128})
    r_opq = recalls(opq, queries, gt, {"rerank": 128})
    RESULTS[("IVFPQ/OPQ", "easy")] = r_opq
    assert r_opq[100] >= R_AT_100 and r_opq[10] >= R_AT_10 \
        and r_opq[1] >= R_AT_1, r_opq
    # OPQ refines the quantizer (measured: mirror MSE 0.2815 vs 0.2905
    # plain at these params) but per-build k-means variance swings
    # recall@10 by a few points either way — compare with slack
    assert r_opq[10] >= r_plain[10] - 0.05, (r_plain, r_opq)

    # dump/load round-trips the rotation
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        opq.dump(tmp)
        eng2 = Engine.open(tmp)
        r2 = recalls(eng2, queries, gt, {"rerank": 128})
        assert abs(r2[10] - r_opq[10]) < 0.05


def test_zz_recall_matrix():
    """Prints the {index} x {regime} recall matrix accumulated by the
    gates above (run with -s to see it; VERDICT r2 #3 asks for the
    matrix printed per round)."""
    rows = sorted(RESULTS)
    if not rows:
        pytest.skip("gate tests deselected (or running on another "
                    "xdist worker); nothing to report")
    print("\nrecall matrix (r@1 / r@10 / r@100):")
    for name, regime in rows:
        r = RESULTS[(name, regime)]
        print(f"  {name:<14} {regime:<7} "
              f"{r[1]:.3f} / {r[10]:.3f} / {r.get(100, float('nan')):.3f}")


# -- Glove-like COSINE regime (reference gates Glove-100-angular;
#    r4 review missing-6: the gates never ran a cosine regime) --------------

@pytest.fixture(scope="module")
def glove_data():
    from tests.datasets import make_glove_like

    return make_glove_like(N, d=64, nq=NQ)


@pytest.mark.parametrize("index_type,build,sp", [
    # rerank >= 512: recall@100 needs the exact-rerank window to hold
    # well over 100 candidates. This regime caught a real bug: without
    # re-normalizing the PQ approximations for cosine, the IP scan
    # ranked by norm error and r@100 was 0.465 (index/ivf.py
    # _absorb_rows)
    ("IVFPQ", {"ncentroids": 64, "nsubvector": 8},
     {"nprobe": 16, "rerank": 512}),
    # probe mode shares the bug class (publish-path buckets needed the
    # same renormalization as the mirror — review r5)
    ("IVFPQ-probe", {"ncentroids": 64, "nsubvector": 8},
     {"scan_mode": "probe", "nprobe": 24, "rerank": 512}),
    ("IVFFLAT", {"ncentroids": 64}, {"nprobe": 16}),
    ("HNSW", {"nlinks": 32}, {"efSearch": 80}),
    ("FLAT", {}, {}),
])
def test_recall_gates_glove_cosine(glove_data, index_type, build, sp):
    base, queries, gt = glove_data
    params = dict(build)
    params["training_threshold"] = len(base)
    eng = build_engine(
        IndexParams(index_type.split("-")[0], MetricType.COSINE, params),
        base)
    gate(eng, glove_data, index_type, "glove-cosine", sp or None)

"""Hardware-independent perf-regression gates (r6 tentpole).

The only real TPU capture (BENCH_r01) was ~100x off the int8-MXU
roofline and every bench since returned 0 because the tunnel was down —
so every perf property the serving path claims is asserted HERE, on the
CPU backend, the way recall is gated:

- dispatch counts: each search path launches exactly its documented
  number of device programs (ops/perf_model.py DOCUMENTED_DISPATCHES);
- compiled-program stability: warmup pre-traces the configured batch
  buckets, after which repeated same-shape searches add ZERO new
  compiled programs (no silent retrace on the hot path);
- bytes materialized: the block-max path's peak intermediate HBM is a
  small fraction of the XLA full score matrix at serving shapes;
- HBM footprint: the per-index capacity model tracks the real device
  state the index publishes.
"""

import numpy as np
import pytest

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType,
    FieldSchema,
    IndexParams,
    MetricType,
    TableSchema,
)
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops import perf_model

D = 32
N = 3000


def _build(index_type, params, n=N, warmup=None):
    params = dict(params)
    if warmup:
        params["warmup_batches"] = warmup
    schema = TableSchema("t", [
        FieldSchema("group", DataType.INT),
        FieldSchema("emb", DataType.VECTOR, dimension=D,
                    index=IndexParams(index_type, MetricType.L2, params)),
    ])
    eng = Engine(schema)
    rng = np.random.default_rng(33)
    vecs = rng.standard_normal((n, D), dtype=np.float32)
    eng.upsert([
        {"_id": f"d{i:05d}", "group": i % 4, "emb": vecs[i]}
        for i in range(n)
    ])
    eng.build_index()
    eng.wait_for_index()
    return eng, vecs


IVFPQ_PARAMS = {
    "ncentroids": 16, "nsubvector": 8, "train_iters": 4,
    "training_threshold": 256,
    # single-device ledger gates; the mesh path has its own gates in
    # test_mesh_serving.py (conftest forces 8 devices → auto would mesh)
    "mesh_serving": "off",
}


@pytest.fixture(scope="module")
def ivfpq_engine():
    return _build("IVFPQ", IVFPQ_PARAMS, warmup=[8])


def _search(eng, vecs, b=8, index_params=None):
    """One engine search under a fresh PerfLedger; returns the ledger."""
    ledger = perf_model.PerfLedger()
    ivf_ops.set_dispatch_ledger(ledger)
    try:
        eng.search(SearchRequest(
            vectors={"emb": vecs[:b]}, k=10, include_fields=[],
            index_params=index_params or {},
        ))
    finally:
        ivf_ops.set_dispatch_ledger(None)
    return ledger


# -- gate 1: dispatch count per search path ----------------------------------


def test_ivfpq_paths_launch_documented_dispatches(ivfpq_engine):
    eng, vecs = ivfpq_engine
    doc = perf_model.DOCUMENTED_DISPATCHES
    cases = {
        "ivfpq_full_fused": {"scan_mode": "full"},
        "ivfpq_full_unfused": {"scan_mode": "full", "fused_rerank": False},
        "ivfpq_full_pallas": {"scan_mode": "full", "scan_kernel": "pallas"},
        "ivfpq_probe": {"scan_mode": "probe"},
    }
    for path, params in cases.items():
        ledger = _search(eng, vecs, index_params=params)
        assert ledger.tags == doc[path], (
            f"{path}: launched {ledger.tags}, documented {doc[path]} — "
            "a new dispatch on a serving path must bump "
            "DOCUMENTED_DISPATCHES in the same PR"
        )


def test_ivfflat_and_flat_dispatch_counts():
    doc = perf_model.DOCUMENTED_DISPATCHES
    eng, vecs = _build("IVFFLAT", {
        "ncentroids": 16, "train_iters": 4, "training_threshold": 256,
    })
    assert _search(eng, vecs).tags == doc["ivfflat"]
    feng, fvecs = _build("FLAT", {}, n=500)
    assert _search(feng, fvecs).tags == doc["flat"]


def test_ledger_per_search_aggregation(ivfpq_engine):
    eng, vecs = ivfpq_engine
    ledger = perf_model.PerfLedger()
    ivf_ops.set_dispatch_ledger(ledger)
    try:
        for _ in range(3):
            eng.search(SearchRequest(
                vectors={"emb": vecs[:8]}, k=10, include_fields=[],
                index_params={"scan_mode": "full"}))
            ledger.mark_search()
    finally:
        ivf_ops.set_dispatch_ledger(None)
    assert ledger.per_search() == [["fused_scan_rerank"]] * 3
    assert ledger.dispatch_count() == 3
    assert ledger.counts() == {"fused_scan_rerank": 3}


# -- gate 2: compiled-program stability --------------------------------------


def test_warmup_then_zero_new_programs(ivfpq_engine):
    """build_index warmed b=8 (warmup_batches): the serving shapes are
    already traced+compiled, so repeated b=8 searches add ZERO compiled
    programs — the first real query never pays a compile stall."""
    eng, vecs = ivfpq_engine
    _search(eng, vecs, b=8)  # settle any first-use side programs
    before = perf_model.total_compiled_programs()
    for _ in range(3):
        _search(eng, vecs, b=8)
    after = perf_model.total_compiled_programs()
    assert after == before, (
        f"repeated same-shape searches grew the jit cache "
        f"{before} -> {after}: something retraces per request"
    )


def test_compiled_program_counts_cover_registry():
    counts = perf_model.compiled_program_counts()
    # the serving entry points are registered and introspectable
    # (-1 would mean jit internals moved under us)
    for name in ("ivf.int8_scan_rerank", "ivf.ivfpq_candidates",
                 "distance.brute_force_search"):
        assert name in counts
        assert counts[name] >= 0


def test_explicit_warmup_pretraces_new_batch_size(ivfpq_engine):
    eng, vecs = ivfpq_engine
    # warmup quantizes the requested batch to its row bucket (5 -> 8):
    # serving pads the same way, so the traced shape is the served shape
    done = eng.warmup(batches=[5])
    assert done == {"emb": [perf_model.bucket_rows(5)]}
    before = perf_model.total_compiled_programs()
    _search(eng, vecs, b=5)
    assert perf_model.total_compiled_programs() == before


def test_warmed_mixed_k_nprobe_workload_zero_new_programs(ivfpq_engine):
    """The continuous-batching gate: once the declared shape buckets a
    workload can touch are warm, a concurrent mixed-(k, nprobe) request
    stream adds ZERO compiled programs — traffic entropy lands on the
    quantized grid, never on fresh XLA specializations."""
    import threading

    eng, vecs = ivfpq_engine
    # warm the bucket grid the workload can reach: row buckets 8 and 64
    # (32 workers x <=2 rows never exceeds 64), fetch-k tier 16
    # (k in {4, 7, 10}), both nprobe variants
    for b in (8, 64):
        for params in ({}, {"nprobe": 4}):
            _search(eng, vecs, b=b, index_params=params)
    before = perf_model.total_compiled_programs()

    errs = []

    def worker(i):
        rows = 1 + i % 2
        try:
            eng.search(SearchRequest(
                vectors={"emb": vecs[i : i + rows]},
                k=(4, 7, 10)[i % 3], include_fields=[],
                index_params={} if i % 2 else {"nprobe": 4},
            ))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    after = perf_model.total_compiled_programs()
    assert after == before, (
        f"warmed mixed-(k, nprobe) traffic grew the jit cache "
        f"{before} -> {after}: a request shape escaped the bucket grid"
    )


def test_dispatches_bounded_by_bucket_capacity(ivfpq_engine):
    """Same-bucket traffic needs at most ceil(requests / capacity)
    dispatches (perf_model.bucket_dispatch_bound): buckets seal exactly
    at capacity, so 16 single-row requests through an 8-row bucket are
    two full dispatches, never sixteen solos."""
    import threading

    from vearch_tpu.engine.batching import BatchScheduler

    eng, vecs = ivfpq_engine
    # age bound far beyond the test: only FULL buckets may dispatch,
    # making the dispatch count deterministic
    mb = BatchScheduler(eng, max_rows=8, max_delay_ms=3_600_000.0)
    try:
        n = 16
        errs = []

        def worker(i):
            try:
                mb.submit(SearchRequest(
                    vectors={"emb": vecs[i]}, k=10, include_fields=[]))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs
        want = perf_model.bucket_dispatch_bound(n, 8)
        assert want == 2
        assert mb.dispatches == want, (
            f"{n} same-bucket requests took {mb.dispatches} dispatches; "
            f"the perf model allows {want}"
        )
        assert mb.full_dispatches == want
    finally:
        mb.stop()


def test_bucket_model_helpers():
    """The quantization model the scheduler and warmup share."""
    assert [perf_model.bucket_rows(b) for b in (1, 8, 9, 64, 65, 1024)] \
        == [8, 8, 64, 64, 256, 1024]
    assert [perf_model.bucket_fetch_k(k) for k in (3, 16, 17, 1024)] \
        == [16, 16, 64, 1024]
    # out-of-grid sizes pass through unchanged (caller-bounded)
    assert perf_model.bucket_rows(5000) == 5000
    assert perf_model.bucket_fetch_k(5000) == 5000
    assert perf_model.bucket_program_bound() == len(
        perf_model.ROW_BUCKETS) * len(perf_model.FETCH_K_TIERS)
    assert perf_model.bucket_dispatch_bound(17, 8) == 3
    assert perf_model.padding_waste_bytes(3, 8, 32) == 5 * 32 * 4


def test_deadline_and_slowlog_capture_add_zero_device_work(ivfpq_engine):
    """Arming a per-request deadline and the slowlog's forced phase
    capture (trace dict) is pure host-side bookkeeping: the warmed
    serving path must launch the identical dispatch sequence and add
    ZERO compiled programs versus a plain search."""
    import time as _time

    from vearch_tpu.engine.engine import RequestContext

    eng, vecs = ivfpq_engine
    params = {"scan_mode": "full"}
    _search(eng, vecs, b=8, index_params=params)  # settle first-use
    plain = _search(eng, vecs, b=8, index_params=params)
    before = perf_model.total_compiled_programs()

    ledger = perf_model.PerfLedger()
    ivf_ops.set_dispatch_ledger(ledger)
    try:
        eng.search(SearchRequest(
            vectors={"emb": vecs[:8]}, k=10, include_fields=[],
            index_params=params,
            # exactly what the PS arms when a deadline or a slowlog
            # threshold is set: a deadline-bearing context checked
            # between dispatches + a forced trace dict
            trace={},
            ctx=RequestContext("perf-gate",
                               deadline=_time.time() + 60.0),
        ))
    finally:
        ivf_ops.set_dispatch_ledger(None)
    assert ledger.tags == plain.tags, (
        f"armed search launched {ledger.tags} vs plain {plain.tags}: "
        "deadline/slowlog instrumentation reached the device"
    )
    assert perf_model.total_compiled_programs() == before, (
        "deadline/slowlog instrumentation compiled new programs on the "
        "warmed serving path"
    )


# -- gate 2b: cache-hit dispatch gates (docs/PERF.md "Tier 4") ---------------


def test_cached_search_adds_zero_dispatches_and_zero_programs(tmp_path):
    """The serving-cache contract, stated on the device ledger: once a
    query is cached, REPEATING it performs zero engine dispatches and
    compiles zero new programs — and the engine's filter-bitmap cache
    stops re-evaluating an identical filter even on cache-bypassing
    requests."""
    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.standalone import StandaloneCluster
    from vearch_tpu.sdk.client import VearchClient

    d = 16
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1)
    c.start()
    try:
        cl = VearchClient(c.router_addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1,
            "fields": [
                {"name": "group", "data_type": "integer"},
                {"name": "v", "data_type": "vector", "dimension": d,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}},
            ],
        })
        rng = np.random.default_rng(9)
        vecs = rng.standard_normal((200, d)).astype(np.float32)
        cl.upsert("db", "s", [
            {"_id": f"d{i}", "group": i % 4, "v": vecs[i]}
            for i in range(200)
        ])

        def search(**extra):
            return rpc.call(c.router_addr, "POST", "/document/search", {
                "db_name": "db", "space_name": "s",
                "vectors": [{"field": "v", "feature": q.tolist()}
                            for q in vecs[:2]],
                "limit": 5, **extra,
            })

        search()  # cold: compiles, dispatches, populates every tier
        before = perf_model.total_compiled_programs()
        ledger = perf_model.PerfLedger()
        ivf_ops.set_dispatch_ledger(ledger)
        try:
            for _ in range(5):
                search()
        finally:
            ivf_ops.set_dispatch_ledger(None)
        assert ledger.tags == [], (
            f"repeated identical searches reached the device: "
            f"{ledger.tags}"
        )
        assert perf_model.total_compiled_programs() == before, (
            "a cache hit compiled new programs"
        )

        # filter-bitmap tier: identical filters on cache-bypassing
        # requests still dispatch the scan but never re-evaluate the
        # filter against the current data version
        eng = c.ps_nodes[0].engines[next(iter(c.ps_nodes[0].engines))]
        filt = {"operator": "AND", "conditions": [
            {"field": "group", "operator": ">=", "value": 2}]}
        search(filters=filt, cache=False)  # miss: evaluates + caches
        hits0 = eng.filter_cache_hits
        ledger = perf_model.PerfLedger()
        ivf_ops.set_dispatch_ledger(ledger)
        try:
            search(filters=filt, cache=False)
        finally:
            ivf_ops.set_dispatch_ledger(None)
        assert eng.filter_cache_hits == hits0 + 1
        assert ledger.counts() == {"flat_scan": 1}  # bypass DID dispatch
    finally:
        c.stop()


def test_partition_split_adds_zero_dispatches_to_serving(tmp_path):
    """The elasticity contract on the device ledger: an entire online
    partition split is host-side work (engine key scans, doc re-reads,
    child-forward RPCs) — it launches ZERO device dispatches of its
    own, and once the post-split shapes have settled, repeated
    identical searches against the children again dispatch nothing and
    compile nothing (the router cache serves them)."""
    from vearch_tpu.cluster.standalone import StandaloneCluster
    from vearch_tpu.sdk.client import VearchClient

    d = 16
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1)
    c.start()
    try:
        cl = VearchClient(c.router_addr, master_addr=c.master_addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1,
            "fields": [
                {"name": "v", "data_type": "vector", "dimension": d,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}},
            ],
        })
        rng = np.random.default_rng(11)
        vecs = rng.standard_normal((300, d)).astype(np.float32)
        cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                              for i in range(300)])
        parent = cl.get_space("db", "s")["partitions"][0]["id"]
        cl.search("db", "s", [{"field": "v", "feature": vecs[0]}],
                  limit=5)  # warm the pre-split serving path

        # the split itself: zero device work
        ledger = perf_model.PerfLedger()
        ivf_ops.set_dispatch_ledger(ledger)
        try:
            job = cl.split_partition("db", "s", parent, timeout_s=120.0)
            cl.wait_elastic_job(job["job_id"], timeout_s=120.0)
        finally:
            ivf_ops.set_dispatch_ledger(None)
        assert ledger.tags == [], (
            f"partition split reached the device: {ledger.tags}"
        )

        # settle the post-split shapes (children are new engines; the
        # first search may trace), then gate steady state
        cl.search("db", "s", [{"field": "v", "feature": vecs[0]}],
                  limit=5)
        before = perf_model.total_compiled_programs()
        ledger = perf_model.PerfLedger()
        ivf_ops.set_dispatch_ledger(ledger)
        try:
            for _ in range(5):
                cl.search("db", "s",
                          [{"field": "v", "feature": vecs[0]}], limit=5)
        finally:
            ivf_ops.set_dispatch_ledger(None)
        assert ledger.tags == [], (
            f"post-split repeated searches reached the device: "
            f"{ledger.tags}"
        )
        assert perf_model.total_compiled_programs() == before, (
            "post-split warmed searches compiled new programs"
        )
    finally:
        c.stop()


# -- gate 2c: tail-latency paths on the device ledger ------------------------


def test_hedged_and_replica_routed_search_add_zero_device_work(tmp_path):
    """The tail-latency contract on the device ledger: a hedged search
    dispatches exactly the documented count ONCE (the winner's) — the
    cancelled loser dies in its host-side wait and never reaches the
    device — and a replica-routed (least_loaded) search is the same
    documented dispatch sequence as a leader read. Neither compiles a
    new program on the warmed path."""
    import time as _time

    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.standalone import StandaloneCluster
    from vearch_tpu.sdk.client import VearchClient

    d = 16
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=2,
                          router_kwargs={"hedge_quantile": 0.5,
                                         "hedge_budget_pct": 100.0,
                                         "hedge_min_delay_ms": 2.0})
    c.start()
    try:
        cl = VearchClient(c.router_addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1, "replica_num": 2,
            "fields": [
                {"name": "v", "data_type": "vector", "dimension": d,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}},
            ],
        })
        rng = np.random.default_rng(21)
        vecs = rng.standard_normal((100, d)).astype(np.float32)
        cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                              for i in range(100)])

        def search(lb=None):
            q = rng.standard_normal(d).astype(np.float32)
            body = {
                "db_name": "db", "space_name": "s",
                "vectors": [{"field": "v", "feature": q.tolist()}],
                "limit": 5,
            }
            if lb:
                body["load_balance"] = lb
            return rpc.call(c.router_addr, "POST", "/document/search",
                            body)

        # warm the hedge sketch past min-samples AND settle first-use
        # programs on BOTH replicas' engines (leader + not_leader)
        for _ in range(25):
            search()
        for _ in range(3):
            search(lb="not_leader")

        part = cl.get_space("db", "s")["partitions"][0]
        ps = next(p for p in c.ps_nodes if p.node_id == part["leader"])
        # the injected delay is the kill's headroom: the loser must be
        # cancelled before it wakes, and under CPU load the winner's
        # round trip (which triggers the kill) can take hundreds of ms
        # — a tight 500ms window made this a timing flake, not a gate
        rpc.call(ps.addr, "POST", "/ps/engine/config", {
            "partition_id": part["id"],
            "config": {"debug_search_delay_ms": 2500},
        })
        doc = perf_model.DOCUMENTED_DISPATCHES["flat"]
        n = 5
        before = perf_model.total_compiled_programs()
        ledger = perf_model.PerfLedger()
        ivf_ops.set_dispatch_ledger(ledger)
        try:
            for _ in range(n):
                out = search()
                assert out["documents"]
            # an un-cancelled loser would wake from its 2.5s injected
            # wait and dispatch inside this drain window — keep the
            # ledger armed so that bug cannot hide in a detach race
            _time.sleep(3.0)
        finally:
            ivf_ops.set_dispatch_ledger(None)
            rpc.call(ps.addr, "POST", "/ps/engine/config", {
                "partition_id": part["id"],
                "config": {"debug_search_delay_ms": 0},
            })
        stats = rpc.call(c.router_addr, "GET", "/router/stats")
        assert stats["hedges"]["fired"] >= n, stats["hedges"]
        assert ledger.counts() == {t: n * doc.count(t) for t in doc}, (
            f"hedged searches launched {ledger.counts()}, documented "
            f"{doc} x{n} — the cancelled attempt reached the device"
        )
        assert perf_model.total_compiled_programs() == before, (
            "a hedged search compiled new programs on the warmed path"
        )

        # replica-routed read: identical documented dispatch sequence.
        # The aggressive hedge knobs above stay live, so under CPU load
        # a phase-2 search can legitimately hedge (no injected delay —
        # the loser may reach the device before the kill lands): bound
        # the ledger by the hedges that actually fired instead of
        # assuming none do.
        fired0 = stats["hedges"]["fired"]
        ledger = perf_model.PerfLedger()
        ivf_ops.set_dispatch_ledger(ledger)
        try:
            for _ in range(n):
                search(lb="least_loaded")
        finally:
            ivf_ops.set_dispatch_ledger(None)
        fired = rpc.call(c.router_addr, "GET",
                         "/router/stats")["hedges"]["fired"] - fired0
        got = ledger.counts()
        want = {t: n * doc.count(t) for t in doc}
        cap = {t: (n + fired) * doc.count(t) for t in doc}
        assert set(got) == set(want) and all(
            want[t] <= got[t] <= cap[t] for t in want
        ), f"least_loaded searches launched {got}, documented {want} " \
           f"with {fired} hedges fired"
        assert perf_model.total_compiled_programs() == before, (
            "a replica-routed search compiled new programs"
        )
    finally:
        c.stop()


def test_shed_request_does_zero_device_work(tmp_path):
    """Admission shedding happens before the microbatcher and the
    engine: a 429'd request launches zero dispatches and compiles
    nothing — the whole point of shedding at the door."""
    import threading as _threading
    import time as _time

    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.standalone import StandaloneCluster
    from vearch_tpu.sdk.client import VearchClient

    d = 16
    c = StandaloneCluster(
        data_dir=str(tmp_path / "c"), n_ps=1,
        ps_kwargs={"max_concurrent_searches": 1})
    c.start()
    try:
        cl = VearchClient(c.router_addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1,
            "fields": [
                {"name": "v", "data_type": "vector", "dimension": d,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}},
            ],
        })
        rng = np.random.default_rng(22)
        vecs = rng.standard_normal((80, d)).astype(np.float32)
        cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                              for i in range(80)])

        def search():
            q = rng.standard_normal(d).astype(np.float32)
            return rpc.call(c.router_addr, "POST", "/document/search", {
                "db_name": "db", "space_name": "s",
                "vectors": [{"field": "v", "feature": q.tolist()}],
                "limit": 5,
            })

        search()  # settle first-use programs
        ps = c.ps_nodes[0]
        pid = next(iter(ps.engines))
        rpc.call(ps.addr, "POST", "/ps/engine/config", {
            "partition_id": pid,
            "config": {"admission_queue_limit": 1,
                       "debug_search_delay_ms": 2000},
        })
        threads = [_threading.Thread(target=search) for _ in range(2)]
        try:
            for t in threads:
                t.start()
            deadline = _time.monotonic() + 5.0
            while ps._admission.waiting < 1:
                assert _time.monotonic() < deadline
                _time.sleep(0.01)
            # gate holder is pinned in its 2s injected wait and the
            # admission slot is full: the shed below resolves while
            # both are still parked, so the armed ledger can only see
            # the shed request itself
            before = perf_model.total_compiled_programs()
            ledger = perf_model.PerfLedger()
            ivf_ops.set_dispatch_ledger(ledger)
            try:
                with pytest.raises(rpc.RpcError) as ei:
                    search()
            finally:
                ivf_ops.set_dispatch_ledger(None)
            assert ei.value.code == 429
            assert ledger.tags == [], (
                f"a shed request reached the device: {ledger.tags}"
            )
            assert perf_model.total_compiled_programs() == before
        finally:
            for t in threads:
                t.join(timeout=15.0)
            rpc.call(ps.addr, "POST", "/ps/engine/config", {
                "partition_id": pid,
                "config": {"admission_queue_limit": 0,
                           "debug_search_delay_ms": 0},
            })
    finally:
        c.stop()


# -- gate 3: bytes materialized ----------------------------------------------


def test_blockmax_materializes_fraction_of_full_matrix():
    """At the headline serving shape (1M x 128, b=1024, rerank 128) the
    XLA path materializes a 4 GB [B, N] f32 score matrix; the block-max
    path's peak intermediate HBM must stay under 5% of that. This is
    the kernel's reason to exist, stated as a number."""
    b, n_pad, d, r = 1024, 1_000_448, 128, 128
    full = perf_model.scan_peak_bytes(b, n_pad, d, r, "xla_full")
    blockmax = perf_model.scan_peak_bytes(b, n_pad, d, r, "pallas_blockmax")
    assert full == b * n_pad * 4
    assert blockmax < 0.05 * full
    # both paths stream the mirror exactly once
    assert (perf_model.scan_traffic_bytes(b, n_pad, d, "xla_full")
            == perf_model.scan_traffic_bytes(b, n_pad, d,
                                             "pallas_blockmax")
            == n_pad * d)


def test_blockmax_selection_matches_kernel_constants():
    # mirrors ops/ivf.py _select_topk over-selection: nb_sel blocks of
    # 512 rows, and never more blocks than exist
    assert perf_model.blockmax_selected_blocks(128, 1_000_448) == 72
    assert perf_model.blockmax_selected_blocks(128, 2048) == 4
    with pytest.raises(ValueError):
        perf_model.scan_peak_bytes(1, 512, 32, 8, "nope")


# -- gate 4: HBM footprint model ---------------------------------------------


def test_footprint_tracks_published_device_state():
    # fresh engine: nothing probe-published yet, so the probe-state
    # growth below is observable
    eng, vecs = _build("IVFPQ", IVFPQ_PARAMS)
    idx = eng.indexes["emb"]
    store = eng.vector_stores["emb"]
    fp = idx.device_footprint_bytes()
    raw = perf_model.raw_store_footprint_bytes(
        store.capacity, store.dimension, store.store_dtype.itemsize)
    mirror = idx._mirror.device_bytes()
    # the model covers at least the raw rerank buffer + the int8 mirror
    assert fp >= raw + mirror
    assert mirror == perf_model.mirror_footprint_bytes(
        idx._mirror._h8.shape[0], D, "int8")
    # probe publish adds the bucket tensors to the model
    _search(eng, vecs, index_params={"scan_mode": "probe"})
    assert idx.device_footprint_bytes() > fp


def test_flat_footprint_is_store_only():
    eng, _ = _build("FLAT", {}, n=500)
    store = eng.vector_stores["emb"]
    assert eng.indexes["emb"].device_footprint_bytes() == (
        perf_model.raw_store_footprint_bytes(
            store.capacity, store.dimension, store.store_dtype.itemsize))


# -- progressive three-stage refinement (IVFRABITQ) --------------------------

RABITQ_PARAMS = {
    "ncentroids": 16, "train_iters": 4, "training_threshold": 256,
    # single-device ledger gates (the mesh three-stage program has its
    # own documented-dispatch gate in test_mesh_serving.py)
    "mesh_serving": "off",
}


@pytest.fixture(scope="module")
def rabitq_engine():
    return _build("IVFRABITQ", RABITQ_PARAMS, warmup=[8])


def test_three_stage_fused_documented_dispatch(rabitq_engine):
    """The RAM-store three-stage search (binary scan -> int8 rescore ->
    exact rerank) is ONE fused program; stage0=off falls back to the
    documented int8-only fused chain."""
    eng, vecs = rabitq_engine
    doc = perf_model.DOCUMENTED_DISPATCHES
    assert _search(eng, vecs).tags == doc["ivfrabitq_three_stage"]
    assert _search(eng, vecs, index_params={"stage0": "off"}).tags == \
        doc["ivfpq_full_fused"]


def test_three_stage_disk_documented_dispatch(tmp_path):
    """Against a disk store the chain splits exactly once: stages 0-1 on
    device, stage-2 through the host readahead gather — two dispatches,
    never a third."""
    schema = TableSchema("t", [
        FieldSchema("emb", DataType.VECTOR, dimension=D,
                    index=IndexParams("IVFRABITQ", MetricType.L2,
                                      {**RABITQ_PARAMS,
                                       "store_type": "RocksDB"})),
    ])
    eng = Engine(schema, data_dir=str(tmp_path / "d"))
    rng = np.random.default_rng(33)
    vecs = rng.standard_normal((N, D), dtype=np.float32)
    eng.upsert([{"_id": f"d{i:05d}", "emb": vecs[i]} for i in range(N)])
    eng.build_index()
    eng.wait_for_index()
    assert _search(eng, vecs).tags == \
        perf_model.DOCUMENTED_DISPATCHES["ivfrabitq_three_stage_disk"]
    eng.close()


def test_three_stage_warmed_zero_new_programs(rabitq_engine):
    """Warmed three-stage searches — including runtime-tuned r0/r1 once
    their shapes are traced — add ZERO compiled programs."""
    eng, vecs = rabitq_engine
    tuned = {"r0": 512, "r1": 64}
    _search(eng, vecs, b=8)              # settle first-use programs
    _search(eng, vecs, b=8, index_params=tuned)
    before = perf_model.total_compiled_programs()
    for _ in range(3):
        _search(eng, vecs, b=8)
        _search(eng, vecs, b=8, index_params=tuned)
    assert perf_model.total_compiled_programs() == before, (
        "warmed three-stage searches retrace per request")


def test_binary_footprint_model_and_density_gate(rabitq_engine):
    """Acceptance gate: the stage-0 bit planes cost <= 1/8 of the int8
    mirror's row payload for the same capacity, the perf model and the
    live device buffers agree byte-for-byte, and the per-row totals
    (payload + 8B scale/vsq aux) match the documented formulas."""
    eng, _ = rabitq_engine
    idx = eng.indexes["emb"]
    cap = idx._bits._h8.shape[0]
    assert cap == idx._mirror._h8.shape[0]  # tiers grow in lockstep
    # model-level density gate: 8x plane payload within the mirror total
    assert 8 * perf_model.binary_plane_bytes(cap, D) <= \
        perf_model.mirror_footprint_bytes(cap, D)
    # model == ledger == live device buffers (the sampler's ground truth)
    assert idx._bits.device_bytes() == \
        perf_model.binary_footprint_bytes(cap, D)
    planes, scale, vsq = idx._bits.flush()
    live = planes.nbytes + scale.nbytes + vsq.nbytes
    assert live == idx._bits.device_bytes(), (live, idx._bits.device_bytes())
    # and the footprint model exposes the stage-0 tier to the HBM gauges
    assert idx.device_footprint_bytes() >= (
        idx._mirror.device_bytes() + idx._bits.device_bytes())


def test_refine_depth_auto_defaults():
    """refine_depths is the documented auto-tuning: r1 covers the exact
    rerank budget (10k floor 128), r0 gives the int8 stage ~3.2x head
    room, both clamped to the corpus."""
    r0, r1 = perf_model.refine_depths(10, 1_000_000)
    assert r1 == 128 and r0 == 512
    r0, r1 = perf_model.refine_depths(100, 1_000_000)
    assert r1 == 1000 and r0 == 3200
    r0, r1 = perf_model.refine_depths(10, 300)   # tiny corpus clamps r0
    assert r1 == 128 and r0 == 300
    r0, r1 = perf_model.refine_depths(10, 64)    # r1 clamps too
    assert r1 == 64 and r0 == 64
    assert r0 >= r1


# -- roofline ----------------------------------------------------------------


def test_roofline_math_and_chip_table():
    # 1M x 128 int8 scan + 128-row rerank on an assumed v5e
    label, peak = perf_model.peak_int8_ops(None)
    assert "assumed" in label and peak == perf_model.INT8_PEAK_OPS["TPU v5e"]
    label, peak = perf_model.peak_int8_ops("TPU v5 lite chip")
    assert label == "TPU v5 lite" and "assumed" not in label
    q = perf_model.roofline_qps(1_000_000, 128, 394.7e12, rerank_r=128)
    # peak / (2*1e6*128 + 2*128*128) ~= 1.54M QPS
    assert 1.5e6 < q < 1.6e6
    # roofline scales down with N
    assert perf_model.roofline_qps(2_000_000, 128, 394.7e12) < q


# -- gate 5: bytes over PCIe (tiered storage, PERF.md Tier 6) ----------------
#
# Disk-tier searches page bucket slabs HBM<-RAM<-NVMe; the PCIe ledger
# (perf_model.note_h2d_bytes / h2d_bytes_total) records every upload.
# The gates: cold misses move EXACTLY the modeled slab bytes, a warmed
# hot working set launches ZERO H2D bytes and ZERO new compiled
# programs, a repeating probe sequence converges onto pinned or
# prefetch-confirmed slabs, and the tiering machinery never changes
# results (bit-identical with prefetch on or off).


def _build_disk(tmp_path, name, n=8000, nlist=64, **params):
    from vearch_tpu.engine.disk_vector import DiskRawVectorStore
    from vearch_tpu.index.registry import create_index

    # uniform vectors -> near-balanced buckets, so the slab cap (and
    # with it the slot count under a 1 MB budget) is deterministic-ish;
    # recall quality is test_disk_index.py's business, not this file's
    rng = np.random.default_rng(7)
    base = rng.standard_normal((n, D)).astype(np.float32)
    store = DiskRawVectorStore(D, str(tmp_path / name))
    store.add(base)
    p = IndexParams(
        index_type="DISKANN",
        params={"ncentroids": nlist, "nprobe": 8, **params},
    )
    idx = create_index(p, store)
    idx.train(base)
    idx.absorb(store.count)
    return base, idx


def test_tier_cold_misses_move_exactly_modeled_bytes(tmp_path):
    base, idx = _build_disk(tmp_path, "cold", cache_mb=1, ram_mb=8,
                            prefetch=False)
    try:
        q = base[:8]
        b0 = perf_model.h2d_bytes_total()
        idx.search(q, 10, None)
        cache = idx._cache
        st = cache.stats()
        assert st["misses"] > 0
        assert perf_model.h2d_bytes_total() - b0 == (
            perf_model.tier_h2d_bytes(st["misses"], cache.cap, D)
        ), "cold-path H2D must match the slab model byte-for-byte"
        assert st["h2d_bytes"] == perf_model.h2d_bytes_total() - b0
    finally:
        idx.close()


def test_tier_warmed_hot_set_zero_h2d_zero_retrace(tmp_path):
    """THE steady-state gate: once the hot working set is resident and
    pinned, a repeat search launches zero H2D bytes and zero new
    compiled programs — the scan runs entirely from HBM."""
    base, idx = _build_disk(tmp_path, "warm", cache_mb=1, ram_mb=8)
    try:
        q = base[:8]
        for _ in range(12):  # warm + let pins form
            idx.search(q, 10, None)
        idx._prefetcher.drain()
        b0 = perf_model.h2d_bytes_total()
        c0 = perf_model.total_compiled_programs()
        s0, i0 = idx.search(q, 10, None)
        idx._prefetcher.drain()
        assert perf_model.h2d_bytes_total() - b0 == 0, (
            "warmed hot-path search moved bytes over PCIe"
        )
        assert perf_model.total_compiled_programs() - c0 == 0, (
            "warmed hot-path search compiled a new program"
        )
        st = idx._cache.stats()
        assert st["pinned"] > 0  # the hot buckets actually pinned
        # and the warmed path returns exactly the cold-path results
        s1, i1 = idx.search(q, 10, None)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(s0, s1)
    finally:
        idx.close()


def test_tier_repeating_sequence_converges_on_pins_and_prefetch(tmp_path):
    """A repeating probe sequence must converge to >=90% of lookups
    landing on pinned or prefetch-confirmed slabs (the acceptance
    floor): the predictor learns the alternation and the pin set
    absorbs the stable half."""
    base, idx = _build_disk(tmp_path, "conv", n=20000, nlist=64,
                            cache_mb=1, ram_mb=32)
    try:
        qa, qb = base[:4], base[10000:10004]
        for _ in range(30):
            idx.search(qa, 10, None)
            idx._prefetcher.drain()
            idx.search(qb, 10, None)
            idx._prefetcher.drain()
        st = idx._cache.stats()
        lookups = st["hits"] + st["misses"]
        served = st["pin_hits"] + st["prefetch_hits"]
        assert lookups > 0
        assert served / lookups >= 0.9, (
            f"pin+prefetch hit share {served}/{lookups} below 90%"
        )
        pf = idx._prefetcher.stats()
        assert pf["errors"] == 0
    finally:
        idx.close()


def test_tier_prefetch_is_bit_identical(tmp_path):
    base, on = _build_disk(tmp_path, "on", prefetch=True, cache_mb=1)
    _, off = _build_disk(tmp_path, "off", prefetch=False, cache_mb=1)
    try:
        q = base[:16]
        for _ in range(3):
            s_on, i_on = on.search(q, 10, None)
            on._prefetcher.drain()
            s_off, i_off = off.search(q, 10, None)
            np.testing.assert_array_equal(i_on, i_off)
            np.testing.assert_array_equal(s_on, s_off)
    finally:
        on.close()
        off.close()


def test_tier_multipass_matches_single_pass(tmp_path):
    """When the probe set exceeds the HBM slots the search degrades to
    several fixed-shape passes — same ids, same scores, no ValueError
    (the graceful-degradation satellite)."""
    base, small = _build_disk(tmp_path, "mp_small", n=20000, nlist=256,
                              cache_mb=1, prefetch=False)
    _, big = _build_disk(tmp_path, "mp_big", n=20000, nlist=256,
                         cache_mb=512, prefetch=False)
    try:
        q = base[:8]
        p = {"nprobe": 256}
        groups = small._ensure_cache().plan_passes(
            np.arange(256).reshape(1, -1))
        assert len(groups) > 1  # the probe set genuinely overflows
        s_m, i_m = small.search(q, 10, None, p)
        s_1, i_1 = big.search(q, 10, None, p)
        np.testing.assert_array_equal(i_m, i_1)
        np.testing.assert_allclose(s_m, s_1, rtol=1e-5, atol=1e-5)
    finally:
        small.close()
        big.close()

"""Round-6 ADVICE fixes (cluster layer).

- joining an auth-enabled master group carries root credentials on
  POST /members/add (previously an unhandled 401);
- a stale InstallSnapshot ack no longer rewinds next_index below the
  follower's real progress (the follower reports last_index; the leader
  resyncs from there instead of re-sending entries it already has).
"""

import numpy as np
import pytest

from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.raft import RaftNode


def test_join_sends_root_auth(tmp_path, monkeypatch):
    from vearch_tpu.cluster.master import MasterServer

    calls = []

    def fake_call(addr, method, path, body=None, timeout=120.0,
                  auth=None, extra_headers=None):
        calls.append((addr, method, path, body, auth))
        if path == "/members/add":
            return {"members": {"1": addr, "2": "127.0.0.1:9"}}
        return {}

    monkeypatch.setattr(rpc, "call", fake_call)
    ms = MasterServer(
        port=0,
        node_id=2,
        join="127.0.0.1:1",
        meta_dir=str(tmp_path / "meta"),
        persist_path=str(tmp_path / "meta.json"),
        auth=True,
        root_password="pw-join",
        auto_recover=False,
    )
    try:
        ms.start()
    finally:
        ms.stop()
    joins = [c for c in calls if c[2] == "/members/add"]
    assert joins, "joiner never registered with the existing group"
    addr, method, _, body, auth = joins[0]
    assert method == "POST" and body["node_id"] == 2
    # the fix: credentials ride the join — /members/add is NOT in the
    # target's auth-exempt group
    assert auth == ("root", "pw-join")


def _leader(tmp_path, send_fn, snap_index=5):
    return RaftNode(
        pid=1, node_id=1, wal_dir=str(tmp_path / "wal"),
        apply_fn=lambda e: None, send_fn=send_fn,
        members=[1, 2], is_leader=True,
        snapshot_fn=lambda: (b"snapbytes", snap_index),
    )


def test_stale_snapshot_ack_respects_follower_progress(tmp_path):
    """Follower already applied past snap_index (stale:true ack with its
    last_index): next_index must resync from the REPORTED progress, not
    rewind to snap_index+1 and re-send entries the follower has."""
    def send_fn(peer, route, body):
        assert route.endswith("/snapshot")
        return {"success": True, "term": 1, "stale": True, "last_index": 9}

    node = _leader(tmp_path, send_fn)
    assert node._send_snapshot(2, term=0)
    assert node._next[2] == 10
    assert node._match[2] == 9
    node.close()


def test_stale_ack_never_rewinds_below_snapshot(tmp_path):
    # degenerate stale ack (last_index below snap_index — e.g. a
    # follower racing truncation): max() keeps the snapshot horizon
    def send_fn(peer, route, body):
        return {"success": True, "term": 1, "stale": True, "last_index": 3}

    node = _leader(tmp_path, send_fn)
    assert node._send_snapshot(2, term=0)
    assert node._next[2] == 6
    node.close()


def test_fresh_snapshot_ack_unchanged(tmp_path):
    def send_fn(peer, route, body):
        return {"success": True, "term": 1}

    node = _leader(tmp_path, send_fn)
    assert node._send_snapshot(2, term=0)
    assert node._next[2] == 6
    assert node._match[2] == 5
    node.close()

"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding
paths (parallel/) are exercised without TPU hardware.

Must set XLA flags before jax initialises any backend, hence module-level
os.environ mutation in conftest (imported before any test module).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)

"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding
paths (parallel/) are exercised without TPU hardware.

Must set XLA flags before jax initialises any backend, hence module-level
os.environ mutation in conftest (imported before any test module).
"""

import os

# force-override: the environment presets JAX_PLATFORMS=axon (real TPU);
# tests must run on the virtual CPU mesh for speed and sharding coverage
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# the jaxtyping pytest plugin imports jax before this conftest runs, so the
# env var alone is too late — update the live config (backend not yet
# initialised during collection, so this still takes effect)
jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def rng():
    return np.random.default_rng(1234)

"""Native module tests: C++ extension vs pure-python oracles."""

import numpy as np
import pytest

from vearch_tpu import native
from vearch_tpu.cluster.hashing import key_slot


def test_native_builds():
    assert native.available(), "g++ extension failed to build"


def test_murmur3_batch_matches_python():
    keys = ["", "hello", "doc1", "x" * 33, "日本語", "a" * 7]
    got = native.murmur3_batch(keys)
    expect = np.asarray([key_slot(k) for k in keys], dtype=np.uint32)
    np.testing.assert_array_equal(got, expect)
    assert got[1] == 0x248BFA47  # spaolacci/murmur3 vector for "hello"


def test_merge_topk_matches_numpy(rng):
    scores = rng.standard_normal((7, 40)).astype(np.float32)
    ids = rng.integers(0, 10_000, (7, 40)).astype(np.int64)
    s, i = native.merge_topk(scores, ids, 5)
    order = np.argsort(-scores, axis=1)[:, :5]
    np.testing.assert_allclose(s, np.take_along_axis(scores, order, axis=1))
    np.testing.assert_array_equal(i, np.take_along_axis(ids, order, axis=1))
    # ascending (L2 metric orientation)
    s, i = native.merge_topk(scores, ids, 5, descending=False)
    order = np.argsort(scores, axis=1)[:, :5]
    np.testing.assert_allclose(s, np.take_along_axis(scores, order, axis=1))


def test_fvecs_roundtrip(tmp_path, rng):
    data = rng.standard_normal((100, 16)).astype(np.float32)
    path = tmp_path / "t.fvecs"
    with open(path, "wb") as f:
        for row in data:
            np.int32(16).tofile(f)
            row.tofile(f)
    got = native.read_fvecs(str(path))
    np.testing.assert_array_equal(got, data)
    got = native.read_fvecs(str(path), 10)
    np.testing.assert_array_equal(got, data[:10])


def test_fvecs_bad_file(tmp_path):
    path = tmp_path / "bad.fvecs"
    path.write_bytes(b"\xff\xff\xff\xff1234")
    with pytest.raises(Exception):
        native.read_fvecs(str(path))

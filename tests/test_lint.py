"""vearch-lint + lockcheck gate (static-analysis tentpole).

Two halves:

- the package gate: `python -m vearch_tpu.tools.lint vearch_tpu/`
  exits 0 against the checked-in allowlist — project invariants hold
  on every commit, in tier-1;
- planted-violation fixtures: each rule (and the runtime lock-order
  detector) demonstrably FIRES on seeded bad code, so a regression in
  the analyzer itself cannot silently turn the gate into a tautology.
"""

import os
import subprocess
import sys
import threading
import textwrap

import pytest

from vearch_tpu.tools import lockcheck
from vearch_tpu.tools.lint.core import Allowlist, run_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "vearch_tpu")


def _lint_file(tmp_path, rel, source, allowlist=None):
    """Write `source` at tmp_path/rel and lint it; returns unsuppressed
    findings. `rel` matters: path-suffix rules (VL102, VL302) key on it."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings = run_paths([str(path)], allowlist=allowlist)
    return [f for f in findings if not f.suppressed]


def _rules(findings):
    return sorted({f.rule for f in findings})


# -- the real gate -----------------------------------------------------------

def test_package_is_lint_clean():
    """The tree passes its own linter with the checked-in allowlist.
    Run as a subprocess so the exact CI/dev command is what's proven."""
    out = subprocess.run(
        [sys.executable, "-m", "vearch_tpu.tools.lint", PKG],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout


def test_list_rules_names_every_rule():
    out = subprocess.run(
        [sys.executable, "-m", "vearch_tpu.tools.lint", "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0
    for rid in ("VL101", "VL102", "VL103", "VL104", "VL105", "VL201",
                "VL202", "VL203", "VL301", "VL302", "VL401", "VL501",
                "VL502", "VL503", "VL504"):
        assert rid in out.stdout, rid


# -- planted static violations: every rule fires -----------------------------

def test_vl101_hidden_dispatch_fires(tmp_path):
    found = _lint_file(tmp_path, "cluster/sneaky.py", """\
        import jax

        def warm(fn):
            return jax.jit(fn)
        """)
    assert _rules(found) == ["VL101"]


def test_vl101_silent_in_device_layers(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/ops/fine.py", """\
        import jax

        def warm(fn):
            return jax.jit(fn)
        """)
    assert found == []


def test_vl101_parallel_is_sanctioned_dispatch_layer(tmp_path):
    """The mesh data plane (parallel/) mints shard_map programs and
    jitted tail-append writers as a first-class dispatch layer."""
    found = _lint_file(tmp_path, "vearch_tpu/parallel/fine.py", """\
        import jax
        from jax.experimental.shard_map import shard_map

        def build(mesh, specs, body):
            return jax.jit(shard_map(body, mesh=mesh, in_specs=specs,
                                     out_specs=specs[0]))
        """)
    assert found == []


def test_vl101_shard_map_outside_dispatch_layers_fires(tmp_path):
    """shard_map is a dispatch construct: the cluster plane minting one
    directly (instead of calling parallel/) still trips VL101."""
    found = _lint_file(tmp_path, "cluster/rogue_mesh.py", """\
        from jax.experimental.shard_map import shard_map

        def scan(mesh, specs, body):
            return shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs[0])
        """)
    assert _rules(found) == ["VL101"]


def test_vl102_host_sync_in_serving_path_fires(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        import numpy as np

        class PSServer:
            def _h_search(self, body):
                q = np.asarray(body["vectors"])
                return q

            def _h_other(self, body):
                return np.asarray(body)  # not a serving-path function
        """)
    # the lexical rule fires, and since ISSUE 20 the interprocedural
    # VL502 sees the same site (PSServer._h_search is a search entry)
    assert _rules(found) == ["VL102", "VL502"]
    assert {f.line for f in found} == {5}


def test_vl102_inline_allow_suppresses(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        import numpy as np

        class PSServer:
            def _h_search(self, body):
                q = np.asarray(body["vectors"])  # lint: allow[host-sync] wire payload normalization
                return q
        """)
    assert found == []


def test_vl103_redeclared_tier_literal_fires(tmp_path):
    """Serving code minting its own shape grid (instead of importing the
    perf model's) breaks the zero-retrace bound silently — VL103."""
    found = _lint_file(tmp_path, "vearch_tpu/engine/rogue.py", """\
        MY_ROW_BUCKETS = (8, 32)

        def pick(n):
            return min(b for b in MY_ROW_BUCKETS if b >= n)
        """)
    assert _rules(found) == ["VL103"]
    assert "re-declare" in found[0].message


def test_vl103_import_and_inline_allow_pass(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/engine/fine.py", """\
        from vearch_tpu.ops import perf_model

        def pick(n):
            return perf_model.bucket_rows(n)

        HIST_BUCKETS = (1, 2, 4)  # lint: allow[bucket-drift] histogram bounds, not dispatch shapes
        """)
    assert found == []


def test_vl103_canonical_grid_must_match_policy_pin(tmp_path):
    """The perf model's own declaration is checked against the lint
    policy pin: a grid change must be a conscious two-file edit."""
    found = _lint_file(tmp_path, "vearch_tpu/ops/perf_model.py", """\
        ROW_BUCKETS = (8, 64, 256, 2048)
        FETCH_K_TIERS = (16, 64, 256, 1024)
        """)
    assert _rules(found) == ["VL103"]
    assert "policy" in found[0].message
    # matching grids are clean
    found = _lint_file(tmp_path, "vearch_tpu/ops/perf_model.py", """\
        ROW_BUCKETS = (8, 64, 256, 1024)
        FETCH_K_TIERS = (16, 64, 256, 1024)
        """)
    assert found == []
    # a missing declaration is as bad as a drifted one
    found = _lint_file(tmp_path, "vearch_tpu/ops/perf_model.py", """\
        ROW_BUCKETS = (8, 64, 256, 1024)
        """)
    assert _rules(found) == ["VL103"]
    assert "FETCH_K_TIERS" in found[0].message


def test_vl104_unattributed_billable_counter_fires(tmp_path):
    """A serving-path kill/shed counter incremented without naming the
    space un-attributes that failure class — VL104."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def shed(self, lbl):
                self._shed_total.inc("search")
                self._killed_total.inc("deadline", lbl)
        """)
    assert _rules(found) == ["VL104"]
    assert sorted(f.line for f in found) == [3, 4]
    assert "space" in found[0].message


def test_vl104_space_argument_passes(tmp_path):
    """Any space-shaped argument — a `space_lbl` local, a
    `_space_key()` call, a system-space constant — attributes the
    increment."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def shed(self, pid):
                space_lbl = self._acct.label(self._space_key(pid))
                self._shed_total.inc("search", space_lbl)
                self._killed_total.inc("deadline",
                                       self._space_key(pid))
                self._killed_total.inc("operator", SYSTEM_SPACE)
        """)
    assert found == []


def test_vl104_inline_allow_and_other_files_pass(tmp_path):
    """Genuinely tenant-free increments waive with a reason; the same
    code outside the serving files is out of scope."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        class Router:
            def warm(self):
                self._shed_total.inc(  # lint: allow[space-attr] zero-fill label registration
                    "search", "other", by=0.0)
        """)
    assert found == []
    found = _lint_file(tmp_path, "vearch_tpu/cluster/master.py", """\
        class Master:
            def note(self):
                self._shed_total.inc("search")
        """)
    assert found == []


def test_vl105_index_mutation_without_staleness_hook_fires(tmp_path):
    """A PS path that rebuilds an index without telling the quality
    monitor leaves shadow recall scoring fresh truth against the old
    serving snapshot — VL105."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def _run_build(self, pid, rebuild):
                eng = self.engines[pid]
                if rebuild:
                    eng.rebuild_index()
                else:
                    eng.build_index()
        """)
    assert _rules(found) == ["VL105"]
    assert len(found) == 1
    assert "note_index_mutation" in found[0].message


def test_vl105_hook_call_satisfies(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def _run_build(self, pid):
                self.engines[pid].build_index()
                self._quality.note_index_mutation(pid, "db/s", op="build")
        """)
    assert found == []


def test_vl105_other_files_out_of_scope_and_allow_waives(tmp_path):
    """The engine owns rebuild paths, so engine.py is IN scope (the
    config lists it next to ps.py); files outside the listed owners are
    not, and a justified def-line pragma waives in scope."""
    found = _lint_file(tmp_path, "vearch_tpu/engine/engine.py", """\
        class Engine:
            def absorb(self):
                self.index.build_index()
        """)
    assert _rules(found) == ["VL105"]
    found = _lint_file(tmp_path, "vearch_tpu/bench/warm.py", """\
        class Warmer:
            def absorb(self, eng):
                eng.build_index()
        """)
    assert found == []
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def _warm(self, eng):  # lint: allow[quality-staleness] offline warmup engine, never serves
                eng.build_index()
        """)
    assert found == []


def test_vl201_unguarded_mutation_fires(tmp_path):
    found = _lint_file(tmp_path, "store.py", """\
        import threading

        class Store:
            _guarded_by = {"items": "_lock", "count": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self.items = {}
                self.count = 0  # __init__ is exempt

            def good(self, k, v):
                with self._lock:
                    self.items[k] = v
                    self.count += 1

            def bad(self, k):
                self.items.pop(k, None)
                self.count -= 1
        """)
    assert _rules(found) == ["VL201"]
    assert len(found) == 2  # .pop() and the augmented assignment


def test_vl201_holds_pragma_trusted(tmp_path):
    found = _lint_file(tmp_path, "store.py", """\
        import threading

        class Store:
            _guarded_by = {"count": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump_locked(self):  # lint: holds[_lock]
                self.count += 1
        """)
    assert found == []


def test_vl202_anonymous_thread_fires(tmp_path):
    found = _lint_file(tmp_path, "bg.py", """\
        import threading

        def go(fn):
            threading.Thread(target=fn, daemon=True).start()
            threading.Thread(target=fn, daemon=True, name="ok").start()
        """)
    assert _rules(found) == ["VL202"]
    assert len(found) == 1 and found[0].line == 4


def test_vl203_wall_clock_fires_and_monotonic_passes(tmp_path):
    found = _lint_file(tmp_path, "timing.py", """\
        import time
        import time as _time

        def latency():
            t0 = time.time()
            t1 = _time.time()
            t2 = time.monotonic()
            return t0, t1, t2
        """)
    assert _rules(found) == ["VL203"]
    assert sorted(f.line for f in found) == [5, 6]


def test_vl301_bare_except_fires(tmp_path):
    found = _lint_file(tmp_path, "anything.py", """\
        def f():
            try:
                return 1
            except:
                return None
        """)
    assert _rules(found) == ["VL301"]


def test_vl302_swallowed_except_in_raft_fires(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/raft.py", """\
        def apply(entries, log):
            for e in entries:
                try:
                    e()
                except Exception:
                    pass
                try:
                    e()
                except Exception as exc:  # visible: logged
                    log.warning("apply failed: %s", exc)
        """)
    assert _rules(found) == ["VL302"]
    assert len(found) == 1 and found[0].line == 5


def test_vl302_only_in_critical_modules(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        def f(e):
            try:
                e()
            except Exception:
                pass
        """)
    assert found == []


def test_reasonless_pragma_is_itself_a_finding(tmp_path):
    found = _lint_file(tmp_path, "timing.py", """\
        import time

        def f():
            return time.time()  # lint: allow[wall-clock]
        """)
    # the naked pragma suppresses the site but fails the gate itself:
    # VL000 is unsuppressable, so a reasonless waiver still exits 1
    assert _rules(found) == ["VL000"]
    assert "no reason" in found[0].message


def test_allowlist_suppresses_and_unused_entries_fail(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "VL203 cluster/timing.py fixture proves file-scoped suppression\n"
        "VL101 cluster/gone.py this entry matches nothing\n"
    )
    found = _lint_file(tmp_path, "cluster/timing.py", """\
        import time

        def f():
            return time.time()
        """, allowlist=Allowlist(str(allow)))
    # VL203 suppressed by the first entry; the dead entry is a VL000
    assert _rules(found) == ["VL000"]
    assert "unused allowlist entry" in found[0].message


# -- runtime lockcheck: the dynamic half -------------------------------------

@pytest.fixture
def lockcheck_on():
    lockcheck.reset()
    lockcheck.enable()
    yield
    lockcheck.reset()


def test_make_lock_is_plain_when_disabled():
    lockcheck.reset()
    lockcheck.disable()
    try:
        lk = lockcheck.make_lock("x")
        assert not isinstance(lk, lockcheck.DebugLock)
    finally:
        lockcheck.reset()


def test_lock_order_inversion_detected(lockcheck_on):
    a = lockcheck.make_lock("fixture.a")
    b = lockcheck.make_lock("fixture.b")
    with a:
        with b:
            pass
    # reverse order on another thread — no deadlock is ever hit, the
    # edge graph alone proves the interleaving exists
    def reverse():
        with b:
            with a:
                pass
    t = threading.Thread(target=reverse, daemon=True, name="fixture-rev")
    t.start()
    t.join(timeout=10)
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert "lock-order-inversion" in kinds
    with pytest.raises(AssertionError, match="lock-order-inversion"):
        lockcheck.check()


def test_consistent_order_is_clean(lockcheck_on):
    a = lockcheck.make_lock("fixture.c")
    b = lockcheck.make_lock("fixture.d")
    for _ in range(3):
        with a:
            with b:
                pass
    lockcheck.check()
    assert (("fixture.c", "fixture.d")
            in lockcheck.acquisition_edges())


def test_unguarded_write_detected(lockcheck_on):
    @lockcheck.guarded
    class Store:
        _guarded_by = {"count": "_lock"}

        def __init__(self):
            self._lock = lockcheck.make_lock("fixture.store")
            self.count = 0  # construction is exempt

    s = Store()
    with s._lock:
        s.count = 1  # guarded: fine
    s.count = 2  # seeded violation
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert kinds == ["unguarded-write"]
    assert "Store.count" in lockcheck.violations()[0]["detail"]


def test_non_reentrant_reacquire_detected(lockcheck_on):
    lk = lockcheck.make_lock("fixture.plain", reentrant=False)
    with lk:
        with lk:  # a real Lock would deadlock right here
            pass
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert "self-deadlock" in kinds


def test_foreign_release_detected(lockcheck_on):
    lk = lockcheck.make_lock("fixture.foreign", reentrant=True)
    lk.acquire()
    err: list[Exception] = []

    def releaser():
        try:
            lk.release()
        except Exception as e:  # RLock may refuse; the record is the point
            err.append(e)

    t = threading.Thread(target=releaser, daemon=True,
                         name="fixture-foreign")
    t.start()
    t.join(timeout=10)
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert "foreign-release" in kinds


def test_condition_integration_keeps_held_stack_honest(lockcheck_on):
    lk = lockcheck.make_lock("fixture.cv", reentrant=True)
    cv = threading.Condition(lk)
    ready = threading.Event()

    def waiter():
        with cv:
            ready.set()
            cv.wait(timeout=10)
            # back under the lock after wait: guarded writes here must
            # see the lock as held
            assert lk.held_by_current()

    t = threading.Thread(target=waiter, daemon=True, name="fixture-cv")
    t.start()
    assert ready.wait(timeout=10)
    with cv:
        cv.notify_all()
    t.join(timeout=10)
    lockcheck.check()


# -- interprocedural rules (VL501-504): planted fixtures ---------------------
#
# Fixture files live under a fake vearch_tpu/ tree so the entry-point
# policy (path-suffix + qualname) matches them exactly like the real
# package; each fixture is linted ALONE, so the whole-program analysis
# is the fixture's own call graph.

def test_vl501_laundered_dispatch_fires_through_two_hops(tmp_path):
    """An inline allow[dispatch] waiver silences VL101 at the site, but
    the site is reachable from a search handler two hops up — VL501
    reports it with the full chain."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        import jax

        class RouterServer:
            def _h_search(self, body, parts):
                return self._route(body)

            def _route(self, body):
                return _prep(body)

        def _prep(body):
            fn = jax.jit(lambda x: x)  # lint: allow[dispatch] offline tooling claim
            return fn(body)
        """)
    assert "VL501" in _rules(found), found
    assert "VL101" not in _rules(found)  # the lexical waiver held
    msg = next(f for f in found if f.rule == "VL501").message
    assert "_h_search" in msg and "_route" in msg and "_prep" in msg


def test_vl502_blocking_open_three_frames_deep(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        class RouterServer:
            def _h_search(self, body, parts):
                return self._impl(body)

            def _impl(self, body):
                return _load(body)

        def _load(body):
            with open("/tmp/x") as f:
                return f.read()
        """)
    assert "VL502" in _rules(found), found
    msg = next(f for f in found if f.rule == "VL502").message
    assert "_h_search" in msg and "_impl" in msg and "_load" in msg


def test_vl502_pragma_at_offending_frame_suppresses(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        class RouterServer:
            def _h_search(self, body, parts):
                return _load(body)

        def _load(body):
            # lint: allow[serving-blocking] fixture: startup-only manifest read
            with open("/tmp/x") as f:
                return f.read()
        """)
    assert "VL502" not in _rules(found), found


def test_vl502_pragma_at_entry_does_not_launder(tmp_path):
    """The justification must sit at the offending frame; waiving the
    entry point does nothing for a callee's blocking call."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        class RouterServer:
            def _h_search(self, body, parts):  # lint: allow[serving-blocking] fixture: wrong frame
                return _load(body)

        def _load(body):
            with open("/tmp/x") as f:
                return f.read()
        """)
    assert "VL502" in _rules(found), found


def test_vl503_constructed_lock_cycle_fires(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/fixlocks.py", """\
        from vearch_tpu.tools import lockcheck

        _a = lockcheck.make_lock("fix.a")
        _b = lockcheck.make_lock("fix.b")

        def one():
            with _a:
                with _b:
                    pass

        def two():
            with _b:
                with _a:
                    pass
        """)
    assert "VL503" in _rules(found), found
    msg = next(f for f in found if f.rule == "VL503").message
    assert "fixlocks:_a" in msg and "fixlocks:_b" in msg


def test_vl503_consistent_order_is_clean(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/fixlocks.py", """\
        from vearch_tpu.tools import lockcheck

        _a = lockcheck.make_lock("fix.a")
        _b = lockcheck.make_lock("fix.b")

        def one():
            with _a:
                with _b:
                    pass

        def two():
            with _a:
                with _b:
                    pass
        """)
    assert "VL503" not in _rules(found), found


def test_vl503_transitive_acquire_completes_cycle(tmp_path):
    """a->b direct in one function, b->a only THROUGH a callee that
    takes _a while _b is held: the fixpoint closes the cycle."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/fixlocks.py", """\
        from vearch_tpu.tools import lockcheck

        _a = lockcheck.make_lock("fix.a")
        _b = lockcheck.make_lock("fix.b")

        def one():
            with _a:
                with _b:
                    pass

        def _inner():
            with _a:
                pass

        def two():
            with _b:
                _inner()
        """)
    assert "VL503" in _rules(found), found


def test_vl504_dropped_deadline_rpc_fires(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        from vearch_tpu.cluster import rpc

        class RouterServer:
            def _h_search(self, body, parts):
                return self._scatter(body)

            def _scatter(self, body):
                return rpc.call("addr", "POST", "/ps/doc/search", body)
        """)
    assert "VL504" in _rules(found), found
    msg = next(f for f in found if f.rule == "VL504").message
    assert "_h_search" in msg and "_scatter" in msg


def test_vl504_timeout_kwarg_or_body_deadline_satisfies(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        from vearch_tpu.cluster import rpc

        class RouterServer:
            def _h_search(self, body, parts):
                rpc.call("addr", "POST", "/p", body, timeout=1.0)
                return rpc.call("addr", "POST", "/p",
                                {"deadline_ms": body.get("deadline_ms")})
        """)
    assert "VL504" not in _rules(found), found


def test_vl50x_out_of_reach_code_is_silent(tmp_path):
    """The same blocking call in a function NO entry point reaches
    produces no interprocedural finding (VL101 still applies lexically
    to dispatch, but offline open()/rpc are fine)."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/offline.py", """\
        from vearch_tpu.cluster import rpc

        def backup_tool(path):
            with open(path) as f:
                return rpc.call("addr", "POST", "/admin", f.read())
        """)
    assert not {"VL501", "VL502", "VL504"} & set(_rules(found)), found


# -- callgraph unit coverage -------------------------------------------------

def _analysis(tmp_path, files):
    from vearch_tpu.tools.lint import callgraph
    from vearch_tpu.tools.lint.core import FileContext

    ctxs = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        ctxs.append(FileContext(str(p), p.read_text()))
    return callgraph.build(ctxs)


def test_callgraph_resolves_self_methods_and_nested_defs(tmp_path):
    a = _analysis(tmp_path, {"vearch_tpu/cluster/router.py": """\
        class RouterServer:
            def _h_search(self, body, parts):
                def timed():
                    return self._deep(body)
                return timed()

            def _deep(self, body):
                return body
        """})
    reach = {q.split(":", 1)[1] for q in a.reachable("search")}
    assert "RouterServer._h_search.timed" in reach  # closure rule
    assert "RouterServer._deep" in reach            # self.m() via MRO


def test_callgraph_resolves_cross_module_imports(tmp_path):
    a = _analysis(tmp_path, {
        "vearch_tpu/cluster/router.py": """\
            from vearch_tpu.cluster import helpers
            from vearch_tpu.cluster.helpers import direct

            class RouterServer:
                def _h_search(self, body, parts):
                    helpers.work(body)
                    return direct(body)
            """,
        "vearch_tpu/cluster/helpers.py": """\
            def work(body):
                return body

            def direct(body):
                return body
            """,
    })
    reach = {q.split(":", 1)[1] for q in a.reachable("search")}
    assert "work" in reach and "direct" in reach


def test_callgraph_stoplisted_names_land_in_unresolved_bucket(tmp_path):
    a = _analysis(tmp_path, {"vearch_tpu/cluster/router.py": """\
        class RouterServer:
            def _h_search(self, body, parts):
                handle = body["h"]
                return handle.get("x")
        """})
    fn = next(f for f in a.funcs.values()
              if f.qualname == "RouterServer._h_search")
    kinds = {r.kind for r in fn.calls}
    assert "fanout" not in kinds  # "get" is stoplisted
    assert any(r.kind == "dynamic" and (r.dotted or "").endswith(".get")
               for r in fn.calls)


def test_lock_graph_artifact_coverage_semantics(tmp_path):
    """Wildcard/prefix lock nodes cover runtime names: the f-string
    mint `ps.flush{pid}` must cover a runtime `ps.flush3` edge."""
    from vearch_tpu.tools.lint import callgraph

    a = _analysis(tmp_path, {"vearch_tpu/cluster/fix.py": """\
        from vearch_tpu.tools import lockcheck

        class PS:
            def __init__(self):
                self._lock = lockcheck.make_lock("fix.ps._lock")
                self._fl = {}

            def _flush_lock(self, pid):
                with self._lock:
                    return self._fl.setdefault(
                        pid, lockcheck.make_lock(f"fix.ps.flush{pid}"))

            def flush(self, pid):
                with self._flush_lock(pid):
                    with self._lock:
                        pass
        """})
    art = a.lock_graph_artifact()
    assert art["cycles"] == []
    assert callgraph.edge_covered(art, "fix.ps.flush3", "fix.ps._lock")
    assert not callgraph.edge_covered(art, "fix.ps._lock", "other.lock")


def test_callgraph_types_locals_from_return_annotation(tmp_path):
    """`node = self._node(pid)` with `_node -> RaftNode` types the
    local; `self.nodes: dict[int, RaftNode]` types subscripted reads.
    Both make the lock the callee takes order under the holder."""
    from vearch_tpu.tools.lint import callgraph

    a = _analysis(tmp_path, {"vearch_tpu/cluster/fixann.py": """\
        from vearch_tpu.tools import lockcheck

        class RaftNode:
            def __init__(self):
                self._apply_lock = lockcheck.make_lock("fixann.apply")

            def save(self):
                with self._apply_lock:
                    pass

        class PS:
            def __init__(self):
                self._flush = lockcheck.make_lock("fixann.flush")
                self.nodes: dict[int, RaftNode] = {}

            def _node(self, pid) -> RaftNode:
                return self.nodes[pid]

            def flush(self, pid):
                node = self._node(pid)
                with self._flush:
                    node.save()
                    self.nodes[pid].save()
        """})
    fn = next(f for f in a.funcs.values() if f.qualname == "PS.flush")
    saves = [r for r in fn.calls
             if any(t.endswith("RaftNode.save") for t in r.targets)]
    assert len(saves) == 2
    assert all(r.kind == "resolved" for r in saves)
    art = a.lock_graph_artifact()
    assert callgraph.edge_covered(art, "fixann.flush", "fixann.apply")


def test_callgraph_ctor_injected_callback_orders_at_invocation(tmp_path):
    """Raft's apply_fn pattern: a lambda bound through the constructor
    resolves at the dynamic `self.apply_fn(...)` site, so the lock the
    callback takes orders under what the INVOKER holds there."""
    from vearch_tpu.tools.lint import callgraph

    a = _analysis(tmp_path, {"vearch_tpu/cluster/fixcb.py": """\
        from vearch_tpu.tools import lockcheck

        class Node:
            def __init__(self, apply_fn):
                self._lock = lockcheck.make_lock("fixcb.node")
                self.apply_fn = apply_fn

            def commit(self, op):
                with self._lock:
                    self.apply_fn(op)

        class PS:
            def __init__(self):
                self._stats = lockcheck.make_lock("fixcb.stats")
                self.node = Node(apply_fn=lambda op: self._apply(op))

            def _apply(self, op):
                with self._stats:
                    return op
        """})
    art = a.lock_graph_artifact()
    assert art["cycles"] == []
    assert callgraph.edge_covered(art, "fixcb.node", "fixcb.stats")
    fn = next(f for f in a.funcs.values() if f.qualname == "Node.commit")
    assert any(r.kind == "callback" for r in fn.calls)


def test_callgraph_param_callback_through_factory(tmp_path):
    """The hbm fetch pattern: a factory-made closure passed as an
    argument binds to the callee's param, so the closure's transitive
    locks order under what the callee holds at the `fetch(...)` site."""
    from vearch_tpu.tools.lint import callgraph

    a = _analysis(tmp_path, {"vearch_tpu/index/fixpc.py": """\
        from vearch_tpu.tools import lockcheck

        class Cache:
            def __init__(self):
                self._lock = lockcheck.make_lock("fixpc.cache")

            def resolve(self, fetch):
                with self._lock:
                    return fetch(1)

        class Tier:
            def __init__(self):
                self._lock = lockcheck.make_lock("fixpc.tier")

            def get(self, b):
                with self._lock:
                    return b

        class Index:
            def __init__(self):
                self.cache = Cache()
                self.tier = Tier()

            def _make_fetch(self):
                def fetch(b):
                    return self.tier.get(b)
                return fetch

            def lookup(self):
                fetch = self._make_fetch()
                return self.cache.resolve(fetch)
        """})
    art = a.lock_graph_artifact()
    assert art["cycles"] == []
    assert callgraph.edge_covered(art, "fixpc.cache", "fixpc.tier")


def test_callback_binding_does_not_launder_reachability(tmp_path):
    """Param/attr callback bindings are a global union; reachability
    must stay with the call-site deferred edges or one entry's
    callbacks would surface on another entry's serving path."""
    a = _analysis(tmp_path, {"vearch_tpu/cluster/router.py": """\
        class Retrier:
            def __init__(self):
                self.op = None

            def run(self, op):
                self.op = op
                return self.op()

        class RouterServer:
            def _h_search(self, body, parts):
                r = Retrier()
                return r.run(self._search_impl)

            def _h_upsert(self, body, parts):
                r = Retrier()
                return r.run(self._upsert_impl)

            def _search_impl(self):
                return 1

            def _upsert_impl(self):
                return 2
        """})
    search = {q.split(":", 1)[1] for q in a.reachable("search")}
    assert "RouterServer._search_impl" in search   # call-site deferred
    assert "RouterServer._upsert_impl" not in search  # no laundering


def test_doctor_lint_clean_standing_check():
    """The doctor's lint_clean invariant: in-process full-suite run is
    green over this tree and memoized for repeat doctor calls."""
    from vearch_tpu.obs import doctor

    ok, detail = doctor._check_lint_clean()
    assert ok is True, detail
    assert "0 hard finding" in detail
    assert doctor._check_lint_clean() == (ok, detail)  # memoized


# -- perf + CLI gates --------------------------------------------------------

def test_whole_package_single_parse_under_budget(monkeypatch):
    """ISSUE 20 perf gate: one lint pass over the package parses each
    file exactly once (the interprocedural analysis shares the lexical
    rules' contexts) and finishes well under the 30s budget."""
    import time as _time

    from vearch_tpu.tools.lint import core

    parses = []
    real = core.FileContext

    class Counting(real):
        def __init__(self, path, source):
            parses.append(path)
            super().__init__(path, source)

    monkeypatch.setattr(core, "FileContext", Counting)
    t0 = _time.monotonic()
    run_paths([PKG])
    elapsed = _time.monotonic() - t0
    assert elapsed < 30.0, f"package lint took {elapsed:.1f}s"
    assert parses, "no files parsed?"
    dup = {p for p in parses if parses.count(p) > 1}
    assert not dup, f"files parsed more than once: {sorted(dup)[:5]}"


def test_cli_json_and_lock_graph_modes():
    import json as _json

    out = subprocess.run(
        [sys.executable, "-m", "vearch_tpu.tools.lint", PKG, "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = _json.loads(out.stdout)
    assert doc["hard"] == 0 and doc["findings"] == []

    out = subprocess.run(
        [sys.executable, "-m", "vearch_tpu.tools.lint", PKG,
         "--lock-graph"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    art = _json.loads(out.stdout)
    assert art["cycles"] == []
    assert art["edges"], "the real tree has known lock nestings"
    ids = {n["id"] for n in art["nodes"]}
    for e in art["edges"]:
        assert e["first"] in ids and e["then"] in ids


def test_cli_changed_only_filters_to_diffed_files():
    """--changed-only HEAD with a clean tree reports nothing; against
    the empty tree it reports the same totals as a full run. Use a
    bogus ref to exercise the error path deterministically."""
    out = subprocess.run(
        [sys.executable, "-m", "vearch_tpu.tools.lint", PKG,
         "--changed-only", "no-such-ref-xyzzy"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 2
    assert "cannot diff" in out.stderr

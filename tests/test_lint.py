"""vearch-lint + lockcheck gate (static-analysis tentpole).

Two halves:

- the package gate: `python -m vearch_tpu.tools.lint vearch_tpu/`
  exits 0 against the checked-in allowlist — project invariants hold
  on every commit, in tier-1;
- planted-violation fixtures: each rule (and the runtime lock-order
  detector) demonstrably FIRES on seeded bad code, so a regression in
  the analyzer itself cannot silently turn the gate into a tautology.
"""

import os
import subprocess
import sys
import threading
import textwrap

import pytest

from vearch_tpu.tools import lockcheck
from vearch_tpu.tools.lint.core import Allowlist, run_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "vearch_tpu")


def _lint_file(tmp_path, rel, source, allowlist=None):
    """Write `source` at tmp_path/rel and lint it; returns unsuppressed
    findings. `rel` matters: path-suffix rules (VL102, VL302) key on it."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings = run_paths([str(path)], allowlist=allowlist)
    return [f for f in findings if not f.suppressed]


def _rules(findings):
    return sorted({f.rule for f in findings})


# -- the real gate -----------------------------------------------------------

def test_package_is_lint_clean():
    """The tree passes its own linter with the checked-in allowlist.
    Run as a subprocess so the exact CI/dev command is what's proven."""
    out = subprocess.run(
        [sys.executable, "-m", "vearch_tpu.tools.lint", PKG],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout


def test_list_rules_names_every_rule():
    out = subprocess.run(
        [sys.executable, "-m", "vearch_tpu.tools.lint", "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0
    for rid in ("VL101", "VL102", "VL103", "VL104", "VL105", "VL201",
                "VL202", "VL203", "VL301", "VL302", "VL401"):
        assert rid in out.stdout, rid


# -- planted static violations: every rule fires -----------------------------

def test_vl101_hidden_dispatch_fires(tmp_path):
    found = _lint_file(tmp_path, "cluster/sneaky.py", """\
        import jax

        def warm(fn):
            return jax.jit(fn)
        """)
    assert _rules(found) == ["VL101"]


def test_vl101_silent_in_device_layers(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/ops/fine.py", """\
        import jax

        def warm(fn):
            return jax.jit(fn)
        """)
    assert found == []


def test_vl101_parallel_is_sanctioned_dispatch_layer(tmp_path):
    """The mesh data plane (parallel/) mints shard_map programs and
    jitted tail-append writers as a first-class dispatch layer."""
    found = _lint_file(tmp_path, "vearch_tpu/parallel/fine.py", """\
        import jax
        from jax.experimental.shard_map import shard_map

        def build(mesh, specs, body):
            return jax.jit(shard_map(body, mesh=mesh, in_specs=specs,
                                     out_specs=specs[0]))
        """)
    assert found == []


def test_vl101_shard_map_outside_dispatch_layers_fires(tmp_path):
    """shard_map is a dispatch construct: the cluster plane minting one
    directly (instead of calling parallel/) still trips VL101."""
    found = _lint_file(tmp_path, "cluster/rogue_mesh.py", """\
        from jax.experimental.shard_map import shard_map

        def scan(mesh, specs, body):
            return shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs[0])
        """)
    assert _rules(found) == ["VL101"]


def test_vl102_host_sync_in_serving_path_fires(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        import numpy as np

        class PSServer:
            def _h_search(self, body):
                q = np.asarray(body["vectors"])
                return q

            def _h_other(self, body):
                return np.asarray(body)  # not a serving-path function
        """)
    assert _rules(found) == ["VL102"]
    assert len(found) == 1 and found[0].line == 5


def test_vl102_inline_allow_suppresses(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        import numpy as np

        class PSServer:
            def _h_search(self, body):
                q = np.asarray(body["vectors"])  # lint: allow[host-sync] wire payload normalization
                return q
        """)
    assert found == []


def test_vl103_redeclared_tier_literal_fires(tmp_path):
    """Serving code minting its own shape grid (instead of importing the
    perf model's) breaks the zero-retrace bound silently — VL103."""
    found = _lint_file(tmp_path, "vearch_tpu/engine/rogue.py", """\
        MY_ROW_BUCKETS = (8, 32)

        def pick(n):
            return min(b for b in MY_ROW_BUCKETS if b >= n)
        """)
    assert _rules(found) == ["VL103"]
    assert "re-declare" in found[0].message


def test_vl103_import_and_inline_allow_pass(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/engine/fine.py", """\
        from vearch_tpu.ops import perf_model

        def pick(n):
            return perf_model.bucket_rows(n)

        HIST_BUCKETS = (1, 2, 4)  # lint: allow[bucket-drift] histogram bounds, not dispatch shapes
        """)
    assert found == []


def test_vl103_canonical_grid_must_match_policy_pin(tmp_path):
    """The perf model's own declaration is checked against the lint
    policy pin: a grid change must be a conscious two-file edit."""
    found = _lint_file(tmp_path, "vearch_tpu/ops/perf_model.py", """\
        ROW_BUCKETS = (8, 64, 256, 2048)
        FETCH_K_TIERS = (16, 64, 256, 1024)
        """)
    assert _rules(found) == ["VL103"]
    assert "policy" in found[0].message
    # matching grids are clean
    found = _lint_file(tmp_path, "vearch_tpu/ops/perf_model.py", """\
        ROW_BUCKETS = (8, 64, 256, 1024)
        FETCH_K_TIERS = (16, 64, 256, 1024)
        """)
    assert found == []
    # a missing declaration is as bad as a drifted one
    found = _lint_file(tmp_path, "vearch_tpu/ops/perf_model.py", """\
        ROW_BUCKETS = (8, 64, 256, 1024)
        """)
    assert _rules(found) == ["VL103"]
    assert "FETCH_K_TIERS" in found[0].message


def test_vl104_unattributed_billable_counter_fires(tmp_path):
    """A serving-path kill/shed counter incremented without naming the
    space un-attributes that failure class — VL104."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def shed(self, lbl):
                self._shed_total.inc("search")
                self._killed_total.inc("deadline", lbl)
        """)
    assert _rules(found) == ["VL104"]
    assert sorted(f.line for f in found) == [3, 4]
    assert "space" in found[0].message


def test_vl104_space_argument_passes(tmp_path):
    """Any space-shaped argument — a `space_lbl` local, a
    `_space_key()` call, a system-space constant — attributes the
    increment."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def shed(self, pid):
                space_lbl = self._acct.label(self._space_key(pid))
                self._shed_total.inc("search", space_lbl)
                self._killed_total.inc("deadline",
                                       self._space_key(pid))
                self._killed_total.inc("operator", SYSTEM_SPACE)
        """)
    assert found == []


def test_vl104_inline_allow_and_other_files_pass(tmp_path):
    """Genuinely tenant-free increments waive with a reason; the same
    code outside the serving files is out of scope."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        class Router:
            def warm(self):
                self._shed_total.inc(  # lint: allow[space-attr] zero-fill label registration
                    "search", "other", by=0.0)
        """)
    assert found == []
    found = _lint_file(tmp_path, "vearch_tpu/cluster/master.py", """\
        class Master:
            def note(self):
                self._shed_total.inc("search")
        """)
    assert found == []


def test_vl105_index_mutation_without_staleness_hook_fires(tmp_path):
    """A PS path that rebuilds an index without telling the quality
    monitor leaves shadow recall scoring fresh truth against the old
    serving snapshot — VL105."""
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def _run_build(self, pid, rebuild):
                eng = self.engines[pid]
                if rebuild:
                    eng.rebuild_index()
                else:
                    eng.build_index()
        """)
    assert _rules(found) == ["VL105"]
    assert len(found) == 1
    assert "note_index_mutation" in found[0].message


def test_vl105_hook_call_satisfies(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def _run_build(self, pid):
                self.engines[pid].build_index()
                self._quality.note_index_mutation(pid, "db/s", op="build")
        """)
    assert found == []


def test_vl105_other_files_out_of_scope_and_allow_waives(tmp_path):
    """Engine-internal build paths are out of scope (the engine calls
    the PS observer); a justified def-line pragma waives in scope."""
    found = _lint_file(tmp_path, "vearch_tpu/engine/engine.py", """\
        class Engine:
            def absorb(self):
                self.index.build_index()
        """)
    assert found == []
    found = _lint_file(tmp_path, "vearch_tpu/cluster/ps.py", """\
        class PSServer:
            def _warm(self, eng):  # lint: allow[quality-staleness] offline warmup engine, never serves
                eng.build_index()
        """)
    assert found == []


def test_vl201_unguarded_mutation_fires(tmp_path):
    found = _lint_file(tmp_path, "store.py", """\
        import threading

        class Store:
            _guarded_by = {"items": "_lock", "count": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self.items = {}
                self.count = 0  # __init__ is exempt

            def good(self, k, v):
                with self._lock:
                    self.items[k] = v
                    self.count += 1

            def bad(self, k):
                self.items.pop(k, None)
                self.count -= 1
        """)
    assert _rules(found) == ["VL201"]
    assert len(found) == 2  # .pop() and the augmented assignment


def test_vl201_holds_pragma_trusted(tmp_path):
    found = _lint_file(tmp_path, "store.py", """\
        import threading

        class Store:
            _guarded_by = {"count": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump_locked(self):  # lint: holds[_lock]
                self.count += 1
        """)
    assert found == []


def test_vl202_anonymous_thread_fires(tmp_path):
    found = _lint_file(tmp_path, "bg.py", """\
        import threading

        def go(fn):
            threading.Thread(target=fn, daemon=True).start()
            threading.Thread(target=fn, daemon=True, name="ok").start()
        """)
    assert _rules(found) == ["VL202"]
    assert len(found) == 1 and found[0].line == 4


def test_vl203_wall_clock_fires_and_monotonic_passes(tmp_path):
    found = _lint_file(tmp_path, "timing.py", """\
        import time
        import time as _time

        def latency():
            t0 = time.time()
            t1 = _time.time()
            t2 = time.monotonic()
            return t0, t1, t2
        """)
    assert _rules(found) == ["VL203"]
    assert sorted(f.line for f in found) == [5, 6]


def test_vl301_bare_except_fires(tmp_path):
    found = _lint_file(tmp_path, "anything.py", """\
        def f():
            try:
                return 1
            except:
                return None
        """)
    assert _rules(found) == ["VL301"]


def test_vl302_swallowed_except_in_raft_fires(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/raft.py", """\
        def apply(entries, log):
            for e in entries:
                try:
                    e()
                except Exception:
                    pass
                try:
                    e()
                except Exception as exc:  # visible: logged
                    log.warning("apply failed: %s", exc)
        """)
    assert _rules(found) == ["VL302"]
    assert len(found) == 1 and found[0].line == 5


def test_vl302_only_in_critical_modules(tmp_path):
    found = _lint_file(tmp_path, "vearch_tpu/cluster/router.py", """\
        def f(e):
            try:
                e()
            except Exception:
                pass
        """)
    assert found == []


def test_reasonless_pragma_is_itself_a_finding(tmp_path):
    found = _lint_file(tmp_path, "timing.py", """\
        import time

        def f():
            return time.time()  # lint: allow[wall-clock]
        """)
    # the naked pragma suppresses the site but fails the gate itself:
    # VL000 is unsuppressable, so a reasonless waiver still exits 1
    assert _rules(found) == ["VL000"]
    assert "no reason" in found[0].message


def test_allowlist_suppresses_and_unused_entries_fail(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "VL203 cluster/timing.py fixture proves file-scoped suppression\n"
        "VL101 cluster/gone.py this entry matches nothing\n"
    )
    found = _lint_file(tmp_path, "cluster/timing.py", """\
        import time

        def f():
            return time.time()
        """, allowlist=Allowlist(str(allow)))
    # VL203 suppressed by the first entry; the dead entry is a VL000
    assert _rules(found) == ["VL000"]
    assert "unused allowlist entry" in found[0].message


# -- runtime lockcheck: the dynamic half -------------------------------------

@pytest.fixture
def lockcheck_on():
    lockcheck.reset()
    lockcheck.enable()
    yield
    lockcheck.reset()


def test_make_lock_is_plain_when_disabled():
    lockcheck.reset()
    lockcheck.disable()
    try:
        lk = lockcheck.make_lock("x")
        assert not isinstance(lk, lockcheck.DebugLock)
    finally:
        lockcheck.reset()


def test_lock_order_inversion_detected(lockcheck_on):
    a = lockcheck.make_lock("fixture.a")
    b = lockcheck.make_lock("fixture.b")
    with a:
        with b:
            pass
    # reverse order on another thread — no deadlock is ever hit, the
    # edge graph alone proves the interleaving exists
    def reverse():
        with b:
            with a:
                pass
    t = threading.Thread(target=reverse, daemon=True, name="fixture-rev")
    t.start()
    t.join(timeout=10)
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert "lock-order-inversion" in kinds
    with pytest.raises(AssertionError, match="lock-order-inversion"):
        lockcheck.check()


def test_consistent_order_is_clean(lockcheck_on):
    a = lockcheck.make_lock("fixture.c")
    b = lockcheck.make_lock("fixture.d")
    for _ in range(3):
        with a:
            with b:
                pass
    lockcheck.check()
    assert (("fixture.c", "fixture.d")
            in lockcheck.acquisition_edges())


def test_unguarded_write_detected(lockcheck_on):
    @lockcheck.guarded
    class Store:
        _guarded_by = {"count": "_lock"}

        def __init__(self):
            self._lock = lockcheck.make_lock("fixture.store")
            self.count = 0  # construction is exempt

    s = Store()
    with s._lock:
        s.count = 1  # guarded: fine
    s.count = 2  # seeded violation
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert kinds == ["unguarded-write"]
    assert "Store.count" in lockcheck.violations()[0]["detail"]


def test_non_reentrant_reacquire_detected(lockcheck_on):
    lk = lockcheck.make_lock("fixture.plain", reentrant=False)
    with lk:
        with lk:  # a real Lock would deadlock right here
            pass
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert "self-deadlock" in kinds


def test_foreign_release_detected(lockcheck_on):
    lk = lockcheck.make_lock("fixture.foreign", reentrant=True)
    lk.acquire()
    err: list[Exception] = []

    def releaser():
        try:
            lk.release()
        except Exception as e:  # RLock may refuse; the record is the point
            err.append(e)

    t = threading.Thread(target=releaser, daemon=True,
                         name="fixture-foreign")
    t.start()
    t.join(timeout=10)
    kinds = [v["kind"] for v in lockcheck.violations()]
    assert "foreign-release" in kinds


def test_condition_integration_keeps_held_stack_honest(lockcheck_on):
    lk = lockcheck.make_lock("fixture.cv", reentrant=True)
    cv = threading.Condition(lk)
    ready = threading.Event()

    def waiter():
        with cv:
            ready.set()
            cv.wait(timeout=10)
            # back under the lock after wait: guarded writes here must
            # see the lock as held
            assert lk.held_by_current()

    t = threading.Thread(target=waiter, daemon=True, name="fixture-cv")
    t.start()
    assert ready.wait(timeout=10)
    with cv:
        cv.notify_all()
    t.join(timeout=10)
    lockcheck.check()

"""Config loader + debug endpoints."""

import urllib.request

import pytest

from vearch_tpu.cluster.config import Config
from vearch_tpu.cluster.master import MasterServer


def test_config_load_and_sections(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text("""
[global]
data = "/tmp/vd"
auth = true
root_password = "pw"

[master]
port = 8817
heartbeat_ttl = 4.0

[ps]
port = 8081
""")
    cfg = Config.load(str(p))
    assert cfg.data_dir == "/tmp/vd"
    assert cfg.auth and cfg.root_password == "pw"
    assert cfg.master["port"] == 8817
    assert cfg.ps["port"] == 8081


def test_config_validation(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text("[master]\nport = 99999\n")
    with pytest.raises(ValueError, match="out of range"):
        Config.load(str(p))
    p.write_text("[master]\nheartbeat_ttl = -1\n")
    with pytest.raises(ValueError, match="positive"):
        Config.load(str(p))


def test_main_conf_flag(tmp_path):
    # the launcher parses --conf without starting (role validation path)
    from vearch_tpu.__main__ import main

    p = tmp_path / "c.toml"
    p.write_text("[router]\nport = 9001\n")
    # router role without master_addr exits 2 (after reading the conf)
    assert main(["--role", "router", "--conf", str(p)]) == 2


def test_debug_stacks_endpoint():
    m = MasterServer()
    m.start()
    try:
        with urllib.request.urlopen(f"http://{m.addr}/debug/stacks") as r:
            text = r.read().decode()
        assert "thread" in text and "_serve" in text
    finally:
        m.stop()


def test_request_kill_switch(tmp_path, rng):
    """Kill switch + slow-request killer (reference: Set/DeleteKillStatus
    c_api surface + ps/schedule_job.go:252 slow-request killer)."""
    import numpy as np

    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.master import MasterServer
    from vearch_tpu.cluster.ps import PSServer
    from vearch_tpu.engine.engine import (
        Engine, RequestContext, RequestKilled, SearchRequest,
    )
    from vearch_tpu.engine.types import (
        DataType, FieldSchema, IndexParams, MetricType, TableSchema,
    )

    # engine level: a pre-killed context aborts before any device work
    schema = TableSchema("k", [
        FieldSchema("v", DataType.VECTOR, dimension=8,
                    index=IndexParams("FLAT", MetricType.L2, {})),
    ])
    eng = Engine(schema)
    eng.upsert([{"_id": "a", "v": [0.0] * 8}])
    ctx = RequestContext("r1")
    ctx.kill("operator")
    import pytest as _pytest

    with _pytest.raises(RequestKilled):
        eng.search(SearchRequest(
            vectors={"v": np.zeros((1, 8), np.float32)}, k=1, ctx=ctx))

    # PS level: the slow killer aborts a first-compile search
    master = MasterServer()
    master.start()
    ps = PSServer(data_dir=str(tmp_path / "ps"), master_addr=master.addr)
    ps.start()
    try:
        rpc.call(ps.addr, "POST", "/ps/partition/create", {
            "partition": {"id": 1, "space_id": 1, "db_name": "d",
                          "space_name": "s", "slot": 0, "replicas": [],
                          "leader": -1},
            "schema": {"name": "s", "fields": [
                {"name": "v", "data_type": "vector", "dimension": 24,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}}]},
        })
        vecs = rng.standard_normal((50, 24)).astype(np.float32)
        rpc.call(ps.addr, "POST", "/ps/doc/upsert", {
            "partition_id": 1,
            "documents": [{"_id": f"d{i}", "v": vecs[i].tolist()}
                          for i in range(50)]})
        rpc.call(ps.addr, "POST", "/ps/engine/config",
                 {"partition_id": 1, "config": {"slow_request_ms": 1}})
        import time as _time

        _time.sleep(0.7)  # let the killer re-arm at the fast tick
        # first search compiles (>> 1ms): the killer flips the ctx and
        # the engine aborts at its next phase boundary with the
        # terminal request_killed code (never retried by the router)
        from vearch_tpu.cluster.rpc import ERR_REQUEST_KILLED

        with _pytest.raises(rpc.RpcError, match="killed") as ei:
            rpc.call(ps.addr, "POST", "/ps/doc/search",
                     {"partition_id": 1, "vectors": {"v": vecs[:3]},
                      "k": 5, "request_id": "victim"})
        assert ei.value.code == ERR_REQUEST_KILLED
        assert rpc.call(ps.addr, "GET", "/ps/stats")["killed_requests"] >= 1
        # disable the killer: the same search now completes
        rpc.call(ps.addr, "POST", "/ps/engine/config",
                 {"partition_id": 1, "config": {"slow_request_ms": 0}})
        out = rpc.call(ps.addr, "POST", "/ps/doc/search",
                       {"partition_id": 1, "vectors": {"v": vecs[:3]},
                        "k": 5})
        assert out["results"][0][0]["_id"] == "d0"
        # killing an unknown request is a loud 404
        with _pytest.raises(rpc.RpcError, match="not in flight"):
            rpc.call(ps.addr, "POST", "/ps/kill", {"request_id": "nope"})
    finally:
        ps.stop()
        master.stop()


def test_debug_heap_endpoint():
    m = MasterServer()
    m.start()
    try:
        with urllib.request.urlopen(f"http://{m.addr}/debug/heap") as r:
            assert b"tracemalloc started" in r.read()
        with urllib.request.urlopen(f"http://{m.addr}/debug/heap") as r:
            text = r.read().decode()
        assert "heap:" in text and "KiB" in text
        with urllib.request.urlopen(
            f"http://{m.addr}/debug/heap?stop=1"
        ) as r:
            assert b"stopped" in r.read()
    finally:
        m.stop()


def test_slow_channel_routing(tmp_path, rng):
    """Slow-query isolation (reference: dedicated slow-search channel
    pool, ps/server.go:95): partitions with slow latency history route
    through a separate small gate and are counted."""
    import numpy as np

    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.master import MasterServer
    from vearch_tpu.cluster.ps import PSServer

    master = MasterServer()
    master.start()
    ps = PSServer(data_dir=str(tmp_path / "ps"), master_addr=master.addr)
    ps.start()
    try:
        rpc.call(ps.addr, "POST", "/ps/partition/create", {
            "partition": {"id": 1, "space_id": 1, "db_name": "d",
                          "space_name": "s", "slot": 0, "replicas": [],
                          "leader": -1},
            "schema": {"name": "s", "fields": [
                {"name": "v", "data_type": "vector", "dimension": 16,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}}]},
        })
        vecs = rng.standard_normal((30, 16)).astype(np.float32)
        rpc.call(ps.addr, "POST", "/ps/doc/upsert", {
            "partition_id": 1,
            "documents": [{"_id": f"d{i}", "v": vecs[i].tolist()}
                          for i in range(30)]})
        # prime the EWMA (first search includes compile time)
        rpc.call(ps.addr, "POST", "/ps/doc/search",
                 {"partition_id": 1, "vectors": {"v": vecs[:2]}, "k": 3})
        stats = rpc.call(ps.addr, "GET", "/ps/stats")
        assert "1" in stats["search_ewma_ms"]
        assert stats["slow_routed"] == 0
        # threshold below the observed history -> next search routes slow
        rpc.call(ps.addr, "POST", "/ps/engine/config",
                 {"partition_id": 1, "config": {"slow_route_ms": 1}})
        # force a tiny positive EWMA regardless of timer resolution
        ps._search_ewma[1] = max(ps._search_ewma.get(1, 0.0), 5.0)
        out = rpc.call(ps.addr, "POST", "/ps/doc/search",
                       {"partition_id": 1, "vectors": {"v": vecs[:2]},
                        "k": 3})
        assert out["results"]
        assert rpc.call(ps.addr, "GET", "/ps/stats")["slow_routed"] >= 1
    finally:
        ps.stop()
        master.stop()

"""Config loader + debug endpoints."""

import urllib.request

import pytest

from vearch_tpu.cluster.config import Config
from vearch_tpu.cluster.master import MasterServer


def test_config_load_and_sections(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text("""
[global]
data = "/tmp/vd"
auth = true
root_password = "pw"

[master]
port = 8817
heartbeat_ttl = 4.0

[ps]
port = 8081
""")
    cfg = Config.load(str(p))
    assert cfg.data_dir == "/tmp/vd"
    assert cfg.auth and cfg.root_password == "pw"
    assert cfg.master["port"] == 8817
    assert cfg.ps["port"] == 8081


def test_config_validation(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text("[master]\nport = 99999\n")
    with pytest.raises(ValueError, match="out of range"):
        Config.load(str(p))
    p.write_text("[master]\nheartbeat_ttl = -1\n")
    with pytest.raises(ValueError, match="positive"):
        Config.load(str(p))


def test_main_conf_flag(tmp_path):
    # the launcher parses --conf without starting (role validation path)
    from vearch_tpu.__main__ import main

    p = tmp_path / "c.toml"
    p.write_text("[router]\nport = 9001\n")
    # router role without master_addr exits 2 (after reading the conf)
    assert main(["--role", "router", "--conf", str(p)]) == 2


def test_debug_stacks_endpoint():
    m = MasterServer()
    m.start()
    try:
        with urllib.request.urlopen(f"http://{m.addr}/debug/stacks") as r:
            text = r.read().decode()
        assert "thread" in text and "_serve" in text
    finally:
        m.stop()

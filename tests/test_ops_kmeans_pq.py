"""Tests for k-means training and product quantization ops."""

import numpy as np
import jax.numpy as jnp

from vearch_tpu.ops import kmeans as km
from vearch_tpu.ops import pq as pqm


def _blobs(rng, n_per, k, d, spread=0.05):
    centers = rng.standard_normal((k, d)).astype(np.float32) * 3
    pts = np.concatenate(
        [c + spread * rng.standard_normal((n_per, d)).astype(np.float32) for c in centers]
    )
    return pts, centers


def test_kmeans_recovers_blobs(rng):
    x, centers = _blobs(rng, n_per=50, k=8, d=16)
    cents = np.asarray(km.train_kmeans(jnp.asarray(x), k=8, iters=15, chunk=128))
    # every true center has a learned centroid nearby
    d = np.linalg.norm(centers[:, None, :] - cents[None, :, :], axis=-1)
    assert (d.min(axis=1) < 0.5).all()


def test_kmeans_no_nan_with_k_gt_clusters(rng):
    # more centroids than natural clusters -> empty clusters must reseed, not NaN
    x, _ = _blobs(rng, n_per=30, k=3, d=8)
    cents = np.asarray(km.train_kmeans(jnp.asarray(x), k=16, iters=8, chunk=64))
    assert np.isfinite(cents).all()


def test_assign_clusters_matches_numpy(rng):
    x = rng.standard_normal((257, 12)).astype(np.float32)
    c = rng.standard_normal((9, 12)).astype(np.float32)
    got = np.asarray(km.assign_clusters(jnp.asarray(x), jnp.asarray(c), chunk=64))
    ref = np.argmin(((x[:, None] - c[None]) ** 2).sum(-1), axis=1)
    # assignment dots run in bf16 (f32 accum): allow rare near-tie flips
    assert (got != ref).mean() < 0.01


def test_pq_roundtrip_reduces_error(rng):
    x, _ = _blobs(rng, n_per=100, k=8, d=32, spread=0.1)
    cb = pqm.train_pq(jnp.asarray(x), m=4, ksub=16, iters=10)
    codes = pqm.encode_pq(jnp.asarray(x), cb)
    assert codes.shape == (800, 4) and codes.dtype == jnp.uint8
    recon = np.asarray(pqm.decode_pq(codes, cb))
    err = np.linalg.norm(recon - x, axis=1).mean()
    base = np.linalg.norm(x - x.mean(0), axis=1).mean()
    assert err < 0.35 * base  # quantization must beat the trivial centroid


def test_adc_scores_match_decoded_l2(rng):
    x = rng.standard_normal((300, 16)).astype(np.float32)
    q = rng.standard_normal((5, 16)).astype(np.float32)
    cb = pqm.train_pq(jnp.asarray(x), m=4, ksub=32, iters=8)
    codes = pqm.encode_pq(jnp.asarray(x), cb)
    lut = pqm.adc_lut_l2(jnp.asarray(q), cb)
    adc = np.asarray(pqm.adc_scores(lut, codes))
    recon = np.asarray(pqm.decode_pq(codes, cb))
    ref = ((q[:, None] - recon[None]) ** 2).sum(-1)
    np.testing.assert_allclose(adc, ref, rtol=1e-3, atol=1e-3)


def test_adc_scores_per_query_candidates(rng):
    x = rng.standard_normal((100, 8)).astype(np.float32)
    q = rng.standard_normal((3, 8)).astype(np.float32)
    cb = pqm.train_pq(jnp.asarray(x), m=2, ksub=16, iters=6)
    codes = pqm.encode_pq(jnp.asarray(x), cb)
    cand = jnp.asarray(np.stack([np.arange(10), np.arange(10, 20), np.arange(20, 30)]))
    per_q_codes = codes[cand]  # [3, 10, 2]
    lut = pqm.adc_lut_l2(jnp.asarray(q), cb)
    got = np.asarray(pqm.adc_scores(lut, per_q_codes))
    full = np.asarray(pqm.adc_scores(lut, codes))
    ref = np.take_along_axis(full, np.asarray(cand), axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-5)

"""Field-type and filter breadth over the REST surface (reference:
test_module_filter.py operator combos; test_module_vector.py string
arrays; date-typed fields). Engine-level coverage exists in
test_scalar_filter.py — this exercises the same semantics end-to-end
through router JSON."""

import numpy as np
import pytest

from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient

D = 8


@pytest.fixture(scope="module")
def client(tmp_path_factory):
    with StandaloneCluster(
        data_dir=str(tmp_path_factory.mktemp("ftypes")), n_ps=2
    ) as c:
        cl = VearchClient(c.router_addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "sp", "partition_num": 2, "replica_num": 1,
            "fields": [
                {"name": "tags", "data_type": "stringArray"},
                {"name": "kind", "data_type": "string",
                 "scalar_index": "BITMAP"},
                {"name": "born", "data_type": "date"},
                {"name": "count", "data_type": "long"},
                {"name": "ok", "data_type": "bool"},
                {"name": "emb", "data_type": "vector", "dimension": D,
                 "index": {"index_type": "FLAT", "metric_type": "L2",
                           "params": {}}},
            ],
        })
        rng = np.random.default_rng(3)
        vecs = rng.standard_normal((60, D)).astype(np.float32)
        day = 86_400_000
        cl.upsert("db", "sp", [
            {"_id": f"d{i}",
             "tags": [f"t{i % 5}", f"g{i % 3}"],
             "kind": ["a", "b", "c"][i % 3],
             "born": i * day,
             "count": int(i) * 10,
             "ok": i % 2 == 0,
             "emb": vecs[i]}
            for i in range(60)
        ])
        yield cl


def _q(cl, conditions, operator="AND", limit=200):
    return {d["_id"] for d in cl.query(
        "db", "sp",
        filters={"operator": operator, "conditions": conditions},
        limit=limit)}


def test_string_array_any_match(client):
    got = _q(client, [{"field": "tags", "operator": "IN",
                       "value": ["t1"]}])
    assert got == {f"d{i}" for i in range(60) if i % 5 == 1}
    # NOT IN on arrays: docs where NO element matches
    got = _q(client, [{"field": "tags", "operator": "NOT IN",
                       "value": ["t1", "t2"]}])
    assert got == {f"d{i}" for i in range(60) if i % 5 not in (1, 2)}


def test_date_range(client):
    day = 86_400_000
    got = _q(client, [
        {"field": "born", "operator": ">=", "value": 10 * day},
        {"field": "born", "operator": "<", "value": 13 * day},
    ])
    assert got == {"d10", "d11", "d12"}


def test_long_in_and_or_combo(client):
    got = _q(client, [{"field": "count", "operator": "IN",
                       "value": [0, 100, 550, 590]}])
    assert got == {"d0", "d10", "d55", "d59"}
    got = _q(client, [
        {"field": "count", "operator": "<", "value": 20},
        {"field": "count", "operator": ">=", "value": 580},
    ], operator="OR")
    assert got == {"d0", "d1", "d58", "d59"}


def test_bool_and_indexed_string(client):
    got = _q(client, [
        {"field": "ok", "operator": "=", "value": True},
        {"field": "kind", "operator": "=", "value": "a"},
    ])
    # kind=a => i%3==0; ok => i%2==0 => i%6==0
    assert got == {f"d{i}" for i in range(60) if i % 6 == 0}


def test_filtered_search_combo_over_rest(client):
    rng = np.random.default_rng(3)
    vecs = rng.standard_normal((60, D)).astype(np.float32)
    hits = client.search(
        "db", "sp", [{"field": "emb", "feature": vecs[7].tolist()}],
        limit=5,
        filters={"operator": "AND", "conditions": [
            {"field": "kind", "operator": "=", "value": "b"},
            {"field": "count", "operator": ">=", "value": 70},
        ]})
    ids = [h["_id"] for h in hits[0]]
    assert ids[0] == "d7"  # kind b (7%3==1), count 70: self-match allowed
    assert all(int(i[1:]) % 3 == 1 and int(i[1:]) >= 7 for i in ids)


def test_alias_document_ops(client):
    import vearch_tpu.cluster.rpc as rpc

    rpc.call(client.addr, "POST", "/alias/al1/dbs/db/spaces/sp")
    docs = client.query("db", "al1", document_ids=["d5"])
    assert docs[0]["kind"] == ["a", "b", "c"][5 % 3]
    assert client.delete("db", "al1", document_ids=["d5"]) == 1
    assert client.query("db", "al1", document_ids=["d5"]) == []

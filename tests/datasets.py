"""Synthetic datasets for recall gates, in two difficulty regimes.

The reference gates recall on SIFT1M/Glove/Nytimes against an in-process
faiss oracle (reference: test/test_recall_baseline.py:301-303,
test/utils/data_utils.py:209,256). This image has zero egress, so real
datasets are unavailable; instead of only the easy isotropic
clustered-Gaussian set (r2 VERDICT weak #3: IVF coarse quantization is
nearly oracle-aligned on it), gates also run on a HARD regime built to
reproduce what makes real ANN datasets hard:

- power-law cluster masses (Zipf): a few huge clusters + a long tail of
  tiny ones, so fixed-nprobe scans miss tail neighborhoods;
- anisotropic per-cluster covariance (decaying eigen-spectrum under
  random rotations): distances concentrate along cluster-specific
  subspaces, misaligning the coarse quantizer's isotropic Voronoi cells;
- out-of-distribution queries: half the queries sit BETWEEN clusters
  (interpolations + noise), where coarse assignment is ambiguous — the
  SIFT/GIST query-set tail the easy set lacks.

Ground truth is always an exact float64 scan (the oracle).
"""

from __future__ import annotations

import numpy as np


def _exact_gt(queries: np.ndarray, base: np.ndarray, k: int = 100):
    q = queries.astype(np.float64)
    b = base.astype(np.float64)
    d2 = (
        np.sum(q ** 2, axis=1)[:, None]
        - 2.0 * q @ b.T
        + np.sum(b ** 2, axis=1)[None, :]
    )
    return np.argsort(d2, axis=1)[:, :k]


def make_easy(n: int, d: int, nq: int, seed: int = 7):
    """Isotropic clustered Gaussians, in-distribution queries (the r1/r2
    generator, kept as the easy half of the matrix)."""
    rng = np.random.default_rng(seed)
    nc = max(n // 100, 16)
    centers = (rng.standard_normal((nc, d)) * 3).astype(np.float32)
    which = rng.integers(0, nc, n)
    base = centers[which] + 0.7 * rng.standard_normal((n, d)).astype(
        np.float32
    )
    q_idx = rng.choice(n, nq, replace=False)
    queries = base[q_idx] + 0.1 * rng.standard_normal((nq, d)).astype(
        np.float32
    )
    return base, queries, _exact_gt(queries, base)


def make_hard(n: int, d: int, nq: int, seed: int = 13):
    """Power-law cluster sizes + anisotropic covariance + OOD queries."""
    rng = np.random.default_rng(seed)
    nc = max(n // 120, 16)
    # Zipf cluster masses: head clusters hold most rows, the tail is
    # hundreds of near-empty cells
    w = 1.0 / np.arange(1, nc + 1) ** 1.1
    w /= w.sum()
    which = rng.choice(nc, n, p=w)
    centers = (rng.standard_normal((nc, d)) * 2.5).astype(np.float32)

    # per-cluster anisotropic transforms: random rotation x decaying
    # eigen-spectrum. A small pool of transforms keeps generation cheap
    # while still giving clusters differently-oriented subspaces.
    n_tf = min(16, nc)
    eigs = (np.arange(1, d + 1, dtype=np.float64) ** -0.6).astype(
        np.float32
    )
    tfs = []
    for _ in range(n_tf):
        q, _ = np.linalg.qr(rng.standard_normal((d, d)))
        tfs.append((q.astype(np.float32) * eigs[None, :]) * 1.6)
    tf_of = rng.integers(0, n_tf, nc)

    noise = rng.standard_normal((n, d)).astype(np.float32)
    base = np.empty((n, d), np.float32)
    for t in range(n_tf):
        m = tf_of[which] == t
        base[m] = centers[which[m]] + noise[m] @ tfs[t]

    # queries: half in-distribution (perturbed rows, including tail
    # clusters), half OOD (between-cluster interpolations + noise)
    nq_in = nq // 2
    q_idx = rng.choice(n, nq_in, replace=False)
    q_in = base[q_idx] + 0.15 * rng.standard_normal(
        (nq_in, d)).astype(np.float32)
    a = rng.integers(0, nc, nq - nq_in)
    b = rng.integers(0, nc, nq - nq_in)
    lam = rng.uniform(0.35, 0.65, (nq - nq_in, 1)).astype(np.float32)
    q_ood = (
        lam * centers[a] + (1.0 - lam) * centers[b]
        + 0.6 * rng.standard_normal((nq - nq_in, d)).astype(np.float32)
    )
    queries = np.concatenate([q_in, q_ood]).astype(np.float32)
    return base, queries, _exact_gt(queries, base)


def make_gist_like(n: int = 10_000, d: int = 960, nq: int = 32,
                   seed: int = 17):
    """GIST1M-shaped config: d=960 with a low intrinsic dimension —
    global correlated structure (rank ~64 mixing matrix) plus small
    ambient noise, the regime where PQ subquantizers see strongly
    correlated subspaces (BASELINE.json lists GIST1M as a target)."""
    rng = np.random.default_rng(seed)
    intrinsic = 64
    mix = rng.standard_normal((intrinsic, d)).astype(np.float32) / np.sqrt(
        intrinsic
    )
    nc = 64
    z_centers = (rng.standard_normal((nc, intrinsic)) * 3).astype(
        np.float32
    )
    which = rng.integers(0, nc, n)
    z = z_centers[which] + 0.7 * rng.standard_normal(
        (n, intrinsic)).astype(np.float32)
    base = z @ mix + 0.05 * rng.standard_normal((n, d)).astype(np.float32)
    q_idx = rng.choice(n, nq, replace=False)
    queries = base[q_idx] + 0.05 * rng.standard_normal(
        (nq, d)).astype(np.float32)
    return base.astype(np.float32), queries.astype(np.float32), _exact_gt(
        queries, base
    )


def _exact_gt_cosine(queries: np.ndarray, base: np.ndarray, k: int = 100):
    q = queries.astype(np.float64)
    b = base.astype(np.float64)
    qn = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
    bn = b / np.maximum(np.linalg.norm(b, axis=1, keepdims=True), 1e-12)
    sims = qn @ bn.T
    return np.argsort(-sims, axis=1)[:, :k]


def make_glove_like(n: int, d: int = 100, nq: int = 32, seed: int = 23):
    """Glove-100-angular-shaped config (reference gates recall on
    Glove, test/test_recall_baseline.py + data_utils.py:295): word
    embeddings under COSINE with the properties that make angular
    search hard —

    - norm spread correlated with cluster mass (frequent-word clusters
      have larger, tighter-normed vectors), so L2 and angular
      neighborhoods disagree and only a cosine-correct pipeline gates;
    - low-ish intrinsic dimension (rank ~48 mixing) with heavy-tailed
      coordinate scales, like trained embedding matrices;
    - in-distribution queries (the Glove query set is held-out words).

    Ground truth is the exact float64 COSINE scan.
    """
    rng = np.random.default_rng(seed)
    intrinsic = 48
    mix = rng.standard_normal((intrinsic, d)).astype(np.float32)
    mix *= (np.arange(1, d + 1, dtype=np.float32) ** -0.3)[None, :]
    nc = max(n // 150, 16)
    w = 1.0 / np.arange(1, nc + 1) ** 1.05
    w /= w.sum()
    which = rng.choice(nc, n, p=w)
    z_centers = (rng.standard_normal((nc, intrinsic)) * 2.0).astype(
        np.float32)
    z = z_centers[which] + 0.6 * rng.standard_normal(
        (n, intrinsic)).astype(np.float32)
    base = (z @ mix).astype(np.float32)
    # norm ~ cluster frequency: head-cluster rows get larger norms
    freq_rank = np.argsort(np.argsort(-w))  # 0 = most massive
    norm_scale = (1.0 + 2.0 / (1.0 + freq_rank[which])).astype(np.float32)
    base *= norm_scale[:, None]
    q_idx = rng.choice(n, nq, replace=False)
    queries = base[q_idx] + 0.1 * rng.standard_normal(
        (nq, d)).astype(np.float32)
    return base, queries.astype(np.float32), _exact_gt_cosine(
        queries, base)

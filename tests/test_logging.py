"""Logging subsystem (reference: internal/pkg/log — leveled rotating
per-role logs, runtime level flips via config)."""

import logging
import os

import numpy as np

from vearch_tpu.utils import log


def _reset_info():
    log.set_level("info")


def test_levels_and_runtime_flip():
    _reset_info()
    assert not log.is_debug_enabled()
    log.set_level("debug")
    assert log.is_debug_enabled()
    assert not log.is_trace_enabled()
    log.set_level("trace")
    assert log.is_trace_enabled()
    _reset_info()


def test_parse_level_rejects_unknown():
    import pytest

    with pytest.raises(ValueError, match="unknown log level"):
        log.parse_level("loud")


def test_init_writes_rotating_file(tmp_path):
    log.init("testrole", log_dir=str(tmp_path), level="info",
             max_bytes=2048, backups=2, stderr=False)
    lg = log.get("unit")
    for i in range(200):
        lg.info("line %d padding %s", i, "x" * 64)
    files = sorted(os.listdir(tmp_path))
    assert "testrole.log" in files
    assert any(f.startswith("testrole.log.") for f in files), files
    # restore default handlers for the rest of the suite
    log.init("pytest", log_dir=None, level="info")


def test_component_logger_namespacing():
    lg = log.get("ps.raft")
    assert lg.name == "vearch.ps.raft"
    assert isinstance(lg, logging.Logger)


def test_cluster_config_flips_log_level(tmp_path, rng):
    """POST /config/{db}/{space} {"log_level": ...} reaches master and
    PS (reference: runtime log-level config fan-out)."""
    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.standalone import StandaloneCluster
    from vearch_tpu.sdk.client import VearchClient

    _reset_info()
    with StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1) as c:
        cl = VearchClient(c.router_addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1,
            "fields": [{"name": "v", "data_type": "vector", "dimension": 8,
                        "index": {"index_type": "FLAT",
                                  "metric_type": "L2", "params": {}}}],
        })
        cl.upsert("db", "s", [
            {"_id": "a", "v": np.asarray(rng.standard_normal(8),
                                         np.float32)}
        ])
        assert not log.is_debug_enabled()
        rpc.call(c.master_addr, "POST", "/config/db/s",
                 {"log_level": "debug"})
        # in-process cluster shares the process-wide logger
        assert log.is_debug_enabled()
    _reset_info()


def test_invalid_log_level_rejected_atomically(tmp_path, rng):
    """A typo'd level 400s without persisting junk config or
    half-applying other keys."""
    import pytest

    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.standalone import StandaloneCluster
    from vearch_tpu.sdk.client import VearchClient

    _reset_info()
    with StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=1) as c:
        cl = VearchClient(c.router_addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1,
            "fields": [{"name": "v", "data_type": "vector", "dimension": 8,
                        "index": {"index_type": "FLAT",
                                  "metric_type": "L2", "params": {}}}],
        })
        with pytest.raises(rpc.RpcError, match="unknown log level"):
            rpc.call(c.master_addr, "POST", "/config/db/s",
                     {"log_level": "loud", "slow_request_ms": 50})
        # nothing persisted, level unchanged
        got = rpc.call(c.master_addr, "GET", "/config/db/s")
        assert "log_level" not in (got or {})
        assert not log.is_debug_enabled()

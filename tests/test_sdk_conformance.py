"""SDK wire-fixture conformance (VERDICT r2 #8).

The Go/Java/Rust SDKs cannot be compiled here (no toolchains in the
image), so nothing used to catch a typo'd wire key in them. This module
closes that hole in two steps:

1. RECORD: drive the canonical operations through the Python SDK against
   a live cluster, capturing every request/response as a STRUCTURE
   (key tree with value types, not values — deterministic across runs)
   and compare against the committed fixture
   `sdk/fixtures/wire_shapes.json`. Server wire drift fails here first.
   Intentional changes: regenerate with VEARCH_UPDATE_FIXTURES=1.

2. ASSERT: for every wire key and route an SDK claims to speak, the
   exact quoted string must appear in that SDK's source. A typo'd
   struct tag (`json:"db_nam"`) or route fails the suite.

Reference intent: sdk/go, sdk/java, sdk/rust are CI-built upstream.
"""

import json
import os

import numpy as np
import pytest

from vearch_tpu.cluster import rpc as rpc_mod
from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "sdk", "fixtures", "wire_shapes.json")
D = 8


def shape_of(v):
    """Value -> deterministic structure: dicts keep keys, lists keep one
    element shape, scalars become type names."""
    if isinstance(v, np.ndarray):
        return "tensor"  # rides the binary codec, not JSON
    if isinstance(v, dict):
        return {k: shape_of(v[k]) for k in sorted(v)}
    if isinstance(v, (list, tuple)):
        return [shape_of(v[0])] if v else []
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float"
    if v is None:
        return "null"
    return "str"


@pytest.fixture(scope="module")
def recorded(tmp_path_factory):
    """Drive canonical ops; capture {op: {method, path, request,
    response}} wire structures."""
    import threading

    rec: dict[str, dict] = {}
    real_call = rpc_mod.call
    current_op: list[str] = [""]
    test_thread = threading.get_ident()

    def recording_call(addr, method, path, body=None, **kw):
        out = real_call(addr, method, path, body, **kw)
        # rpc.call is module-shared: the router's background watch poll
        # rides through here too — record only this thread's SDK calls
        if threading.get_ident() != test_thread:
            return out
        op = current_op[0]
        if op and op not in rec:
            rec[op] = {
                "method": method,
                # server-assigned path segments normalized
                "path": path,
                "request": shape_of(body) if body is not None else None,
                "response": shape_of(out),
            }
        return out

    import vearch_tpu.sdk.client as sdk_mod

    sdk_mod.rpc.call = recording_call
    try:
        with StandaloneCluster(
            data_dir=str(tmp_path_factory.mktemp("sdkfix")), n_ps=1
        ) as c:
            cl = VearchClient(c.router_addr)
            rng = np.random.default_rng(0)
            vecs = rng.standard_normal((20, D)).astype(np.float32)

            def op(name, fn):
                current_op[0] = name
                out = fn()
                current_op[0] = ""
                return out

            op("create_database", lambda: cl.create_database("db"))
            op("create_space", lambda: cl.create_space("db", {
                "name": "sp", "partition_num": 1, "replica_num": 1,
                "fields": [
                    {"name": "color", "data_type": "string"},
                    {"name": "price", "data_type": "float"},
                    {"name": "emb", "data_type": "vector", "dimension": D,
                     "index": {"index_type": "FLAT", "metric_type": "L2",
                               "params": {}}},
                ],
            }))
            op("get_space", lambda: cl.get_space("db", "sp"))
            op("upsert", lambda: cl.upsert("db", "sp", [
                {"_id": f"d{i}", "color": "red", "price": float(i),
                 "emb": vecs[i]} for i in range(20)
            ]))
            op("search", lambda: cl.search(
                "db", "sp", [{"field": "emb", "feature": vecs[1].tolist()}],
                limit=3,
                filters={"operator": "AND", "conditions": [
                    {"operator": "=", "field": "color", "value": "red"}]},
                fields=["color", "price"],
            ))
            op("query", lambda: cl.query("db", "sp",
                                         document_ids=["d1", "d2"]))
            op("delete", lambda: cl.delete("db", "sp",
                                           document_ids=["d1"]))
            op("flush", lambda: cl.flush("db", "sp"))
            op("add_field_index",
               lambda: cl.add_field_index("db", "sp", "color", "BITMAP",
                                          background=False))
            op("remove_field_index",
               lambda: cl.remove_field_index("db", "sp", "color"))
            op("list_databases", lambda: cl.list_databases())
    finally:
        sdk_mod.rpc.call = real_call
    return rec


def test_wire_shapes_match_committed_fixture(recorded):
    if os.environ.get("VEARCH_UPDATE_FIXTURES") == "1" \
            or not os.path.exists(FIXTURE):
        os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
        with open(FIXTURE, "w") as f:
            json.dump(recorded, f, indent=1, sort_keys=True)
    with open(FIXTURE) as f:
        committed = json.load(f)
    assert recorded == committed, (
        "wire structures drifted from sdk/fixtures/wire_shapes.json — "
        "if intentional, regenerate with VEARCH_UPDATE_FIXTURES=1 and "
        "update the non-Python SDKs to match"
    )


# which fixture ops each SDK implements, and the wire keys it must spell
# correctly for them (request keys it serializes + response keys it
# reads; projection fields like doc columns excluded)
_DOC_OPS = ("upsert", "search", "query", "delete", "flush")
_SDK_SURFACES = {
    "go/client.go": {
        "ops": _DOC_OPS + ("create_database", "create_space", "get_space"),
        "extra_keys": ["document_ids", "total", "documents", "_id",
                       "_score", "code", "msg", "data"],
    },
    # Java and Rust return the raw `data` payload (callers unwrap
    # result keys), so only the envelope is their response surface
    "java/src/main/java/io/vearchtpu/VearchTpuClient.java": {
        "ops": _DOC_OPS + ("create_database", "create_space"),
        "extra_keys": ["code", "msg", "data"],
        # createSpace(String spaceConfigJson): schema keys are caller
        # passthrough, not serialized by the SDK
        "passthrough_ops": {"create_space"},
    },
    "rust/src/lib.rs": {
        "ops": _DOC_OPS + ("create_database", "create_space"),
        "extra_keys": ["code", "msg", "data"],
    },
}

# request keys an SDK serializes for each op (top-level only; nested
# schema/filter keys are caller-provided passthrough in all three SDKs,
# except the universally-typed ones below)
_REQUEST_KEYS = {
    "upsert": ["db_name", "space_name", "documents"],
    "search": ["db_name", "space_name", "vectors", "limit", "filters",
               "fields", "field", "feature"],
    "query": ["db_name", "space_name", "document_ids", "limit"],
    "delete": ["db_name", "space_name", "document_ids"],
    "flush": ["db_name", "space_name"],
    "create_space": ["name", "fields", "partition_num", "replica_num",
                     "data_type", "dimension", "index"],
    "create_database": [],
    "get_space": [],
    "list_databases": [],
}

_ROUTES = {
    "upsert": "/document/upsert",
    "search": "/document/search",
    "query": "/document/query",
    "delete": "/document/delete",
    "flush": "/index/flush",
    "create_database": "/dbs",
    "create_space": "/spaces",
    "get_space": "/spaces",
}


def _tree_keys(node, out: set):
    if isinstance(node, dict):
        for k, v in node.items():
            out.add(k)
            _tree_keys(v, out)
    elif isinstance(node, list):
        for v in node:
            _tree_keys(v, out)


# wire keys of ops the fixture run does not exercise (partition rules,
# aliases, ranker, tracing, kill, backup) — kept curated so the reverse
# check below stays strict
_EXTRA_VALID = {
    "operator_type", "partition_name", "partition_rule", "type", "field",
    "ranges", "value", "min_score", "max_score", "boost", "ranker",
    "params", "weight",
    "load_balance", "request_id", "raft_consistent", "trace", "trace_id",
    "topn", "index_params", "anti_affinity", "enable_id_cache",
    "vector_value", "dbs", "spaces", "servers", "partitions", "alias",
    "code", "msg", "data",  # the response envelope itself
    "training_threshold", "refresh_interval_ms", "metric_type",
    "index_type", "store_type", "offset", "document_ids",
    # r5 full-surface additions (sort/pagination, membership, backup
    # jobs, RBAC, config, schedule ops) — all live server keys
    "sort", "order", "missing", "page_size", "page_num", "_sort",
    "node_id", "addr", "members", "leader",
    "command", "version", "versions", "async", "job_id", "store_root",
    "store", "status", "files_done", "files_total", "background",
    "partition_id", "method",
    "password", "role_name", "privileges", "name",
}


def _valid_wire_keys(recorded) -> set:
    valid: set = set(_EXTRA_VALID)
    for op in recorded.values():
        _tree_keys(op.get("request"), valid)
        _tree_keys(op.get("response"), valid)
    return valid


# per-SDK extraction of every wire key the source spells, for the
# reverse check: an SDK must not emit a key the server doesn't speak
_KEY_EXTRACTORS = {
    "go/client.go": [
        r'json:"([A-Za-z0-9_]+)',          # struct tags
        r'"([a-z_][a-z0-9_]*)":',          # inline map literals
    ],
    "java/src/main/java/io/vearchtpu/VearchTpuClient.java": [
        r'\\"([a-z_][a-z0-9_]*)\\":',      # string-built JSON keys
    ],
    "rust/src/lib.rs": [
        r'"([a-z_][a-z0-9_]*)"\s*:',       # json! macro keys
        r'insert\("([a-z_][a-z0-9_]*)"',   # map inserts
        r'pub ([a-z_][a-z0-9_]*):',        # serde-derived struct fields
    ],
}

# identifiers matched by the extractors that are not wire keys
_NON_WIRE = {"router_url", "auth", "agent"}  # rust Client struct fields


@pytest.mark.parametrize("sdk_file", sorted(_KEY_EXTRACTORS))
def test_sdk_emits_only_known_wire_keys(recorded, sdk_file):
    """Reverse conformance: every key the SDK spells must exist in the
    recorded wire structures (or the curated extra set). This is what
    catches a typo'd tag like json:"db_nam" — the forward check can be
    masked by a correct spelling elsewhere in the file."""
    import re

    with open(os.path.join(REPO, "sdk", sdk_file)) as f:
        src = f.read()
    valid = _valid_wire_keys(recorded) | _NON_WIRE
    emitted = set()
    for pat in _KEY_EXTRACTORS[sdk_file]:
        emitted.update(re.findall(pat, src))
    unknown = sorted(emitted - valid)
    assert not unknown, (
        f"{sdk_file} spells wire keys the server does not speak "
        f"(typo?): {unknown}"
    )


def _spells(src: str, key: str) -> bool:
    """Does the source serialize/read `key`? Accepts the exact quoted
    form ("key"), a Go/Java tag or option-suffixed form ("key,omitempty),
    and a serde-derived struct field (`pub key: T` / `key:` in json!)."""
    import re

    quoted = '"' + re.escape(key) + '["\',]'       # "key" / "key,omitempty
    field = r"\b" + re.escape(key) + r"\s*:"       # serde field / json! key
    escaped = f'\\"{key}\\"'                       # Java "...\"key\"..."
    return bool(
        re.search(quoted, src) or re.search(field, src) or escaped in src
    )


@pytest.mark.parametrize("sdk_file", sorted(_SDK_SURFACES))
def test_sdk_source_spells_wire_keys(recorded, sdk_file):
    path = os.path.join(REPO, "sdk", sdk_file)
    with open(path) as f:
        src = f.read()
    surface = _SDK_SURFACES[sdk_file]
    missing = []
    for op in surface["ops"]:
        assert op in recorded, f"fixture recorder lost op {op}"
        route = _ROUTES.get(op)
        if route and route not in src:
            missing.append(f"route {route} ({op})")
        if op in surface.get("passthrough_ops", set()):
            continue
        for key in _REQUEST_KEYS.get(op, []):
            if not _spells(src, key):
                missing.append(f'request key "{key}" ({op})')
    for key in surface["extra_keys"]:
        if not _spells(src, key):
            missing.append(f'response key "{key}"')
    assert not missing, (
        f"{sdk_file} does not spell these wire strings (typo or missing "
        f"op): {missing}"
    )


# -- full-route coverage vs OpenAPI (r4 review next-8) -----------------------
#
# Every route the OpenAPI document advertises must appear (as its static
# prefix) in all three non-Python SDK sources. Routes with no SDK
# surface anywhere (debug/metrics/PS-port internals) are excluded with
# reasons.

_ROUTE_EXCLUDES = {
    "/metrics",        # Prometheus scrapers, not SDK clients
    "/debug/stacks",   # operator debugging surface
    "/ps/kill",        # PS-port internal (reference SDKs lack it too)
    "/ps/requests",    # PS-port internal
    "/cache/dbs",      # router cache introspection, internal
    "/clean_lock",     # Go covers it; a JSON-string client adds no value
    "/schedule/fail_server",  # DELETE variant covered via the list route
}


def _openapi_route_prefixes() -> list[str]:
    """Static prefixes of every documented path ('/dbs/{db}/spaces' ->
    '/dbs/', plus distinctive literal segments like '/spaces')."""
    import re

    with open(os.path.join(REPO, "api", "openapi.yaml")) as f:
        paths = re.findall(r"^  (/[^\s:]+):", f.read(), re.M)
    out = []
    for p in paths:
        static = p.split("{")[0].rstrip("/")
        if not static:
            continue
        if any(static == e or static.startswith(e + "/")
               for e in _ROUTE_EXCLUDES):
            continue
        out.append(static)
    return sorted(set(out))


@pytest.mark.parametrize("sdk_file", sorted(_KEY_EXTRACTORS))
def test_sdk_covers_every_openapi_route(sdk_file):
    with open(os.path.join(REPO, "sdk", sdk_file)) as f:
        src = f.read()
    missing = [r for r in _openapi_route_prefixes() if r not in src]
    assert not missing, (
        f"{sdk_file} lacks OpenAPI routes: {missing} — every documented "
        "route must appear in all three SDKs (r4 review next-8)"
    )


def test_error_envelope_and_auth_header_shapes(tmp_path):
    """The error envelope ({code, msg}) and BasicAuth header the three
    SDKs implement, pinned against the live server."""
    import base64
    import urllib.request

    from vearch_tpu.cluster.master import MasterServer

    m = MasterServer(auth=True, root_password="pw")
    m.start()
    try:
        # error envelope: wrong credentials -> 401 code + msg keys
        req = urllib.request.Request(
            f"http://{m.addr}/dbs", method="GET",
            headers={"Authorization": "Basic " + base64.b64encode(
                b"root:wrong").decode()})
        body = json.loads(urllib.request.urlopen(req).read())
        assert shape_of(body) == {"code": "int", "msg": "str"}
        assert body["code"] == 401
        # the exact header scheme all three SDKs build
        req = urllib.request.Request(
            f"http://{m.addr}/dbs", method="GET",
            headers={"Authorization": "Basic " + base64.b64encode(
                b"root:pw").decode()})
        ok = json.loads(urllib.request.urlopen(req).read())
        assert ok["code"] == 0 and "data" in ok
        # 404 error envelope has the same shape
        req = urllib.request.Request(
            f"http://{m.addr}/dbs/nope", method="GET",
            headers={"Authorization": "Basic " + base64.b64encode(
                b"root:pw").decode()})
        nf = json.loads(urllib.request.urlopen(req).read())
        assert shape_of(nf) == {"code": "int", "msg": "str"}
        assert nf["code"] == 404
    finally:
        m.stop()


def test_all_sdks_spell_auth_and_envelope():
    """Each SDK must build 'Authorization: Basic <b64(user:password)>'
    and read the {code, msg, data} envelope."""
    for sdk_file in _KEY_EXTRACTORS:
        with open(os.path.join(REPO, "sdk", sdk_file)) as f:
            src = f.read()
        assert "Authorization" in src and "Basic " in src, sdk_file
        for key in ("code", "msg", "data"):
            assert _spells(src, key), (sdk_file, key)


# -- framework integrations (reference: sdk/integrations/*) ------------------

def test_integration_adapters_use_real_sdk_methods():
    """langchaingo / LangChain4j adapters are source-only (no
    toolchains); pin them to the SDK surface they call so an SDK rename
    breaks HERE, not in a consumer's build."""
    import re

    go_sdk = open(os.path.join(REPO, "sdk", "go", "client.go")).read()
    go_methods = set(re.findall(r"func \(c \*Client\) (\w+)\(", go_sdk))
    adapter = open(os.path.join(
        REPO, "sdk", "integrations", "langchaingo", "vearchtpu.go")).read()
    called = set(re.findall(r"\.client\.(\w+)\(", adapter))
    assert called and called <= go_methods, (
        f"langchaingo adapter calls unknown Go SDK methods: "
        f"{sorted(called - go_methods)}"
    )
    # struct fields the adapter reads from SDK types must exist
    for name in ("SpaceConfig", "SearchVector", "SearchRequest",
                 "Document", "APIError"):
        assert f"vearch.{name}" in adapter or name in go_methods, name

    java_sdk = open(os.path.join(
        REPO, "sdk", "java", "src", "main", "java", "io", "vearchtpu",
        "VearchTpuClient.java")).read()
    java_methods = set(re.findall(
        r"public \w+(?:<[^>]+>)? (\w+)\(", java_sdk))
    j_adapter = open(os.path.join(
        REPO, "sdk", "integrations", "langchain4j", "src", "main",
        "java", "io", "vearchtpu", "langchain4j",
        "VearchTpuEmbeddingStore.java")).read()
    j_called = set(re.findall(r"client\.(\w+)\(", j_adapter))
    assert j_called and j_called <= java_methods, (
        f"langchain4j adapter calls unknown Java SDK methods: "
        f"{sorted(j_called - java_methods)}"
    )

"""Runtime truth layer: device telemetry drift gate, compile-audit
flight recorder, streaming tail quantiles, and the cluster doctor.

The perf model PREDICTS footprints and the perf gates assert compile
behavior in CI; this suite asserts the runtime itself is being
measured in a live cluster and that lying models / post-warmup
compiles surface as first-class health signals:

- drift gate: measured HBM within model tolerance on a healthy
  cluster; an injected untracked allocation flips the drift gauge and
  degrades /cluster/health;
- compile audit: a warmed cluster serves repeats with a flat compile
  counter; a forced novel-shape request produces exactly one
  /debug/compiles event carrying the originating trace id;
- quantiles: P2 sketches feed per-op latency quantile gauges on the
  PS and a merged view at /router/stats;
- doctor: exit 0 against the healthy 2-node cluster, exit 1 with the
  violation named once one is injected.
"""

import gc
import re
import time
import urllib.request

import numpy as np
import pytest

from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.sdk.client import VearchClient

D = 8


def scrape(addr: str) -> str:
    with urllib.request.urlopen(f"http://{addr}/metrics", timeout=10) as r:
        return r.read().decode()


def gauge_value(text: str, name: str, **labels) -> float | None:
    want = {k: str(v) for k, v in labels.items()}
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        m = re.match(rf"{name}(?:{{(.*)}})? ([-0-9.e+]+)$", line)
        if not m:
            continue
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1) or ""))
        if got == want:
            return float(m.group(2))
    return None


def _poll(cond, timeout_s: float, interval_s: float = 0.2):
    deadline = time.monotonic() + timeout_s
    while True:
        if cond():
            return True
        if time.monotonic() >= deadline:
            return cond()
        time.sleep(interval_s)


def _seed_space(cluster, partitions: int = 2, docs: int = 60):
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": partitions, "replica_num": 1,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })
    rng = np.random.default_rng(7)
    vecs = rng.standard_normal((docs, D)).astype(np.float32)
    cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                          for i in range(docs)])
    return cl, vecs


def _warm(cluster, cl, vecs, n: int = 3):
    """Prime the serving shapes, then declare 'warmed now' the way an
    operator does: reset every PS flight recorder so what follows is
    measured against a clean post-warmup baseline."""
    for _ in range(n):
        cl.search("db", "s", [{"field": "v", "feature": vecs[0]}],
                  limit=3)
    for ps in cluster.ps_nodes:
        rpc.call(ps.addr, "POST", "/debug/compiles/reset")


@pytest.fixture()
def cluster(tmp_path):
    # tight drift knobs: zero tolerance, 8 MiB slack, and a background
    # interval long enough that only explicit sample_now() calls run —
    # the tests control exactly when the runtime is measured
    c = StandaloneCluster(data_dir=str(tmp_path / "obs"), n_ps=2,
                          ps_kwargs={
                              "device_sample_interval": 60.0,
                              "hbm_drift_tolerance": 0.0,
                              "hbm_drift_slack_mb": 8,
                          })
    c.start()
    yield c
    c.stop()


# -- drift gate --------------------------------------------------------------


def test_hbm_drift_gate_flips_gauge_and_health(cluster):
    cl, vecs = _seed_space(cluster)
    cl.search("db", "s", [{"field": "v", "feature": vecs[1]}], limit=3)

    # healthy: measured HBM within model + baseline on every node, the
    # drift gauge renders 0, and the rollup carries no drift nodes.
    # Other suites in this process may still hold unreferenced device
    # garbage; collect it and poll — buffer teardown is asynchronous.
    gc.collect()
    for ps in cluster.ps_nodes:
        sampler = ps.device_sampler
        assert _poll(lambda: not sampler.sample_now()["drift"], 10.0), \
            sampler.sample_now()
        snap = ps.device_sampler.sample_now()
        assert snap["samples"] >= 1
        assert snap["devices"], "sampler saw no devices"
        assert not snap["drift"], snap
        text = scrape(ps.addr)
        assert gauge_value(text, "vearch_ps_hbm_model_drift") == 0.0
        # device gauge renders a real per-device byte count
        dev = next(iter(snap["devices"]))
        assert (gauge_value(text, "vearch_ps_device_hbm_live_bytes",
                            device=dev) or 0.0) > 0.0
    health = rpc.call(cluster.master_addr, "GET", "/cluster/health")
    assert health.get("hbm_drift_nodes") == []

    # inject an allocation the footprint model knows nothing about:
    # 32 MiB of live device buffer, held so it stays in live_arrays
    import jax.numpy as jnp

    blob = jnp.ones((8 << 20,), jnp.float32)
    blob.block_until_ready()
    try:
        ps0 = cluster.ps_nodes[0]
        snap = ps0.device_sampler.sample_now()
        assert snap["drift"], snap
        assert snap["drift_bytes"] >= (24 << 20), snap["drift_bytes"]
        text = scrape(ps0.addr)
        assert gauge_value(text, "vearch_ps_hbm_model_drift") == 1.0
        assert gauge_value(
            text, "vearch_ps_hbm_model_drift_bytes") >= (24 << 20)

        # the flag rides the heartbeat into the master and degrades the
        # health rollup — no polling of the PS by the master
        def degraded():
            h = rpc.call(cluster.master_addr, "GET", "/cluster/health")
            return (ps0.node_id in (h.get("hbm_drift_nodes") or [])
                    and h.get("status") in ("yellow", "red"))

        assert _poll(degraded, 15.0), rpc.call(
            cluster.master_addr, "GET", "/cluster/health")
    finally:
        del blob
    # dropping the allocation clears the drift — device buffer
    # deletion is asynchronous, so collect and poll for the clear
    gc.collect()
    sampler = cluster.ps_nodes[0].device_sampler
    assert _poll(lambda: not sampler.sample_now()["drift"], 10.0), \
        sampler.sample_now()


def test_h2d_and_compiled_program_gauges_render(cluster):
    _seed_space(cluster)
    text = scrape(cluster.ps_nodes[0].addr)
    # uploads happened during seeding: the transfer accumulator moved
    assert (gauge_value(text, "vearch_ps_h2d_bytes_total") or 0.0) > 0.0
    assert (gauge_value(text, "vearch_ps_compiled_programs")
            or 0.0) > 0.0


# -- compile audit -----------------------------------------------------------


def test_warmed_cluster_serves_repeats_with_flat_compile_counter(cluster):
    cl, vecs = _seed_space(cluster)
    _warm(cluster, cl, vecs)
    for i in range(5):
        cl.search("db", "s", [{"field": "v", "feature": vecs[i]}],
                  limit=3)
    for ps in cluster.ps_nodes:
        comp = rpc.call(ps.addr, "GET", "/debug/compiles")
        assert comp["total"] == 0, comp
        assert comp["events"] == []
        # the counter never minted a series either
        assert "vearch_serving_compiles_total{" not in scrape(ps.addr)


def test_novel_shape_request_records_one_event_with_trace_id(cluster):
    cl, vecs = _seed_space(cluster)
    _warm(cluster, cl, vecs)

    # limit is a static arg of the top-k program, but the scheduler
    # quantizes it to a fetch-k tier (PERF.md Tier 7) — warmup's
    # limit=3 already compiled the 16-tier, so only a limit in an
    # UNSEEN tier (17 -> 64) forces a new specialisation
    out = rpc.call(cluster.router_addr, "POST", "/document/search", {
        "db_name": "db", "space_name": "s", "limit": 17, "trace": True,
        "vectors": [{"field": "v", "feature": vecs[9].tolist()}],
    })
    assert out.get("trace_id")

    comp = rpc.call(cluster.ps_nodes[0].addr, "GET", "/debug/compiles")
    assert comp["total"] == 1, comp
    assert len(comp["events"]) == 1
    ev = comp["events"][0]
    # the event names the program, the shape cause (the padded tier k,
    # not the caller's 17), and the request
    assert ev["path"] == "distance.brute_force_search"
    assert "|64|" in ev["shapes"], ev["shapes"]
    assert ev["trace_id"] == out["trace_id"]
    assert ev["elapsed_ms"] > 0

    # ... and the counter minted exactly the one series
    text = scrape(cluster.ps_nodes[0].addr)
    assert gauge_value(text, "vearch_serving_compiles_total",
                       path="distance.brute_force_search") == 1.0

    # a REPEAT adds nothing (dedupe + jit hit) — and so does any OTHER
    # limit in the same tier: shape buckets make 20 the same program
    for lim in (17, 20):
        rpc.call(cluster.router_addr, "POST", "/document/search", {
            "db_name": "db", "space_name": "s", "limit": lim,
            "vectors": [{"field": "v", "feature": vecs[9].tolist()}],
        })
    comp = rpc.call(cluster.ps_nodes[0].addr, "GET", "/debug/compiles")
    assert comp["total"] == 1

    # the digest rides the heartbeat into the master rollup
    def counted():
        h = rpc.call(cluster.master_addr, "GET", "/cluster/health")
        return (h.get("serving_compiles") or 0) >= 1

    assert _poll(counted, 15.0)


def test_warmup_scopes_suppress_expected_compiles(cluster):
    """Engine open/build/create paths compile — those are expected and
    must land in the warmup counter, never the post-warmup ring."""
    _seed_space(cluster)
    for ps in cluster.ps_nodes:
        comp = rpc.call(ps.addr, "GET", "/debug/compiles")
        # partition create + first dump ran under warmup scopes
        assert comp["warmup_compiles"] >= 0
        for ev in comp["events"]:
            # nothing in the ring is from an engine-lifecycle path:
            # every recorded event is a serving-path program
            assert ev["path"].split(".")[0] in (
                "distance", "ivf", "fused", "mesh", "sharded",
            ), ev


# -- streaming tail quantiles ------------------------------------------------


def test_latency_quantile_gauges_and_router_merge(cluster):
    cl, vecs = _seed_space(cluster)
    for i in range(30):
        cl.search("db", "s",
                  [{"field": "v", "feature": vecs[i % len(vecs)]}],
                  limit=3, cache=False)

    ps_text = scrape(cluster.ps_nodes[0].addr)
    # full fixed label set renders from the very first scrape,
    # observed or not ...
    for op in ("search", "write"):
        for q in ("0.5", "0.95", "0.99"):
            assert gauge_value(ps_text, "vearch_ps_latency_quantile",
                               op=op, q=q) is not None, (op, q)
    # ... and the searched op carries real measurements in order
    q50 = gauge_value(ps_text, "vearch_ps_latency_quantile",
                      op="search", q="0.5")
    q99 = gauge_value(ps_text, "vearch_ps_latency_quantile",
                      op="search", q="0.99")
    assert q50 > 0.0
    assert q99 >= q50

    # per-partition sketches surface in /ps/stats
    stats = rpc.call(cluster.ps_nodes[0].addr, "GET", "/ps/stats")
    lq = stats.get("latency_quantiles") or {}
    search_keys = [k for k in lq if k.endswith("/search")]
    assert search_keys, lq
    rec = lq[search_keys[0]]
    assert rec["count"] > 0
    assert set(rec["q"]) == {"0.5", "0.95", "0.99"}

    # router-side merged view: scatter quantiles per partition + node
    rstats = rpc.call(cluster.router_addr, "GET", "/router/stats")
    rlq = rstats.get("latency_quantiles") or {}
    assert any(k.endswith("/scatter") for k in rlq), rlq
    node = rlq.get("_node/scatter")
    assert node and node["count"] > 0
    rtext = scrape(cluster.router_addr)
    assert gauge_value(rtext, "vearch_router_latency_quantile",
                       op="scatter", q="0.95") is not None


def test_queue_depth_and_inflight_gauges_render_full_label_set(cluster):
    _seed_space(cluster)
    text = scrape(cluster.ps_nodes[0].addr)
    for op in ("search", "write"):
        assert gauge_value(text, "vearch_ps_queue_depth",
                           op=op) is not None, op
        assert gauge_value(text, "vearch_ps_inflight",
                           op=op) is not None, op
    # idle cluster: nothing waiting, nothing executing
    assert gauge_value(text, "vearch_ps_queue_depth", op="search") == 0.0
    assert gauge_value(text, "vearch_ps_inflight", op="search") == 0.0


# -- cluster doctor ----------------------------------------------------------


def test_doctor_green_on_healthy_cluster_then_flags_violation(cluster):
    from vearch_tpu.obs import doctor

    cl, vecs = _seed_space(cluster)
    _warm(cluster, cl, vecs)

    report, code = doctor.run(cluster.master_addr)
    assert code == 0, doctor.format_report(report)
    names = {c["name"] for c in report["checks"]}
    assert {"hbm_drift", "post_warmup_compiles", "cardinality_ceiling",
            "cluster_health", "obs_docs"} <= names
    assert report["violations"] == []
    # evidence made it into the report: both PS visited, series counted
    assert len(report["servers"]) == 2
    for srv in report["servers"]:
        assert srv["metrics_series"] is not None
        assert "partitions" in (srv["stats"] or {})
    # the human summary names every check
    summary = doctor.format_report(report)
    assert "all checks passed" in summary

    # inject a violation: force a post-warmup serving compile — the
    # limit must land in a fetch-k tier no test in this process has
    # touched (100 -> tier 256; a same-tier limit like 11 rides the
    # warmed program and compiles nothing, which is the scheduler
    # working as designed — the 64-tier is already burned by the
    # novel-shape test sharing this jit cache)
    rpc.call(cluster.router_addr, "POST", "/document/search", {
        "db_name": "db", "space_name": "s", "limit": 100,
        "vectors": [{"field": "v", "feature": vecs[2].tolist()}],
    })
    report, code = doctor.run(cluster.master_addr)
    assert code == 1
    violated = {v["name"] for v in report["violations"]}
    assert "post_warmup_compiles" in violated, report["violations"]
    assert "post_warmup_compiles" in doctor.format_report(report)


def test_doctor_cli_verb(cluster, capsys):
    """`python -m vearch_tpu doctor` — the operator entry point."""
    from vearch_tpu.__main__ import main as tpu_main

    cl, vecs = _seed_space(cluster)
    _warm(cluster, cl, vecs)
    code = tpu_main(["doctor", "--master", cluster.master_addr])
    out = capsys.readouterr().out
    assert code == 0, out
    assert "all checks passed" in out

    # JSON mode emits the machine-readable report
    import json

    code = tpu_main(["doctor", "--master", cluster.master_addr,
                     "--json"])
    out = capsys.readouterr().out
    assert code == 0
    report = json.loads(out)
    assert report["checks"]

"""Concurrency stress: writers + searchers + deleters hammering one
engine (the parity answer to TSAN-style CI the reference lacks too —
SURVEY §5 race detection)."""

import os
import threading

import numpy as np

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType, FieldSchema, IndexParams, MetricType, TableSchema,
)

D = 16

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "vearch_tpu")

_STATIC_LOCK_GRAPH = None


def _assert_static_covers(edges):
    """ISSUE 20 truth link: every (first, then) acquisition edge the
    runtime lockcheck recorder observed must be covered by the static
    lock-order graph (`lint --lock-graph`). A runtime edge the
    analyzer cannot see is a resolution blind spot to fix — the
    static cycle-freedom proof only binds if the runtime behavior is
    inside the proved graph. Computed in-process once per session."""
    global _STATIC_LOCK_GRAPH
    from vearch_tpu.tools.lint import callgraph
    from vearch_tpu.tools.lint.core import run_paths

    if _STATIC_LOCK_GRAPH is None:
        run_paths([PKG])  # builds callgraph.LAST as a side effect
        assert callgraph.LAST is not None
        _STATIC_LOCK_GRAPH = callgraph.LAST.lock_graph_artifact()
    assert _STATIC_LOCK_GRAPH["cycles"] == []
    uncovered = sorted(
        (a, b) for (a, b) in edges
        if not callgraph.edge_covered(_STATIC_LOCK_GRAPH, a, b))
    assert not uncovered, (
        "runtime acquisition edges missing from the static lock-order "
        f"graph (analyzer blind spot): {uncovered}")


def test_concurrent_upsert_search_delete(rng):
    schema = TableSchema(
        "stress",
        fields=[FieldSchema("v", DataType.VECTOR, dimension=D,
                            index=IndexParams("IVFFLAT", MetricType.L2,
                                              {"ncentroids": 8,
                                               "training_threshold": 300}))],
        refresh_interval_ms=30,
    )
    eng = Engine(schema)
    eng.start_refresh_loop()
    vecs = rng.standard_normal((3000, D)).astype(np.float32)
    eng.upsert([{"_id": f"seed{i}", "v": vecs[i]} for i in range(400)])
    eng.wait_for_index(timeout=120)

    errors: list[Exception] = []
    stop = threading.Event()

    def writer(tid: int):
        try:
            for batch in range(8):
                base = 400 + tid * 800 + batch * 100
                eng.upsert([
                    {"_id": f"w{tid}_{base + i}", "v": vecs[(base + i) % 3000]}
                    for i in range(100)
                ])
        except Exception as e:
            errors.append(e)

    def searcher():
        try:
            while not stop.is_set():
                res = eng.search(SearchRequest(vectors={"v": vecs[:4]}, k=5))
                assert len(res) == 4
        except Exception as e:
            errors.append(e)

    def deleter():
        try:
            for i in range(50):
                eng.delete([f"seed{i}"])
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(3)]
    threads += [threading.Thread(target=searcher) for _ in range(2)]
    threads += [threading.Thread(target=deleter)]
    for t in threads:
        t.start()
    for t in threads[:3] + threads[-1:]:
        t.join(timeout=180)
    stop.set()
    for t in threads[3:5]:
        t.join(timeout=60)

    assert not errors, errors
    # final state is consistent: 400 seeds - 50 deleted + 3*800 writes
    assert eng.doc_count == 400 - 50 + 3 * 8 * 100
    # absorb everything and verify no duplicate docids in the index
    idx = eng.indexes["v"]
    idx.absorb(eng.vector_stores["v"].count)
    all_members = [m for mm in idx._members for m in mm]
    assert len(all_members) == len(set(all_members)), "duplicate absorb"
    # searches see post-stress writes
    res = eng.search(SearchRequest(vectors={"v": vecs[400:401]}, k=3))
    assert res[0].items
    eng.close()


def test_cluster_stress_under_lockcheck(tmp_path, rng):
    """The same class of stress, but against the replicated cluster
    layer with VEARCH_LOCKCHECK enabled: every ps/raft/wal/querycache
    lock becomes a named DebugLock recording the acquisition graph,
    and `_guarded_by` writes are runtime-verified. Concurrent writes,
    searches, and a mid-stress flush must leave the recorder with zero
    violations — no lock-order inversion is *possible*, not merely
    unobserved, given the edges this run produced."""
    from vearch_tpu.cluster.master import MasterServer
    from vearch_tpu.cluster.ps import PSServer
    from vearch_tpu.cluster.router import RouterServer
    from vearch_tpu.sdk.client import VearchClient
    from vearch_tpu.tools import lockcheck

    lockcheck.reset()
    lockcheck.enable()  # BEFORE construction: locks are minted at init
    master = nodes = router = None
    try:
        master = MasterServer(heartbeat_ttl=3600.0)
        master.start()
        nodes = []
        for i in range(2):
            ps = PSServer(data_dir=str(tmp_path / f"ps{i}"),
                          master_addr=master.addr,
                          heartbeat_interval=0.3,
                          flush_interval=3600.0, raft_tick=0.3)
            ps.start()
            nodes.append(ps)
        router = RouterServer(master_addr=master.addr)
        router.start()

        cl = VearchClient(router.addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 2, "replica_num": 2,
            "fields": [{"name": "v", "data_type": "vector",
                        "dimension": D,
                        "index": {"index_type": "FLAT",
                                  "metric_type": "L2", "params": {}}}],
        })
        vecs = rng.standard_normal((400, D)).astype("float32")
        cl.upsert("db", "s", [{"_id": f"seed{i}", "v": vecs[i].tolist()}
                              for i in range(100)])

        errors: list[Exception] = []
        stop = threading.Event()

        def writer(tid: int):
            try:
                for b in range(4):
                    base = 100 + tid * 100 + b * 25
                    cl.upsert("db", "s", [
                        {"_id": f"w{tid}_{base + i}",
                         "v": vecs[(base + i) % 400].tolist()}
                        for i in range(25)
                    ])
            except Exception as e:
                errors.append(e)

        def searcher():
            try:
                while not stop.is_set():
                    out = cl.search("db", "s",
                                    [{"field": "v", "feature": vecs[:2]}],
                                    limit=3)
                    assert len(out) == 2
            except Exception as e:
                errors.append(e)

        def flusher():
            try:
                for ps in nodes:
                    for pid in list(ps.engines):
                        ps.flush_partition(pid)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,),
                                    daemon=True, name=f"stress-w{t}")
                   for t in range(2)]
        threads += [threading.Thread(target=searcher, daemon=True,
                                     name=f"stress-s{i}")
                    for i in range(2)]
        threads += [threading.Thread(target=flusher, daemon=True,
                                     name="stress-flush")]
        for t in threads:
            t.start()
        for t in threads[:2] + threads[-1:]:
            t.join(timeout=180)
        stop.set()
        for t in threads[2:4]:
            t.join(timeout=60)

        assert not errors, errors
        # the detector really ran: the instrumented layer produced
        # acquisition edges (e.g. ps._lock held while minting raft locks)
        edges = lockcheck.acquisition_edges()
        assert edges, "no DebugLock edges recorded — lockcheck inert?"
        lockcheck.check()  # zero inversions / unguarded writes / misuse
        _assert_static_covers(edges)
    finally:
        if router is not None:
            router.stop()
        for ps in (nodes or []):
            try:
                ps.stop(flush=False)
            except Exception:
                pass
        if master is not None:
            master.stop()
        lockcheck.reset()


def test_concurrent_split_under_lockcheck(tmp_path, rng):
    """An online partition split racing writers and searchers with the
    lock-discipline recorder on: the split machinery's new locks
    (ps._split_lock, the mirror condvar, the master's elastic-job and
    reconfig locks) must produce zero ordering violations while the
    full copy → mirror → sync → cutover pipeline runs to completion."""
    from vearch_tpu.cluster import rpc
    from vearch_tpu.cluster.master import MasterServer
    from vearch_tpu.cluster.ps import PSServer
    from vearch_tpu.cluster.router import RouterServer
    from vearch_tpu.sdk.client import VearchClient
    from vearch_tpu.tools import lockcheck

    lockcheck.reset()
    lockcheck.enable()  # BEFORE construction: locks are minted at init
    master = nodes = router = None
    try:
        master = MasterServer(heartbeat_ttl=3600.0)
        master.start()
        nodes = []
        for i in range(2):
            ps = PSServer(data_dir=str(tmp_path / f"ps{i}"),
                          master_addr=master.addr,
                          heartbeat_interval=0.3,
                          flush_interval=3600.0, raft_tick=0.3)
            ps.start()
            nodes.append(ps)
        router = RouterServer(master_addr=master.addr)
        router.start()

        cl = VearchClient(router.addr, master_addr=master.addr)
        cl.create_database("db")
        cl.create_space("db", {
            "name": "s", "partition_num": 1, "replica_num": 1,
            "fields": [{"name": "v", "data_type": "vector",
                        "dimension": D,
                        "index": {"index_type": "FLAT",
                                  "metric_type": "L2", "params": {}}}],
        })
        vecs = rng.standard_normal((400, D)).astype("float32")
        cl.upsert("db", "s", [{"_id": f"seed{i}", "v": vecs[i].tolist()}
                              for i in range(60)])
        parent = cl.get_space("db", "s")["partitions"][0]["id"]

        errors: list[Exception] = []
        stop = threading.Event()
        acked: list[str] = []

        def writer(tid: int):
            i = 0
            try:
                while not stop.is_set():
                    ids = [f"w{tid}_{i + j}" for j in range(5)]
                    cl.upsert("db", "s", [
                        {"_id": k, "v": vecs[(60 + i + j) % 400].tolist()}
                        for j, k in enumerate(ids)
                    ])
                    acked.extend(ids)
                    i += 5
            except Exception as e:
                errors.append(e)

        def searcher():
            try:
                while not stop.is_set():
                    out = cl.search("db", "s",
                                    [{"field": "v", "feature": vecs[:2]}],
                                    limit=3)
                    assert len(out) == 2
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,),
                                    daemon=True, name=f"split-w{t}")
                   for t in range(2)]
        threads += [threading.Thread(target=searcher, daemon=True,
                                     name="split-s0")]
        for t in threads:
            t.start()
        try:
            job = cl.split_partition("db", "s", parent, timeout_s=120.0)
            done = cl.wait_elastic_job(job["job_id"], timeout_s=150.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not errors, errors
        assert done["status"] == "done"
        # the split actually exercised the new machinery end to end
        kids = [p["id"]
                for p in cl.get_space("db", "s")["partitions"]]
        assert len(kids) == 2 and parent not in kids
        docs = cl.query("db", "s", limit=len(acked) + 200, fields=[])
        assert len(docs) == 60 + len(acked)

        edges = lockcheck.acquisition_edges()
        assert edges, "no DebugLock edges recorded — lockcheck inert?"
        lockcheck.check()  # zero inversions / unguarded writes / misuse
        _assert_static_covers(edges)
        # the health rollup is heartbeat-fed, so it drains within a
        # beat of the parent's retirement
        import time as _time
        for _ in range(50):
            if rpc.call(master.addr, "GET",
                        "/cluster/health")["splits_running"] == 0:
                break
            _time.sleep(0.1)
        else:
            raise AssertionError("splits_running never drained")
    finally:
        if router is not None:
            router.stop()
        for ps in (nodes or []):
            try:
                ps.stop(flush=False)
            except Exception:
                pass
        if master is not None:
            master.stop()
        lockcheck.reset()


def test_diskann_absorb_search_under_lockcheck(tmp_path, rng):
    """The narrowed disk-tier critical section, proven: a realtime
    writer (store.add + absorb) races searcher threads while the
    prefetch worker pages slabs in the background. Under
    VEARCH_LOCKCHECK every tiering lock (absorb, hbm_cache, ram tier,
    prefetch) is a named DebugLock — the run must leave a non-empty
    acquisition graph with zero violations, i.e. the absorb lock never
    nests with the cache locks in an invertible order."""
    from vearch_tpu.engine.disk_vector import DiskRawVectorStore
    from vearch_tpu.engine.types import IndexParams
    from vearch_tpu.index.registry import create_index
    from vearch_tpu.tools import lockcheck

    lockcheck.reset()
    lockcheck.enable()  # BEFORE construction: locks are minted at init
    idx = None
    try:
        base = rng.standard_normal((6000, D)).astype(np.float32)
        store = DiskRawVectorStore(D, str(tmp_path / "dstress"))
        store.add(base[:4000])
        p = IndexParams(
            index_type="DISKANN",
            params={"ncentroids": 16, "nprobe": 4, "cache_mb": 1,
                    "ram_mb": 8},
        )
        idx = create_index(p, store)
        idx.train(base[:4000])
        idx.absorb(store.count)

        errors: list[Exception] = []
        stop = threading.Event()

        def writer():
            try:
                for lo in range(4000, 6000, 200):
                    store.add(base[lo:lo + 200])
                    idx.absorb(store.count)
            except Exception as e:
                errors.append(e)
            finally:
                stop.set()

        def searcher(tid: int):
            try:
                q = base[tid * 8:tid * 8 + 4]
                while not stop.is_set():
                    s, ids = idx.search(q, 5, None)
                    assert ids.shape == (4, 5)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, name="dstress-writer",
                                    daemon=True)]
        threads += [
            threading.Thread(target=searcher, args=(t,),
                             name=f"dstress-search{t}", daemon=True)
            for t in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        idx._prefetcher.drain()

        assert not errors, errors
        assert idx.indexed_count == 6000
        # the checker actually saw the tiering locks interact
        edges = lockcheck.acquisition_edges()
        assert edges, "lockcheck recorded no lock activity"
        lockcheck.check()  # raises listing any inversion / guarded write
        _assert_static_covers(edges)
    finally:
        if idx is not None:
            idx.close()
        lockcheck.reset()


def test_rabitq_absorb_binary_search_under_lockcheck(rng):
    """Concurrent absorb + three-stage binary search, proven: a
    realtime writer appends rows (store.add + absorb — which quantizes
    into BOTH compressed tiers, the int8 mirror and the stage-0 bit
    planes) while searcher threads run the fused binary -> int8 ->
    exact chain, whose flush() races the tail-append. Under
    VEARCH_LOCKCHECK every lock is a named DebugLock — the run must
    leave a non-empty acquisition graph with zero inversions."""
    from vearch_tpu.engine.raw_vector import RawVectorStore
    from vearch_tpu.index.registry import create_index
    from vearch_tpu.tools import lockcheck

    lockcheck.reset()
    lockcheck.enable()  # BEFORE construction: locks are minted at init
    try:
        base = rng.standard_normal((6000, D)).astype(np.float32)
        store = RawVectorStore(D)
        store.add(base[:4000])
        p = IndexParams(
            index_type="IVFRABITQ", metric_type=MetricType.L2,
            params={"ncentroids": 16, "train_iters": 4,
                    "mesh_serving": "off"},
        )
        idx = create_index(p, store)
        idx.train(base[:4000])
        idx.absorb(store.count)

        errors: list[Exception] = []
        stop = threading.Event()

        def writer():
            try:
                for lo in range(4000, 6000, 200):
                    store.add(base[lo:lo + 200])
                    idx.absorb(store.count)
            except Exception as e:
                errors.append(e)
            finally:
                stop.set()

        def searcher(tid: int):
            try:
                q = base[tid * 8:tid * 8 + 4]
                while not stop.is_set():
                    s, ids = idx.search(q, 5, None)
                    assert ids.shape == (4, 5)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, name="rq-writer",
                                    daemon=True)]
        threads += [
            threading.Thread(target=searcher, args=(t,),
                             name=f"rq-search{t}", daemon=True)
            for t in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)

        assert not errors, errors
        assert idx.indexed_count == 6000
        # both compressed tiers absorbed every row in lockstep
        assert idx._bits._n == idx._mirror._n == 6000
        edges = lockcheck.acquisition_edges()
        assert edges, "lockcheck recorded no lock activity"
        lockcheck.check()  # raises listing any inversion / guarded write
        _assert_static_covers(edges)
    finally:
        lockcheck.reset()

"""Concurrency stress: writers + searchers + deleters hammering one
engine (the parity answer to TSAN-style CI the reference lacks too —
SURVEY §5 race detection)."""

import threading

import numpy as np

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import (
    DataType, FieldSchema, IndexParams, MetricType, TableSchema,
)

D = 16


def test_concurrent_upsert_search_delete(rng):
    schema = TableSchema(
        "stress",
        fields=[FieldSchema("v", DataType.VECTOR, dimension=D,
                            index=IndexParams("IVFFLAT", MetricType.L2,
                                              {"ncentroids": 8,
                                               "training_threshold": 300}))],
        refresh_interval_ms=30,
    )
    eng = Engine(schema)
    eng.start_refresh_loop()
    vecs = rng.standard_normal((3000, D)).astype(np.float32)
    eng.upsert([{"_id": f"seed{i}", "v": vecs[i]} for i in range(400)])
    eng.wait_for_index(timeout=120)

    errors: list[Exception] = []
    stop = threading.Event()

    def writer(tid: int):
        try:
            for batch in range(8):
                base = 400 + tid * 800 + batch * 100
                eng.upsert([
                    {"_id": f"w{tid}_{base + i}", "v": vecs[(base + i) % 3000]}
                    for i in range(100)
                ])
        except Exception as e:
            errors.append(e)

    def searcher():
        try:
            while not stop.is_set():
                res = eng.search(SearchRequest(vectors={"v": vecs[:4]}, k=5))
                assert len(res) == 4
        except Exception as e:
            errors.append(e)

    def deleter():
        try:
            for i in range(50):
                eng.delete([f"seed{i}"])
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(3)]
    threads += [threading.Thread(target=searcher) for _ in range(2)]
    threads += [threading.Thread(target=deleter)]
    for t in threads:
        t.start()
    for t in threads[:3] + threads[-1:]:
        t.join(timeout=180)
    stop.set()
    for t in threads[3:5]:
        t.join(timeout=60)

    assert not errors, errors
    # final state is consistent: 400 seeds - 50 deleted + 3*800 writes
    assert eng.doc_count == 400 - 50 + 3 * 8 * 100
    # absorb everything and verify no duplicate docids in the index
    idx = eng.indexes["v"]
    idx.absorb(eng.vector_stores["v"].count)
    all_members = [m for mm in idx._members for m in mm]
    assert len(all_members) == len(set(all_members)), "duplicate absorb"
    # searches see post-stress writes
    res = eng.search(SearchRequest(vectors={"v": vecs[400:401]}, k=3))
    assert res[0].items
    eng.close()

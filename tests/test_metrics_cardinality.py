"""Metrics-cardinality safety (observability satellite): a profiled
soak must not grow the /metrics series set. Per-request observability
rides spans and the profile payload — NEVER metric labels — so label
sets stay bounded by topology (partition, peer, http route), not by
traffic (docid, trace id, query).
"""

import re

import numpy as np
import pytest

import vearch_tpu.cluster.rpc as rpc
from vearch_tpu.cluster.standalone import StandaloneCluster
from vearch_tpu.obs.doctor import SERIES_CEILING
from vearch_tpu.sdk.client import VearchClient

from tests.test_metrics_gauges import scrape

D = 8
N_QUERIES = 1000
BATCH = 10  # queries per RPC: 100 RPCs x 10 vectors = the 1k soak


def _series(text: str) -> set[str]:
    """Every `name{labels}` sample key on the page (values stripped;
    histogram bucket/sum/count lines included — a new bucket IS a new
    series)."""
    out = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][\w:]*(?:\{[^}]*\})?) ", line)
        if m:
            out.add(m.group(1))
    return out


@pytest.fixture()
def cluster(tmp_path):
    c = StandaloneCluster(data_dir=str(tmp_path / "c"), n_ps=2)
    c.start()
    yield c
    c.stop()


def test_profiled_soak_does_not_grow_series(cluster, rng):
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": 2,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })
    vecs = rng.standard_normal((100, D)).astype(np.float32)
    cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                          for i in range(100)])

    def profiled_batch(qs: np.ndarray) -> None:
        out = rpc.call(cluster.router_addr, "POST", "/document/search", {
            "db_name": "db", "space_name": "s",
            "vectors": [{"field": "v", "feature": q.tolist()} for q in qs],
            "limit": 5, "profile": True, "trace": True,
        })
        assert out["profile"]["partition_count"] == 2

    addrs = [cluster.router_addr] + [ps.addr for ps in cluster.ps_nodes]

    # warm every code path once so first-use series (http route labels,
    # histogram label sets) exist before the baseline scrape
    profiled_batch(vecs[:BATCH])
    baseline = {a: _series(scrape(a)) for a in addrs}

    # the runtime-truth layer renders its FULL fixed label set from the
    # very first scrape — sampler gauges, latency quantiles, queue/
    # inflight — so traffic and samples below can only move values
    for ps in cluster.ps_nodes:
        names = {s.split("{")[0] for s in baseline[ps.addr]}
        assert {"vearch_ps_device_hbm_live_bytes",
                "vearch_ps_h2d_bytes_total",
                "vearch_ps_compiled_programs",
                "vearch_ps_hbm_model_drift",
                "vearch_ps_hbm_model_drift_bytes",
                "vearch_ps_latency_quantile",
                "vearch_ps_queue_depth",
                "vearch_ps_inflight",
                "vearch_ps_admission_shed_total"} <= names, names
        # the search-quality truth layer is callback-rendered from the
        # same fixed topology: recall/rbo/breach by (k-tier x space
        # label), shadow pipeline by its closed event set, index health
        # by partition — all present (zero-filled) before any sampling
        assert {"vearch_ps_search_recall",
                "vearch_ps_search_rbo",
                "vearch_ps_search_recall_floor_breach",
                "vearch_ps_quality_shadow_total",
                "vearch_ps_index_health_recon_error",
                "vearch_ps_index_health_cell_imbalance",
                "vearch_ps_index_health_deleted_frac",
                "vearch_ps_index_health_unindexed_frac",
                "vearch_ps_index_health_needs_retrain"} <= names, names
        # progressive-refinement serving counters render their closed
        # path/stage label sets zero-filled from the first scrape
        assert {"vearch_ps_refine_searches_total",
                "vearch_ps_refine_stage_rows_total"} <= names, names
        for path in ("fused", "disk", "mesh"):
            assert (f'vearch_ps_refine_searches_total{{path="{path}"}}'
                    in baseline[ps.addr]), path
        for stage in ("binary", "int8", "exact"):
            assert (f'vearch_ps_refine_stage_rows_total{{stage="{stage}"}}'
                    in baseline[ps.addr]), stage
    rnames = {s.split("{")[0] for s in baseline[cluster.router_addr]}
    # tail-latency series are pre-initialized (hedge events zero-filled,
    # per-node routes zero-filled at discovery): traffic, hedges and
    # replica routing can only move values, never mint series mid-soak
    assert {"vearch_router_latency_quantile",
            "vearch_router_hedges_total",
            "vearch_router_replica_refetch_total",
            "vearch_router_replica_route_total"} <= rnames, rnames

    done = BATCH
    while done < N_QUERIES:
        qs = vecs[rng.integers(0, 100, size=BATCH)]
        profiled_batch(qs)
        done += BATCH
        # device-runtime samples mid-soak: measurement must never mint
        # a series (devices are fixed; drift flips a value, not a label)
        if done % (N_QUERIES // 4) == 0:
            for ps in cluster.ps_nodes:
                ps.device_sampler.sample_now()

    for addr in addrs:
        text = scrape(addr)
        grown = _series(text) - baseline[addr]
        # uptime/process gauges may appear lazily but per-REQUEST series
        # must not: anything new would scale with traffic
        assert not grown, f"{addr}: series grew during soak: {grown}"
        # no per-request identifiers as label values anywhere
        assert "trace_id=" not in text
        assert "span_id=" not in text
        assert 'peer="d' not in text  # docids never leak into peer
        for line in text.splitlines():
            assert not re.search(r'="d\d{1,3}"', line), line
        # and the page stays small in absolute terms
        assert len(_series(text)) <= SERIES_CEILING, addr


def test_space_churn_soak_stays_under_series_ceiling(cluster, rng):
    """Tenant-churn mirror of the search soak: 50 spaces churning
    through the cost accountant must not scale the series set — the
    top-K + `other` label policy (docs/ACCOUNTING.md) bounds the
    per-space metrics by POLICY, not by tenant count, while the exact
    per-space figures survive on the JSON surfaces."""
    from vearch_tpu.obs.accounting import (
        ACCOUNTANT, OTHER_LABEL, SPACE_LABEL_TOPK,
    )

    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": 1,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })
    vecs = rng.standard_normal((20, D)).astype(np.float32)
    cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                          for i in range(20)])
    rpc.call(cluster.router_addr, "POST", "/document/search", {
        "db_name": "db", "space_name": "s",
        "vectors": [{"field": "v", "feature": vecs[0].tolist()}],
        "limit": 3,
    })
    addrs = [ps.addr for ps in cluster.ps_nodes]
    try:
        # exhaust the label budget deliberately (the cluster's real
        # traffic already owns some of it), then baseline: everything
        # past this point MUST collapse into `other`
        for i in range(SPACE_LABEL_TOPK + 2):
            ACCOUNTANT.charge("requests", 1, space=f"churn/t{i}")
        mid = {a: _series(scrape(a)) for a in addrs}

        # the churn: 50 tenants accruing every expensive meter
        for i in range(50):
            sp = f"churn/t{i}"
            ACCOUNTANT.charge("requests", 3, space=sp)
            ACCOUNTANT.charge("device_us", 1234, space=sp)
            ACCOUNTANT.charge("h2d_bytes", 1 << 20, space=sp)
            ACCOUNTANT.charge("dispatches", 2, space=sp)
            ACCOUNTANT.charge("queue_wait_us", 55, space=sp)

        for addr in addrs:
            text = scrape(addr)
            grown = _series(text) - mid[addr]
            assert not grown, (
                f"{addr}: tenant churn minted series: {grown}")
            # every space metric is bounded by the label policy and the
            # collapsed bucket is rendering
            labels = re.findall(
                r'vearch_space_requests_total\{space="([^"]+)"\}', text)
            assert labels, "space metrics must render"
            assert len(labels) <= SPACE_LABEL_TOPK + 2, labels
            assert OTHER_LABEL in labels, labels
            assert len(_series(text)) <= SERIES_CEILING, addr

        # no collapse on the JSON surface: all 50 tenants exact
        snap = ACCOUNTANT.snapshot()
        churned = [s for s in snap["spaces"] if s.startswith("churn/")]
        assert len(churned) == 50
        assert all(snap["spaces"][s]["requests"] >= 1 for s in churned)
    finally:
        # hand the first-come label budget back to later tests
        ACCOUNTANT.reset()


def test_cached_soak_does_not_grow_series(cluster, rng):
    """Cache-tier mirror of the search soak: 1k queries served almost
    entirely from the router/PS result caches (plus coalesced groups)
    must not mint a single new series. Cache observability is
    callback-rendered from pre-initialized stats dicts, so every
    {event} label exists from the first scrape — hits/misses/coalesced
    only move values, never label sets."""
    import threading

    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": 2,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })
    vecs = rng.standard_normal((100, D)).astype(np.float32)
    cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                          for i in range(100)])

    # a small pool of hot queries: first touch misses, the rest hit
    pool = [vecs[i:i + BATCH] for i in range(0, 5 * BATCH, BATCH)]

    def search(qs: np.ndarray) -> None:
        out = rpc.call(cluster.router_addr, "POST", "/document/search", {
            "db_name": "db", "space_name": "s",
            "vectors": [{"field": "v", "feature": q.tolist()} for q in qs],
            "limit": 5,
        })
        assert out["documents"]

    addrs = [cluster.router_addr] + [ps.addr for ps in cluster.ps_nodes]

    for qs in pool:  # warm: one miss per pool entry, caches populated
        search(qs)
    baseline = {a: _series(scrape(a)) for a in addrs}

    done = len(pool)
    while done < N_QUERIES - 20:
        search(pool[done % len(pool)])
        done += 1
    # a burst of concurrent identical FRESH queries exercises the
    # coalesced path inside the soak window
    fresh = vecs[:BATCH] + 0.5
    ts = [threading.Thread(target=search, args=(fresh,)) for _ in range(20)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30.0)

    stats = cluster.router.result_cache.stats
    assert stats["hit"] >= N_QUERIES // 2, stats  # the soak DID hit
    for addr in addrs:
        text = scrape(addr)
        grown = _series(text) - baseline[addr]
        assert not grown, f"{addr}: series grew during cached soak: {grown}"
        assert len(_series(text)) <= SERIES_CEILING, addr


def test_profiled_write_soak_does_not_grow_series(cluster, rng):
    """Write-path mirror of the search soak: 1k profiled upserts plus a
    full index build after the baseline scrape must not mint a single
    new series — WAL/apply/build observability is labelled by topology
    (partition, op), never by request."""
    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": 2,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })
    vecs = rng.standard_normal((100, D)).astype(np.float32)

    def profiled_upsert(i0: int) -> None:
        out = cl.upsert("db", "s", [
            {"_id": f"w{i0 + j}", "v": vecs[(i0 + j) % 100]}
            for j in range(BATCH)
        ], profile=True)
        prof = out["profile"]
        assert prof["partition_count"] >= 1
        for p in prof["partitions"].values():
            assert "wal_append" in p["phases"]

    def build_all(op_path: str) -> None:
        for ps in cluster.ps_nodes:
            for pid in list(ps.engines):
                rpc.call(ps.addr, "POST", op_path, {"partition_id": pid})

    addrs = [cluster.router_addr] + [ps.addr for ps in cluster.ps_nodes]

    # warm every write-side code path once (upsert + build + rebuild
    # histograms, WAL histograms, progress gauges) before the baseline
    profiled_upsert(0)
    profiled_upsert(BATCH)
    build_all("/ps/index/build")
    build_all("/ps/index/rebuild")
    baseline = {a: _series(scrape(a)) for a in addrs}

    done = 2 * BATCH
    while done < N_QUERIES:
        profiled_upsert(done)
        done += BATCH
    # one more full build + rebuild mid-soak: job state transitions and
    # duration observations must reuse the warmed label sets
    build_all("/ps/index/build")
    build_all("/ps/index/rebuild")

    for addr in addrs:
        text = scrape(addr)
        grown = _series(text) - baseline[addr]
        assert not grown, f"{addr}: series grew during write soak: {grown}"
        assert "trace_id=" not in text
        for line in text.splitlines():
            assert not re.search(r'="w\d{1,4}"', line), line
        assert len(_series(text)) <= SERIES_CEILING, addr


def test_quality_shadow_soak_does_not_grow_series(cluster, rng):
    """Quality-layer mirror of the search soak: with shadow sampling
    wide open (rate 1.0) every query is queued, ground-truthed and
    scored — and none of it may mint a series. Recall/rbo/breach render
    (k-tier x space-label), the shadow counter renders its closed event
    tuple, and index health renders per partition: topology and fixed
    enums only, never per-query."""
    import time

    from vearch_tpu.obs.quality import SHADOW_EVENTS
    from vearch_tpu.ops.perf_model import RECALL_K_TIERS

    cl = VearchClient(cluster.router_addr)
    cl.create_database("db")
    cl.create_space("db", {
        "name": "s", "partition_num": 2,
        "fields": [{"name": "v", "data_type": "vector", "dimension": D,
                    "index": {"index_type": "FLAT", "metric_type": "L2",
                              "params": {}}}],
    })
    vecs = rng.standard_normal((100, D)).astype(np.float32)
    cl.upsert("db", "s", [{"_id": f"d{i}", "v": vecs[i]}
                          for i in range(100)])
    for ps in cluster.ps_nodes:
        ps._quality.configure(sample_rate=1.0, min_samples=1)

    def search(qs: np.ndarray) -> None:
        out = rpc.call(cluster.router_addr, "POST", "/document/search", {
            "db_name": "db", "space_name": "s",
            "vectors": [{"field": "v", "feature": q.tolist()} for q in qs],
            "limit": 5,
        })
        assert out["documents"]

    def executed() -> int:
        return sum(ps._quality.counters().get("executed", 0)
                   for ps in cluster.ps_nodes)

    addrs = [ps.addr for ps in cluster.ps_nodes]

    # warm: first shadow per node compiles the exact path and scores at
    # least once, so estimator-backed values exist before the baseline
    search(vecs[:BATCH])
    deadline = time.monotonic() + 30.0
    while executed() < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert executed() >= 1, "no shadow sample ever scored"
    baseline = {a: _series(scrape(a)) for a in addrs}

    for addr in addrs:
        text = scrape(addr)
        # the shadow counter's label set is the closed event enum,
        # fully zero-filled from the first scrape
        events = set(re.findall(
            r'vearch_ps_quality_shadow_total\{event="([^"]+)"\}', text))
        assert events == set(SHADOW_EVENTS), events
        # recall series = k-tiers x space labels (top-K policy + other),
        # never per-query; both hosted spaces collapse to one label here
        recalls = re.findall(
            r'vearch_ps_search_recall\{k="(\d+)",space="([^"]+)"\}', text)
        assert {k for k, _ in recalls} == {str(t) for t in RECALL_K_TIERS}
        assert len(recalls) <= len(RECALL_K_TIERS) * (len(recalls) and
                                                      len({s for _, s in
                                                           recalls}))

    done = BATCH
    while done < N_QUERIES // 2:
        search(vecs[rng.integers(0, 100, size=BATCH)])
        done += BATCH
    # let the background worker drain what it will; whatever executed,
    # shed or went stale moves VALUES only
    deadline = time.monotonic() + 30.0
    while executed() < 20 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert executed() >= 20, "soak shadows never drained"

    for addr in addrs:
        text = scrape(addr)
        grown = _series(text) - baseline[addr]
        assert not grown, f"{addr}: quality soak minted series: {grown}"
        for line in text.splitlines():  # docids never become labels
            assert not re.search(r'="d\d{1,3}"', line), line
        assert len(_series(text)) <= SERIES_CEILING, addr

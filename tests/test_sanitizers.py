"""ASAN/UBSAN build + run of the native modules (SURVEY row 54: the
reference runs its C++ engine under sanitizer CI; here the csrc modules
are rebuilt with -fsanitize=address,undefined and driven through build/
search/save/load plus a concurrent-search phase in a subprocess — any
heap error, OOB, UB, or use-after-free aborts the run and fails the
test)."""

import os
import subprocess
import sys
import sysconfig

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCENARIO = r"""
import importlib.util, os, sys, tempfile, threading
import numpy as np

def load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

hnsw = load(sys.argv[1], "vearch_hnsw")
native = load(sys.argv[2], "vearch_native")

# -- vearch_native: hashing, top-k merge, fvecs reader --------------------
keys = [f"doc{i}" for i in range(5000)]
h = np.frombuffer(native.murmur3_batch(keys), dtype=np.uint32)
assert h.shape[0] == 5000

rng = np.random.default_rng(0)
scores = rng.standard_normal((8, 64)).astype(np.float32)
ids = np.arange(8 * 64, dtype=np.int64).reshape(8, 64)
s, i = native.merge_topk(scores.tobytes(), ids.tobytes(), 8, 64, 10, True)
assert np.frombuffer(s, dtype=np.float32).shape[0] == 80

with tempfile.NamedTemporaryFile(suffix=".fvecs", delete=False) as f:
    arr = rng.standard_normal((100, 16)).astype(np.float32)
    for row in arr:
        f.write(np.int32(16).tobytes()); f.write(row.tobytes())
    path = f.name
raw, rn, rd = native.read_fvecs(path, -1)
assert (rn, rd) == (100, 16)
os.unlink(path)

# -- vearch_hnsw: build / filtered search / save / load / concurrency ----
dim, n = 24, 3000
data = rng.standard_normal((n, dim)).astype(np.float32)
g = hnsw.hnsw_new(dim, 12, 80, 0, 1234)
first = hnsw.hnsw_add(g, data.tobytes(), n)
assert hnsw.hnsw_count(g) == n

q = data[:16]
valid = np.ones(n, dtype=np.uint8); valid[::3] = 0
sc, gi = hnsw.hnsw_search(g, q.tobytes(), 16, 10, 64, valid.tobytes())
gi = np.frombuffer(gi, dtype=np.int64).reshape(16, 10)
assert (gi[gi >= 0] % 3 != 0).all()  # filtered ids never surface

with tempfile.TemporaryDirectory() as d:
    p = os.path.join(d, "g.hnsw")
    hnsw.hnsw_save(g, p)
    g2 = hnsw.hnsw_load(dim, 12, 80, 0, p)
    assert hnsw.hnsw_count(g2) == n
    # concurrent searches on the loaded graph (C++ releases the GIL in
    # search: ASAN would flag any unsynchronized memory error)
    errs = []
    def worker(seed):
        try:
            r = np.random.default_rng(seed)
            for _ in range(20):
                qq = data[r.integers(0, n, 8)]
                hnsw.hnsw_search(g2, qq.tobytes(), 8, 5, 48, None)
        except Exception as e:
            errs.append(e)
    ts = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    hnsw.hnsw_free(g2)
hnsw.hnsw_free(g)
print("SANITIZED RUN OK")
"""


@pytest.mark.slow
def test_native_modules_under_asan_ubsan(tmp_path):
    try:
        asan_rt = subprocess.run(
            ["gcc", "-print-file-name=libasan.so"],
            capture_output=True, text=True,
        ).stdout.strip()
    except FileNotFoundError:
        pytest.skip("no native toolchain")
    if not os.path.isabs(asan_rt):
        pytest.skip("libasan runtime not available")
    include = sysconfig.get_paths()["include"]
    sos = {}
    for name in ("vearch_hnsw", "vearch_native"):
        so = str(tmp_path / f"{name}.asan.so")
        r = subprocess.run(
            ["g++", "-O1", "-g", "-shared", "-fPIC", "-std=c++17",
             "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
             f"-I{include}", os.path.join(REPO, "csrc", f"{name}.cpp"),
             "-o", so],
            capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        sos[name] = so

    scenario = tmp_path / "scenario.py"
    scenario.write_text(SCENARIO)
    out = subprocess.run(
        [sys.executable, str(scenario), sos["vearch_hnsw"],
         sos["vearch_native"]],
        capture_output=True, text=True, timeout=300,
        env={
            **os.environ,
            # python itself leaks by design; halt on real errors only
            "LD_PRELOAD": asan_rt,
            "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
            "UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1",
        },
    )
    assert out.returncode == 0, (
        f"sanitized run failed\nstdout:{out.stdout[-1500:]}\n"
        f"stderr:{out.stderr[-3000:]}"
    )
    assert "SANITIZED RUN OK" in out.stdout

"""Router gRPC front door (reference: internal/router/server.go:92 —
gRPC served next to HTTP). Drives the four document RPCs over a real
grpc channel against a live cluster and checks parity with the HTTP
path, including error-status mapping."""

import json

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

from vearch_tpu.cluster.master import MasterServer
from vearch_tpu.cluster.ps import PSServer
from vearch_tpu.cluster.router import RouterServer
from vearch_tpu.cluster.grpc_server import load_pb2
from vearch_tpu.sdk.client import VearchClient

D = 8


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    root = tmp_path_factory.mktemp("grpc")
    master = MasterServer()
    master.start()
    ps = PSServer(data_dir=str(root / "ps"), master_addr=master.addr)
    ps.start()
    router = RouterServer(master_addr=master.addr, grpc_port=0)
    router.start()
    cl = VearchClient(router.addr)
    cl.create_database("g")
    cl.create_space("g", {
        "name": "sp", "partition_num": 2, "replica_num": 1,
        "fields": [
            {"name": "color", "data_type": "string"},
            {"name": "emb", "data_type": "vector", "dimension": D,
             "index": {"index_type": "FLAT", "metric_type": "L2",
                       "params": {}}},
        ],
    })
    channel = grpc.insecure_channel(router.grpc.addr)
    yield router, cl, channel
    channel.close()
    router.stop()
    ps.stop()
    master.stop()


def _stub(channel, pb2, method, req_cls, resp_cls):
    return channel.unary_unary(
        f"/vearch_tpu.Router/{method}",
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )


def test_grpc_upsert_search_query_delete(stack):
    router, cl, channel = stack
    pb2 = load_pb2()
    rng = np.random.default_rng(3)
    vecs = rng.standard_normal((50, D)).astype(np.float32)

    upsert = _stub(channel, pb2, "Upsert", pb2.UpsertRequest,
                   pb2.UpsertResponse)
    out = upsert(pb2.UpsertRequest(
        db_name="g", space_name="sp",
        documents=[
            pb2.Document(id=f"d{i}", fields_json=json.dumps({
                "color": ["red", "blue"][i % 2],
                "emb": vecs[i].tolist(),
            })) for i in range(50)
        ],
    ))
    assert out.total == 50
    assert sorted(out.document_ids) == sorted(f"d{i}" for i in range(50))

    search = _stub(channel, pb2, "Search", pb2.SearchRequest,
                   pb2.SearchResponse)
    resp = search(pb2.SearchRequest(
        db_name="g", space_name="sp",
        vectors=[pb2.VectorQuery(field="emb",
                                 feature=vecs[7].ravel().tolist())],
        limit=3, fields=["color"],
    ))
    assert len(resp.results) == 1
    items = resp.results[0].items
    assert items[0].id == "d7"
    assert json.loads(items[0].fields_json)["color"] == "blue"
    # scores ascend for L2
    assert items[0].score <= items[1].score <= items[2].score

    # batched query vectors: 2 flattened queries in one feature array
    resp2 = search(pb2.SearchRequest(
        db_name="g", space_name="sp",
        vectors=[pb2.VectorQuery(
            field="emb",
            feature=np.concatenate([vecs[3], vecs[4]]).tolist())],
        limit=1,
    ))
    assert [r.items[0].id for r in resp2.results] == ["d3", "d4"]

    # filtered search parity with the HTTP path
    filt = {"operator": "AND", "conditions": [
        {"operator": "=", "field": "color", "value": "red"}]}
    resp3 = search(pb2.SearchRequest(
        db_name="g", space_name="sp",
        vectors=[pb2.VectorQuery(field="emb",
                                 feature=vecs[7].ravel().tolist())],
        limit=5, filters_json=json.dumps(filt),
    ))
    got_http = cl.search("g", "sp", [{"field": "emb",
                                      "feature": vecs[7].tolist()}],
                         limit=5, filters=filt)
    assert [it.id for it in resp3.results[0].items] == \
        [d["_id"] for d in got_http[0]]

    query = _stub(channel, pb2, "Query", pb2.QueryRequest,
                  pb2.QueryResponse)
    qr = query(pb2.QueryRequest(db_name="g", space_name="sp",
                                document_ids=["d3", "d9"]))
    got = {d.id: json.loads(d.fields_json) for d in qr.documents}
    assert set(got) == {"d3", "d9"}
    assert got["d9"]["color"] == "blue"

    delete = _stub(channel, pb2, "Delete", pb2.DeleteRequest,
                   pb2.DeleteResponse)
    dr = delete(pb2.DeleteRequest(db_name="g", space_name="sp",
                                  document_ids=["d3"]))
    assert dr.total == 1
    qr2 = query(pb2.QueryRequest(db_name="g", space_name="sp",
                                 document_ids=["d3"]))
    assert len(qr2.documents) == 0


def test_grpc_error_status_mapping(stack):
    router, cl, channel = stack
    pb2 = load_pb2()
    search = _stub(channel, pb2, "Search", pb2.SearchRequest,
                   pb2.SearchResponse)
    with pytest.raises(grpc.RpcError) as e:
        search(pb2.SearchRequest(db_name="g", space_name="nope",
                                 vectors=[pb2.VectorQuery(
                                     field="emb", feature=[0.0] * D)]))
    assert e.value.code() == grpc.StatusCode.NOT_FOUND
    with pytest.raises(grpc.RpcError) as e:
        search(pb2.SearchRequest(db_name="g", space_name="sp"))  # no vectors
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # bad feature length
    with pytest.raises(grpc.RpcError) as e:
        search(pb2.SearchRequest(
            db_name="g", space_name="sp",
            vectors=[pb2.VectorQuery(field="emb", feature=[0.0] * (D + 1))]))
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    # non-dict JSON payloads map to INVALID_ARGUMENT, not UNKNOWN
    upsert = _stub(channel, pb2, "Upsert", pb2.UpsertRequest,
                   pb2.UpsertResponse)
    with pytest.raises(grpc.RpcError) as e:
        upsert(pb2.UpsertRequest(db_name="g", space_name="sp", documents=[
            pb2.Document(id="x", fields_json=json.dumps([1, 2]))]))
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    with pytest.raises(grpc.RpcError) as e:
        search(pb2.SearchRequest(
            db_name="g", space_name="sp",
            vectors=[pb2.VectorQuery(field="emb", feature=[0.0] * D)],
            filters_json='"oops"'))
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
